"""Shared threshold-crypto sidecar — one process owns the box's crypto.

SURVEY §5's deployment note: with several replica daemons (and edge
gateways) co-located on one accelerator host, per-process dispatchers
each pay their own device launches, XLA compilations, and transfer
overhead.  This sidecar is the Thetacrypt-shaped answer: ONE co-located
service multiplexes every tenant's crypto — verify, sign, and raw
modexp batches from all processes coalesce in its dispatchers into
shared launches (shard_map fan-out over every local device;
``native/montmodexp.c`` as the GIL-free host-fallback tier), and only
one process compiles/holds the kernels.

The service is **untrusted by construction** (2G2T's verifiable-
outsourcing framing): tenants self-check returned signatures with the
public exponent (cheap at e=65537) on EVERY item — a forged signature
can never leave a tenant — and spot-check verify/modexp verdicts
locally at a sampled rate, falling back to local crypto — with the
breaker open and a ``sidecar_dishonest`` fleet anomaly raised — on
any mismatch.  A lying service is therefore evicted within an
expected ``1/spot_rate`` batches; the sampled window is the tunable
trade, and ``BFTKV_SIDECAR_SPOT_RATE=1`` closes it (DESIGN.md §17.3).

Wire protocol (length-prefixed, one request per frame):

- **v1 (legacy verify)**: ``u32 count``, then per item ``chunk(msg)
  chunk(sig) chunk(n) u32 e``; response: count bytes of 0/1.  Kept
  bit-compatible for old clients.
- **v2 (op-tagged)**: ``u32 0xFFFFFFFF`` (impossible as a v1 count),
  ``u8 op``, payload.  Response: ``u8 status`` + payload.  Ops:
  VERIFY (v1 body), SIGN (``u32 count``, per item ``u32 handle``
  ``chunk(msg)``), REGISTER (``u32 count``, per key ``chunk(n) u32 e
  chunk(d) chunk(p) chunk(q)``), MODEXP (``u32 count``, per item
  ``chunk(base) chunk(exp) chunk(mod)``), STATS (empty → JSON stats
  frame).  Statuses: OK / SHED (admission declined — tenant falls
  back local WITHOUT opening its breaker) / ERR (internal failure —
  tenant falls back local and opens its breaker) / BAD_HANDLE (sign
  handle unknown, e.g. after a sidecar restart — tenant re-registers
  and retries once) / REFUSED (key registration declined for the
  connection's lifetime: a channel that must not carry keys, or the
  per-connection key budget spent — the client keeps signing locally
  and never asks again).

Sign keys are registered **per connection** as integer handles and are
accepted ONLY over the mode-0600 Unix socket or an HMAC-authenticated
channel — private material never crosses a squatter-able plain TCP
port (the client enforces the same policy and simply never remotes
signing there).

Backpressure: VERIFY/SIGN/MODEXP pass a bounded admission queue
(``bftkv_tpu.admission.AdmissionQueue``, the gateway's semantics) —
bounded inflight + bounded wait, instant shed past it with the
``sidecar.shed`` metric.  A shed tenant batch runs on the tenant's own
host crypto; the service degrades, it never queues unboundedly.

Failure semantics for v1 frames (deliberate, load-bearing):

- *Malformed frame* (attacker-controlled bytes): all-fail response of
  the claimed count — the client's accounting stays aligned and hostile
  input can never manufacture a "valid" verdict.
- *Internal error* (dispatcher/device failure): **zero-length
  response** — a count mismatch on the client side, which makes
  ``RemoteVerifierDomain`` fall back to local verification.  A broken
  accelerator must degrade to local verify, not masquerade as
  "all signatures invalid" (a cluster-wide liveness outage).

Trust boundary: results are checked by the tenants, but *liveness* and
key secrecy still require transport integrity, so the recommended
deployment is a **Unix domain socket** (``--listen unix:/path/sock``,
created mode 0600) — a TCP port can be squatted by any local user
after a sidecar crash.  For TCP, configure a shared secret
(``--secret-file``): every request and response carries an HMAC-SHA256
tag and the client fails closed (local crypto) on tag mismatch.

Run: ``python -m bftkv_tpu.cmd.verify_sidecar --listen
unix:/run/bftkv/crypto.sock --stats 127.0.0.1:7960``.  Daemons opt in
with ``bftkv --sidecar unix:/run/bftkv/crypto.sock`` (verify-only
legacy spelling: ``--verify-sidecar``); ``run_cluster --sidecar auto``
boots one beside the whole fleet and the FleetCollector scrapes the
``--stats`` endpoint as a ``role=sidecar`` member.
"""

from __future__ import annotations

import argparse
import hashlib
import hmac
import io
import json
import os
import socket
import socketserver
import struct
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from bftkv_tpu.admission import AdmissionQueue
from bftkv_tpu.metrics import registry as metrics
from bftkv_tpu.packet import read_chunk, write_chunk
from bftkv_tpu import flags

__all__ = [
    "serve",
    "main",
    "encode_request",
    "decode_request",
    "encode_op",
    "encode_sign_request",
    "decode_sign_request",
    "encode_register_request",
    "decode_register_request",
    "encode_modexp_request",
    "decode_modexp_request",
    "request_tag",
    "response_tag",
    "SidecarService",
    "TAG_LEN",
    "MAGIC",
    "OP_VERIFY",
    "OP_SIGN",
    "OP_REGISTER",
    "OP_MODEXP",
    "OP_STATS",
    "ST_OK",
    "ST_SHED",
    "ST_ERR",
    "ST_BAD_HANDLE",
    "ST_REFUSED",
]

TAG_LEN = 32  # HMAC-SHA256

#: v2 frame marker: impossible as a v1 item count (> any max_frame).
MAGIC = b"\xff\xff\xff\xff"

OP_VERIFY = 1
OP_SIGN = 2
OP_REGISTER = 3
OP_MODEXP = 4
OP_STATS = 5

ST_OK = 0
ST_SHED = 1
ST_ERR = 2
ST_BAD_HANDLE = 3
ST_REFUSED = 4

_OP_NAMES = {OP_VERIFY: "verify", OP_SIGN: "sign", OP_MODEXP: "modexp"}


def request_tag(secret: bytes, body: bytes) -> bytes:
    return hmac.new(secret, b"bftkv-sidecar-req" + body, hashlib.sha256).digest()


def response_tag(secret: bytes, req_body: bytes, out: bytes) -> bytes:
    """Tag binds the verdicts to the exact request they answer, so a
    recorded response for one batch cannot be replayed for another."""
    h = hashlib.sha256(req_body).digest()
    return hmac.new(secret, b"bftkv-sidecar-res" + h + out, hashlib.sha256).digest()


# -- codecs (shared by client and server) -----------------------------------


def _int_bytes(v: int) -> bytes:
    return v.to_bytes((v.bit_length() + 7) // 8 or 1, "big")


def encode_request(items: list) -> bytes:
    """[(message, sig_bytes, PublicKey)] → one VERIFY body (v1 shape)."""
    buf = io.BytesIO()
    buf.write(struct.pack(">I", len(items)))
    for message, sig, key in items:
        write_chunk(buf, message)
        write_chunk(buf, sig)
        write_chunk(buf, _int_bytes(key.n))
        buf.write(struct.pack(">I", key.e))
    return buf.getvalue()


def decode_request(body: bytes) -> list:
    from bftkv_tpu.crypto.rsa import PublicKey

    r = io.BytesIO(body)
    (count,) = struct.unpack(">I", r.read(4))
    if count > len(body):  # each item needs headers at minimum
        raise ValueError("bad count")
    items = []
    for _ in range(count):
        msg = read_chunk(r) or b""
        sig = read_chunk(r) or b""
        n = int.from_bytes(read_chunk(r) or b"", "big")
        (e,) = struct.unpack(">I", r.read(4))
        items.append((msg, sig, PublicKey(n=n, e=e)))
    return items


def encode_op(op: int, payload: bytes = b"") -> bytes:
    """One v2 body: magic + op byte + payload."""
    return MAGIC + bytes([op]) + payload


def encode_sign_request(items: list) -> bytes:
    """[(handle, message)] → SIGN payload."""
    buf = io.BytesIO()
    buf.write(struct.pack(">I", len(items)))
    for handle, message in items:
        buf.write(struct.pack(">I", handle))
        write_chunk(buf, message)
    return buf.getvalue()


def decode_sign_request(payload: bytes) -> list:
    r = io.BytesIO(payload)
    (count,) = struct.unpack(">I", r.read(4))
    if count > len(payload):
        raise ValueError("bad count")
    items = []
    for _ in range(count):
        (handle,) = struct.unpack(">I", r.read(4))
        items.append((handle, read_chunk(r) or b""))
    return items


def encode_register_request(keys: list) -> bytes:
    """[PrivateKey] → REGISTER payload (n, e, d, p, q per key)."""
    buf = io.BytesIO()
    buf.write(struct.pack(">I", len(keys)))
    for k in keys:
        write_chunk(buf, _int_bytes(k.n))
        buf.write(struct.pack(">I", k.e))
        write_chunk(buf, _int_bytes(k.d))
        write_chunk(buf, _int_bytes(k.p))
        write_chunk(buf, _int_bytes(k.q))
    return buf.getvalue()


def decode_register_request(payload: bytes) -> list:
    from bftkv_tpu.crypto.rsa import PrivateKey

    r = io.BytesIO(payload)
    (count,) = struct.unpack(">I", r.read(4))
    if count > len(payload):
        raise ValueError("bad count")
    keys = []
    for _ in range(count):
        n = int.from_bytes(read_chunk(r) or b"", "big")
        (e,) = struct.unpack(">I", r.read(4))
        d = int.from_bytes(read_chunk(r) or b"", "big")
        p = int.from_bytes(read_chunk(r) or b"", "big")
        q = int.from_bytes(read_chunk(r) or b"", "big")
        if not (1 < p < n and 1 < q < n and p * q == n and d > 0):
            raise ValueError("inconsistent private key")
        keys.append(PrivateKey(n=n, e=e, d=d, p=p, q=q))
    return keys


def wrap_keys(secret: bytes, payload: bytes) -> bytes:
    """AEAD-seal a REGISTER payload under the shared secret.

    The HMAC frame tags authenticate but do not HIDE: a squatter on a
    freed TCP port would otherwise read n/e/d/p/q out of the very first
    frame a reconnecting client sends — before any response proves the
    peer knows the secret.  Sealing makes captured key material
    worthless without the secret (the unix socket needs none of this:
    the kernel enforces mode 0600)."""
    from bftkv_tpu.crypto.aead import AESGCM
    from bftkv_tpu.crypto.rng import generate_random

    key = hashlib.sha256(b"bftkv-sidecar-keywrap" + secret).digest()
    nonce = generate_random(12)
    return nonce + AESGCM(key).encrypt(
        nonce, payload, b"bftkv-sidecar-register"
    )


def unwrap_keys(secret: bytes, wrapped: bytes) -> bytes:
    """Inverse of :func:`wrap_keys`; raises on tamper/garbage."""
    from bftkv_tpu.crypto.aead import AESGCM

    if len(wrapped) < 12:
        raise ValueError("short keywrap")
    key = hashlib.sha256(b"bftkv-sidecar-keywrap" + secret).digest()
    return AESGCM(key).decrypt(
        wrapped[:12], wrapped[12:], b"bftkv-sidecar-register"
    )


def encode_modexp_request(items: list) -> bytes:
    """[(base, exp, mod)] → MODEXP payload."""
    buf = io.BytesIO()
    buf.write(struct.pack(">I", len(items)))
    for b, e, m in items:
        write_chunk(buf, _int_bytes(b))
        write_chunk(buf, _int_bytes(e))
        write_chunk(buf, _int_bytes(m))
    return buf.getvalue()


def decode_modexp_request(payload: bytes) -> list:
    r = io.BytesIO(payload)
    (count,) = struct.unpack(">I", r.read(4))
    if count > len(payload):
        raise ValueError("bad count")
    items = []
    for _ in range(count):
        b = int.from_bytes(read_chunk(r) or b"", "big")
        e = int.from_bytes(read_chunk(r) or b"", "big")
        m = int.from_bytes(read_chunk(r) or b"", "big")
        if m <= 0:
            raise ValueError("bad modulus")
        items.append((b, e, m))
    return items


def _chunks(payload: bytes, count: int) -> list:
    """``count`` length-prefixed chunks (sign/modexp response bodies)."""
    r = io.BytesIO(payload)
    out = []
    for _ in range(count):
        out.append(read_chunk(r) or b"")
    if r.read(1):
        raise ValueError("trailing bytes")
    return out


# -- the service ------------------------------------------------------------


class SidecarService:
    """Dispatchers + admission + stats for one sidecar process.

    Cross-tenant coalescing happens HERE: every connection handler
    thread submits into these shared dispatchers, so batches from
    different replica/gateway processes ride the same launches.  The
    measured host/device crossover steers each flush's tier *inside*
    the launch (``dispatch.calibration()``: CPU backends pin
    always-host — the Montgomery native kernel — so the r05 CPU-XLA
    flush disaster cannot recur here either), while the dispatcher
    queue itself is never bypassed: occupancy must stay observable and
    tenants must keep coalescing even on a host-only box."""

    def __init__(
        self,
        *,
        max_batch: int = 4096,
        max_wait: float | None = None,
        admission: AdmissionQueue | None = None,
    ):
        from bftkv_tpu.ops import dispatch

        cal = dispatch.calibration()
        # Host tier (CPU-calibrated box): there is no launch overhead
        # to amortize, so a collection window only adds latency —
        # cross-tenant coalescing still happens through concurrency (a
        # flush in service queues every arrival behind it).  On an
        # accelerator the usual windows amortize the launch RTT.
        host_tier = cal["prefer_host"]
        if max_wait is None and host_tier:
            max_wait = 0.0005
        kw = {} if max_wait is None else {"max_wait": max_wait}
        self.verify = dispatch.VerifyDispatcher(
            max_batch=max_batch, calibrate=False, **kw
        ).start()
        sign_wait = 0.0005 if host_tier else None
        # Host-tier flush bounds: a host sign is ~2 ms/item with no
        # launch to amortize, so a flush merging several tenants'
        # batches makes EACH wait for ALL (fair-share latency, minus
        # nothing).  Bounding the flush keeps FIFO-at-request latency;
        # on an accelerator the big merges ARE the win and the bounds
        # stay wide.
        sign_flush = 16 if host_tier else max_batch
        if host_tier:
            self.verify.max_batch = min(self.verify.max_batch, 256)
        self.sign = dispatch.SignDispatcher(
            max_batch=sign_flush, calibrate=False, max_wait=sign_wait
        ).start()
        self.modexp = dispatch.ModexpDispatcher(
            max_batch=sign_flush,
            calibrate=False,
            **kw,
        ).start()
        self._cal: dict = {}
        self.apply_calibration(cal)
        self.admission = admission or AdmissionQueue(
            max_inflight=flags.get_int("BFTKV_SIDECAR_MAX_INFLIGHT"),
            max_queue=flags.get_int("BFTKV_SIDECAR_MAX_QUEUE"),
            max_wait=flags.get_float("BFTKV_SIDECAR_MAX_WAIT"),
            metric="sidecar.shed",
        )
        self.max_keys = flags.get_int("BFTKV_SIDECAR_MAX_KEYS")
        self._t0 = time.monotonic()
        # Online recalibration (ISSUE 19): the boot verdict above used
        # to be forever — nothing ever called calibration(force=True)
        # again, so an accelerator attached (or un-wedged) mid-run
        # could not flip ALWAYS_HOST without a restart.  The loop
        # re-measures every BFTKV_DISPATCH_RECAL_S seconds, and
        # immediately after the FIRST accelerator-backed launch
        # completes (observed_launch_rtt turns non-None).
        self._recal_stop = threading.Event()
        self._recal_seen_rtt = False
        self._recal_thread: threading.Thread | None = None
        period = flags.get_float("BFTKV_DISPATCH_RECAL_S")
        if period and period > 0:
            self._recal_thread = threading.Thread(
                target=self._recal_loop, args=(period,), daemon=True
            )
            self._recal_thread.start()

    def apply_calibration(self, cal: dict) -> None:
        """(Re-)point the dispatchers' host/device thresholds at a
        calibration verdict — boot and every recalibration.  The tier
        decision lives inside each launch, so no dispatcher restart
        (and no caller disruption) is needed when the verdict moves.
        Note the sidecar intentionally does NOT adopt ``prefer_host``
        inline bypass: tenants must keep coalescing through the queue
        even on a host-only box (occupancy stays observable)."""
        from bftkv_tpu.ops import dispatch

        if flags.raw("BFTKV_HOST_VERIFY_THRESHOLD") is None:
            self.verify.verifier.host_threshold = cal["verify_crossover"]
        if flags.raw("BFTKV_HOST_SIGN_THRESHOLD") is None:
            if cal["sign_crossover"] is not None:
                self.sign.signer.host_threshold = cal["sign_crossover"]
            elif self.sign._signer_default_threshold is not None:
                self.sign.signer.host_threshold = (
                    self.sign._signer_default_threshold
                )
        self.modexp.device_threshold = (
            dispatch.ALWAYS_HOST
            if cal["prefer_host"]
            else max(16, cal["verify_crossover"])
        )
        self._cal = cal

    def recalibrate(self) -> dict:
        """Force a fresh measurement and re-apply it (the
        ``/recalibrate`` devtools hook and the periodic loop)."""
        from bftkv_tpu.ops import dispatch

        cal = dispatch.calibration(force=True)
        self.apply_calibration(cal)
        metrics.incr("sidecar.recalibrations")
        return cal

    def _recal_loop(self, period: float) -> None:
        from bftkv_tpu.ops import dispatch

        next_at = time.monotonic() + period
        # Wake at min(period, 2 s): the periodic re-measure honors the
        # full period, but the first-successful-launch trigger should
        # not wait out a 60 s window to engage a device that just
        # proved itself.
        while not self._recal_stop.wait(timeout=min(period, 2.0)):
            rtt = dispatch.observed_launch_rtt()
            first_launch = rtt is not None and not self._recal_seen_rtt
            if first_launch:
                self._recal_seen_rtt = True
            if first_launch or time.monotonic() >= next_at:
                try:
                    self.recalibrate()
                except Exception:
                    metrics.incr("sidecar.recalibration_errors")
                next_at = time.monotonic() + period

    def stop(self) -> None:
        self._recal_stop.set()
        if self._recal_thread is not None:
            self._recal_thread.join(timeout=5)
            self._recal_thread = None
        self.verify.stop()
        self.sign.stop()
        self.modexp.stop()

    def stats(self) -> dict:
        """The ``/metrics``-style stats frame (OP_STATS and the stats
        HTTP ``/info``): queue depth, per-dispatcher batch occupancy,
        shed, and per-op throughput counters."""
        snap = metrics.snapshot()
        inflight, waiting = self.admission.depth()

        def disp(name: str) -> dict:
            flushes = snap.get(f"{name}.flushes", 0)
            items = snap.get(f"{name}.items", 0)
            return {
                "flushes": flushes,
                "items": items,
                "occupancy_per_launch": round(items / flushes, 2)
                if flushes
                else None,
                "batch_p50": snap.get(f"{name}.batch.p50", 0),
                "throughput_items_per_s": round(
                    snap.get(f"{name}.throughput", 0), 1
                ),
            }

        from bftkv_tpu.ops import devbuf, dispatch

        rtt = dispatch.observed_launch_rtt()
        return {
            "uptime_s": round(time.monotonic() - self._t0, 1),
            "queue": {
                "inflight": inflight,
                "waiting": waiting,
                "max_inflight": self.admission.max_inflight,
                "shed": self.admission.shed,
            },
            "ops": {
                name: snap.get("sidecar.items{op=%s}" % name, 0)
                for name in _OP_NAMES.values()
            },
            "batch": {
                "verify": disp("dispatch"),
                "sign": disp("signdispatch"),
                "modexp": disp("modexpdispatch"),
            },
            "device_plane": {
                "calibration": {
                    k: self._cal.get(k)
                    for k in (
                        "backend",
                        "verify_crossover",
                        "prefer_host",
                        "source",
                    )
                },
                "launch_rtt_s": None if rtt is None else round(rtt, 6),
                "recalibrations": snap.get("sidecar.recalibrations", 0),
                "buffer_rings": devbuf.stats(),
            },
        }


class _Handler(socketserver.BaseRequestHandler):
    def handle(self) -> None:
        sock = self.request
        secret = self.server.secret
        # Per-CONNECTION sign-key handles: a reconnect starts empty, so
        # a client that reconnects after a sidecar restart re-registers
        # (and a crashed client's keys die with its connection).
        conn_keys: dict = {}
        next_handle = [1]
        try:
            while True:
                hdr = _recvall(sock, 4)
                if hdr is None:
                    return
                (ln,) = struct.unpack(">I", hdr)
                if ln > self.server.max_frame:
                    return  # oversized frame: drop the connection
                body = _recvall(sock, ln)
                if body is None:
                    return
                if secret is not None:
                    # Unauthenticated peer: drop the connection. No
                    # all-fail reply — an attacker must not be able to
                    # steer verdicts at all without the secret.
                    if len(body) < TAG_LEN or not hmac.compare_digest(
                        body[-TAG_LEN:], request_tag(secret, body[:-TAG_LEN])
                    ):
                        return
                    body = body[:-TAG_LEN]
                if body[:4] == MAGIC and len(body) >= 5:
                    status, payload = self._handle_v2(
                        body[4], body[5:], conn_keys, next_handle
                    )
                    out = bytes([status]) + payload
                else:
                    out = self._handle_v1(body)
                tag = b"" if secret is None or not out else response_tag(
                    secret, body, out
                )
                sock.sendall(struct.pack(">I", len(out) + len(tag)) + out + tag)
        except (ConnectionError, OSError):
            return

    def _handle_v1(self, body: bytes) -> bytes:
        """Legacy verify frames, bit-compatible with old clients."""
        claimed = struct.unpack(">I", body[:4])[0] if len(body) >= 4 else 0
        try:
            items = decode_request(body)
        except Exception:
            # Malformed frame: all-fail response of the claimed count
            # keeps the client's accounting aligned (a hostile count is
            # already bounded by the frame).
            return bytes(min(claimed, len(body)))
        try:
            ok = self.server.dispatcher.verify(items)
            return bytes(bool(b) for b in ok)
        except Exception:
            # Internal failure (dead/hung accelerator, bug): zero-
            # length reply = count mismatch = client falls back to
            # LOCAL verification.  Never fabricate "all invalid" for
            # well-formed input.
            return b""

    def _handle_v2(
        self, op: int, payload: bytes, conn_keys: dict, next_handle: list
    ) -> tuple[int, bytes]:
        svc: SidecarService = self.server.service
        if op == OP_STATS:
            try:
                return ST_OK, json.dumps(svc.stats()).encode()
            except Exception:
                return ST_ERR, b""
        if op == OP_REGISTER:
            if not self.server.keys_ok:
                # Key material must only cross the 0600 unix socket or
                # the HMAC channel; plain TCP is refusable by policy
                # (the client never sends keys there either).
                return ST_REFUSED, b""
            try:
                if self.server.secret is not None:
                    # Key material on the HMAC channel arrives sealed
                    # (wrap_keys): the frame tag authenticates, the
                    # AEAD hides — see the client's register path.
                    payload = unwrap_keys(self.server.secret, payload)
                keys = decode_register_request(payload)
            except Exception:
                return ST_ERR, b""
            if len(conn_keys) + len(keys) > svc.max_keys:
                # Per-connection key budget spent (handles are add-only
                # while the connection lives): REFUSED, not ERR — the
                # client's refused-path is terminal for the connection
                # (signing stays local, verify keeps remoting), whereas
                # ERR would trip the shared breaker and re-trip it on
                # every register retry — a permanent flap that benches
                # verify too and spams sidecar_down anomalies.
                return ST_REFUSED, b""
            handles = []
            for k in keys:
                h = next_handle[0]
                next_handle[0] += 1
                conn_keys[h] = k
                handles.append(h)
            return ST_OK, struct.pack(">I", len(handles)) + b"".join(
                struct.pack(">I", h) for h in handles
            )
        opname = _OP_NAMES.get(op)
        if opname is None:
            return ST_ERR, b""
        if not svc.admission.acquire(opname):
            return ST_SHED, b""
        try:
            metrics.incr("sidecar.ops", labels={"op": opname})
            if op == OP_VERIFY:
                try:
                    items = decode_request(payload)
                except Exception:
                    return ST_ERR, b""
                metrics.incr(
                    "sidecar.items", len(items), labels={"op": opname}
                )
                ok = self.server.dispatcher.verify(items)
                return ST_OK, bytes(bool(b) for b in ok)
            if op == OP_SIGN:
                try:
                    pairs = decode_sign_request(payload)
                except Exception:
                    return ST_ERR, b""
                if any(h not in conn_keys for h, _m in pairs):
                    # Unknown handle: the canonical cause is a client
                    # that outlived a sidecar restart — it re-registers
                    # on its (new) connection and retries.
                    return ST_BAD_HANDLE, b""
                metrics.incr(
                    "sidecar.items", len(pairs), labels={"op": opname}
                )
                sigs = svc.sign.submit(
                    [(m, conn_keys[h]) for h, m in pairs]
                )
                buf = io.BytesIO()
                for sig in sigs:
                    write_chunk(buf, sig)
                return ST_OK, buf.getvalue()
            # OP_MODEXP
            try:
                items = decode_modexp_request(payload)
            except Exception:
                return ST_ERR, b""
            metrics.incr(
                "sidecar.items", len(items), labels={"op": opname}
            )
            vals = svc.modexp.submit(items)
            buf = io.BytesIO()
            for v in vals:
                write_chunk(buf, _int_bytes(v))
            return ST_OK, buf.getvalue()
        except Exception:
            # Internal failure: the status byte IS the signal — the
            # tenant falls back to local crypto and opens its breaker.
            return ST_ERR, b""
        finally:
            svc.admission.release()


def _recvall(sock, n: int) -> bytes | None:
    buf = b""
    while len(buf) < n:
        part = sock.recv(n - len(buf))
        if not part:
            return None
        buf += part
    return buf


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class _UnixServer(socketserver.ThreadingUnixStreamServer):
    daemon_threads = True


# -- stats endpoint (FleetCollector scrape surface) -------------------------


class _StatsHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *a):
        pass

    def _reply(self, code: int, body: bytes, ctype="application/json"):
        self.send_response(code)
        self.send_header("content-type", ctype)
        self.send_header("content-length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        import urllib.parse

        path = self.path
        try:
            if path == "/info":
                doc = {
                    "name": self.server.sidecar_name,
                    "role": "sidecar",
                    "sidecar": self.server.service.stats(),
                }
                self._reply(200, json.dumps(doc, sort_keys=True).encode())
            elif path == "/metrics" or path.startswith("/metrics?"):
                q = urllib.parse.parse_qs(
                    urllib.parse.urlparse(path).query
                )
                accept = self.headers.get("accept") or ""
                want_prom = q.get("format", [""])[0] == "prometheus" or (
                    "application/json" not in accept
                    and ("text/plain" in accept or "openmetrics" in accept)
                )
                if want_prom:
                    self._reply(
                        200,
                        metrics.prometheus().encode(),
                        "text/plain; version=0.0.4; charset=utf-8",
                    )
                else:
                    self._reply(
                        200,
                        json.dumps(
                            metrics.snapshot(), sort_keys=True
                        ).encode(),
                    )
            elif path == "/trace" or path.startswith("/trace?"):
                from bftkv_tpu import trace as trmod

                q = urllib.parse.parse_qs(
                    urllib.parse.urlparse(path).query
                )
                try:
                    since = int(q.get("since", ["0"])[0])
                except ValueError:
                    since = 0
                doc = trmod.tracer.export(max(0, since))
                doc["slow"] = trmod.tracer.slow()
                self._reply(
                    200,
                    json.dumps(doc, sort_keys=True, default=str).encode(),
                )
            elif path == "/recalibrate":
                # Devtools hook (ISSUE 19 satellite): force a fresh
                # host/device calibration and re-apply it live.  GET for
                # curl convenience; the stats port is loopback/operator
                # surface, and the action is idempotent re-measurement.
                cal = self.server.service.recalibrate()
                self._reply(
                    200, json.dumps(cal, sort_keys=True, default=str).encode()
                )
            else:
                self._reply(404, b'"unknown endpoint"')
        except Exception as e:  # operator surface: never kill the sidecar
            self._reply(500, json.dumps(str(e)).encode())

    def do_POST(self):
        # Drain any body so keep-alive framing survives the reply.
        ln = int(self.headers.get("content-length") or 0)
        if ln:
            self.rfile.read(min(ln, 1 << 16))
        if self.path == "/recalibrate":
            return self.do_GET()
        self._reply(404, b'"unknown endpoint"')


def serve(
    listen: str,
    *,
    max_batch: int = 4096,
    max_wait: float | None = None,
    max_frame: int = 1 << 26,
    secret: bytes | None = None,
    stats: str = "",
    name: str = "sidecar01",
    admission: AdmissionQueue | None = None,
):
    """Start the sidecar; returns (server, thread) for embedding.

    ``listen`` is ``host:port`` or ``unix:/path/to.sock`` (socket file
    created mode 0600 — only this uid's processes can reach the
    service).  ``stats`` optionally serves /info + /metrics + /trace
    on an HTTP port for the fleet collector (``role=sidecar``).
    """
    if listen.startswith("unix:"):
        path = listen[len("unix:"):]
        try:
            os.unlink(path)
        except OSError:
            pass
        # umask, not post-bind chmod: the socket must never be
        # world-connectable, even for the bind→chmod window (a peer
        # that connects in that window keeps its connection).
        old_umask = os.umask(0o177)
        try:
            srv = _UnixServer(path, _Handler)
        finally:
            os.umask(old_umask)
        os.chmod(path, 0o600)
    else:
        host, _, port = listen.rpartition(":")
        srv = _Server((host or "127.0.0.1", int(port)), _Handler)
    srv.service = SidecarService(
        max_batch=max_batch, max_wait=max_wait, admission=admission
    )
    #: Back-compat alias: v1 handling and existing embedders address
    #: the verify dispatcher as ``srv.dispatcher``.
    srv.dispatcher = srv.service.verify
    srv.max_frame = max_frame
    srv.secret = secret
    # Sign keys may only arrive over a channel a local squatter cannot
    # impersonate: the 0600 unix socket, or HMAC-authenticated frames.
    srv.keys_ok = listen.startswith("unix:") or secret is not None
    srv.stats_httpd = None
    if stats:
        host, _, port = stats.rpartition(":")
        httpd = ThreadingHTTPServer(
            (host or "127.0.0.1", int(port)), _StatsHandler
        )
        httpd.daemon_threads = True
        httpd.service = srv.service
        httpd.sidecar_name = name
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        srv.stats_httpd = httpd
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    return srv, t


def load_secret(path: str) -> bytes:
    with open(path, "rb") as f:
        return f.read().strip()


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description="shared crypto sidecar")
    ap.add_argument("--listen", default="127.0.0.1:7900",
                    help="host:port, or unix:/path/to.sock (recommended: "
                         "a TCP port can be squatted after a crash, and "
                         "sign-key registration needs unix or --secret-"
                         "file)")
    ap.add_argument("--max-batch", type=int, default=4096)
    ap.add_argument("--secret-file", default="",
                    help="file holding a shared secret; frames are then "
                         "HMAC-authenticated both ways (use for TCP)")
    ap.add_argument("--stats", default="",
                    help="host:port for the /info + /metrics + /trace "
                         "stats endpoint the fleet collector scrapes "
                         "(role=sidecar member)")
    ap.add_argument("--name", default="sidecar01",
                    help="member name reported on the stats /info")
    args = ap.parse_args(argv)
    secret = load_secret(args.secret_file) if args.secret_file else None
    srv, t = serve(
        args.listen,
        max_batch=args.max_batch,
        secret=secret,
        stats=args.stats,
        name=args.name,
    )
    print(
        f"crypto-sidecar: listening on {args.listen}"
        + (f", stats @ {args.stats}" if args.stats else ""),
        flush=True,
    )
    try:
        t.join()
    except KeyboardInterrupt:
        srv.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
