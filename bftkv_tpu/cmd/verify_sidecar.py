"""Shared signature-verification sidecar — one process owns the chip.

SURVEY §5's deployment note: with several replica daemons co-located on
one accelerator host, per-process dispatchers each pay their own device
launches, XLA compilations, and transfer overhead.  *Verification* uses
only public data (message, signature, public key), so — unlike signing,
which must stay inside each replica's trust domain — all co-located
daemons can safely forward their verify batches to one sidecar: batches
from different replicas coalesce in the sidecar's dispatcher into
shared launches, and only one process compiles/holds the kernels.

Wire protocol (length-prefixed, one request per frame, localhost/unix
trust assumed — co-located processes on one machine are one failure
domain already):

    request:  u32 count, then per item chunk(msg) chunk(sig) chunk(n) u32 e
    response: count bytes of 0/1

Run: ``python -m bftkv_tpu.cmd.verify_sidecar --listen 127.0.0.1:7900``
Daemons opt in with ``bftkv --verify-sidecar 127.0.0.1:7900``.
"""

from __future__ import annotations

import argparse
import io
import socket
import socketserver
import struct
import sys
import threading

from bftkv_tpu.packet import read_chunk, write_chunk

__all__ = ["serve", "main", "encode_request", "decode_request"]


def encode_request(items: list) -> bytes:
    """[(message, sig_bytes, PublicKey)] → one request frame body."""
    buf = io.BytesIO()
    buf.write(struct.pack(">I", len(items)))
    for message, sig, key in items:
        write_chunk(buf, message)
        write_chunk(buf, sig)
        n = key.n
        write_chunk(buf, n.to_bytes((n.bit_length() + 7) // 8 or 1, "big"))
        buf.write(struct.pack(">I", key.e))
    return buf.getvalue()


def decode_request(body: bytes) -> list:
    from bftkv_tpu.crypto.rsa import PublicKey

    r = io.BytesIO(body)
    (count,) = struct.unpack(">I", r.read(4))
    if count > len(body):  # each item needs headers at minimum
        raise ValueError("bad count")
    items = []
    for _ in range(count):
        msg = read_chunk(r) or b""
        sig = read_chunk(r) or b""
        n = int.from_bytes(read_chunk(r) or b"", "big")
        (e,) = struct.unpack(">I", r.read(4))
        items.append((msg, sig, PublicKey(n=n, e=e)))
    return items


class _Handler(socketserver.BaseRequestHandler):
    def handle(self) -> None:
        sock = self.request
        try:
            while True:
                hdr = _recvall(sock, 4)
                if hdr is None:
                    return
                (ln,) = struct.unpack(">I", hdr)
                if ln > self.server.max_frame:
                    return  # oversized frame: drop the connection
                body = _recvall(sock, ln)
                if body is None:
                    return
                claimed = (
                    struct.unpack(">I", body[:4])[0] if len(body) >= 4 else 0
                )
                try:
                    items = decode_request(body)
                    ok = self.server.dispatcher.verify(items)
                    out = bytes(bool(b) for b in ok)
                except Exception:
                    # Malformed frame: all-fail response of the claimed
                    # count keeps the client's accounting aligned (a
                    # hostile count is already bounded by the frame).
                    out = bytes(min(claimed, len(body)))
                sock.sendall(struct.pack(">I", len(out)) + out)
        except (ConnectionError, OSError):
            return


def _recvall(sock, n: int) -> bytes | None:
    buf = b""
    while len(buf) < n:
        part = sock.recv(n - len(buf))
        if not part:
            return None
        buf += part
    return buf


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


def serve(
    listen: str,
    *,
    max_batch: int = 4096,
    max_wait: float | None = None,
    max_frame: int = 1 << 26,
):
    """Start the sidecar; returns (server, thread) for embedding."""
    from bftkv_tpu.ops import dispatch

    host, _, port = listen.rpartition(":")
    srv = _Server((host or "127.0.0.1", int(port)), _Handler)
    kw = {} if max_wait is None else {"max_wait": max_wait}
    srv.dispatcher = dispatch.VerifyDispatcher(max_batch=max_batch, **kw).start()
    srv.max_frame = max_frame
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    return srv, t


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description="shared verify sidecar")
    ap.add_argument("--listen", default="127.0.0.1:7900")
    ap.add_argument("--max-batch", type=int, default=4096)
    args = ap.parse_args(argv)
    srv, t = serve(args.listen, max_batch=args.max_batch)
    print(f"verify-sidecar: listening on {args.listen}", flush=True)
    try:
        t.join()
    except KeyboardInterrupt:
        srv.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
