"""The gateway process: certified front door over the existing transport.

A :class:`Gateway` is wired exactly like a :class:`~bftkv_tpu.protocol.
server.Server` — ``(self_node, qs, tr, crypt)`` from ``topology.
make_node`` — but holds no storage: its only state is a soundness-
checked cache.  It registers on the transport as a listener for the two
front-door commands (``GW_READ`` / ``GW_WRITE``, same encrypted
session envelope + nonce echo as every other command) and drives the
quorums through an internal protocol :class:`~bftkv_tpu.protocol.
client.Client` — which means every upstream RPC inherits the hedged
staged fan-out, adaptive per-peer deadlines, and health-aware staging
order of DESIGN.md §13 for free.

Soundness (the certified-fill rule, DESIGN.md §14.2): every record the
gateway caches or serves has had its completed collective signature
verified against the OWNER quorum *by this gateway* — fills from the
client resolve path are re-verified at the cache boundary, a fill that
fails verification increments ``gateway.cache.verify_fail`` and is
never served, and TPA-protected records (proof-gated reads) are never
cached at all.  The gateway therefore cannot be tricked into serving a
fabrication, and a *compromised* gateway still cannot forge one — the
:class:`~bftkv_tpu.gateway.client.GatewayClient` re-verifies the same
signature before trusting the bytes.
"""

from __future__ import annotations

import logging
import threading
import time

from bftkv_tpu import flags
from bftkv_tpu import packet as pkt
from bftkv_tpu import quorum as qm
from bftkv_tpu import regions as rg
from bftkv_tpu import trace
from bftkv_tpu import transport as tp
from bftkv_tpu.errors import (
    ERR_GATEWAY_OVERLOADED,
    ERR_PERMISSION_DENIED,
    ERR_UNCERTIFIED_RECORD,
    ERR_UNKNOWN_COMMAND,
)
# AdmissionQueue lives in bftkv_tpu/admission.py so the crypto sidecar
# shares the exact shed semantics (DESIGN.md §17.4); re-exported here
# for existing importers.
from bftkv_tpu.admission import AdmissionQueue
from bftkv_tpu.gateway.cache import CertifiedCache
from bftkv_tpu.gateway.coalesce import WriteCoalescer
from bftkv_tpu.metrics import registry as metrics
from bftkv_tpu.protocol.client import Client
from bftkv_tpu.protocol.server import HIDDEN_PREFIX
from bftkv_tpu.devtools.lockwatch import named_lock

__all__ = ["AdmissionQueue", "Gateway"]

log = logging.getLogger("bftkv_tpu.gateway")


class Gateway:
    #: How long a fill follower waits for the leader before taking the
    #: fill over itself (single-flight on hot-key miss storms).
    FILL_WAIT = 10.0

    def __init__(
        self,
        self_node,
        qs,
        tr,
        crypt,
        *,
        cache_max: int = 65536,
        cache_ttl: float = 30.0,
        max_inflight: int = 64,
        max_queue: int = 128,
        linger: float | None = None,
    ):
        self.self_node = self_node
        self.qs = qs
        self.tr = tr
        self.crypt = crypt
        self.address = ""  # set by start()
        self.client = Client(self_node, qs, tr, crypt)
        # Write-through: every record the client certifies (collapsed
        # write tails, batched writes) re-verifies at the cache
        # boundary and replaces the stale entry — invalidation rides
        # the same plane that delivers the certified bytes.
        self.client.on_certified = self._on_certified
        self.cache = CertifiedCache(cache_max, cache_ttl)
        self.coalescer = WriteCoalescer(self.client, linger=linger)
        self.admission = AdmissionQueue(max_inflight, max_queue)
        self._fill_lock = named_lock("gateway.fill")
        self._fills: dict[bytes, threading.Event] = {}
        # Per-INSTANCE observability counters for /info: the process
        # metrics registry is shared tier-wide in one process, so
        # reporting its totals per gateway would double-count
        # (increments ride the same lock-free-ish sites the metrics
        # do; they are stats, and a lost race costs one count).
        self._hits = 0
        self._misses = 0
        self._verify_fails = 0
        #: Shards the fleet snapshot reports over budget — reads for
        #: them prefer a stale-but-certified cache entry over a fill
        #: that would pile onto a struggling quorum.
        self._degraded_shards: set = set()
        # Region-local read tier (DESIGN.md §21): a freshness lease
        # bounds how stale a same-region certified-cache read can be.
        # While the last sync-invalidation round completed recently
        # (every shard group answered its digest poll within
        # BFTKV_REGION_LEASE_S), TTL-expired entries may still be
        # served: every survivor was confirmed unchanged (or dropped)
        # at that poll, so staleness is bounded by TTL + lease + one
        # poll RTT instead of forcing a cross-region quorum fill.
        self._lease_s = flags.get_float("BFTKV_REGION_LEASE_S") or 0.0
        self._lease_until = 0.0
        self._lease_served = 0
        # Anti-entropy invalidation state: per-peer last-seen digest +
        # a STICKY peer cursor per shard group (a digest only means
        # something diffed against the SAME peer's previous one, so the
        # poll target moves only when the current one stops answering).
        self._digests: dict[int, dict[int, bytes]] = {}
        self._sync_cursor: dict[object, int] = {}
        self._sync_stop = threading.Event()
        self._sync_thread: threading.Thread | None = None

    # -- lifecycle ---------------------------------------------------------

    def start(self, addr: str) -> None:
        """Register the front-door listener at ``addr`` (the listen
        side of the configured dial address — gateway certificates
        carry none; see ``topology.Universe.gateways``)."""
        addr = addr.split("://", 1)[-1]
        self.address = addr
        self.tr.start(self, addr)
        log.info("gateway @ %s running", addr)

    def stop(self) -> None:
        self._sync_stop.set()
        self.coalescer.stop()
        self.tr.stop()

    # -- dispatch (the Server.handler shape) -------------------------------

    _handlers = {tp.GW_READ: "_gw_read", tp.GW_WRITE: "_gw_write"}

    def handler(self, cmd: int, data: bytes) -> bytes | None:
        plain, sender, nonce = self.crypt.message.decrypt(data)
        tctx, plain = pkt.unwrap_trace(plain)
        name = self._handlers.get(cmd)
        if name is None:
            raise ERR_UNKNOWN_COMMAND
        cmd_name = tp.COMMAND_NAMES.get(cmd, cmd)
        metrics.incr(f"gateway.{cmd_name}.count")
        run = getattr(self, name)
        if tctx is not None:
            with trace.attach(trace.SpanContext(*tctx)), trace.span(
                f"gateway.{cmd_name}",
                attrs={"node": getattr(self.self_node, "name", "")},
            ):
                res = run(plain, sender)
        else:
            res = run(plain, sender)
        return self.crypt.message.encrypt([sender], res or b"", nonce)

    # -- certified-fill rule ----------------------------------------------

    def _verify_certified(self, variable: bytes, raw: bytes):
        """The soundness gate every record crosses before the cache or
        a client sees it: parse, bind to the requested variable, and
        verify the COMPLETED collective signature against the owner
        quorum.  Returns the parsed packet; raises
        ``ERR_UNCERTIFIED_RECORD`` (and counts
        ``gateway.cache.verify_fail``) on any shortfall."""
        try:
            p = pkt.parse(raw)
            if (
                (p.variable or b"") != variable
                or p.sig is None
                or p.ss is None
                or not p.ss.completed
            ):
                raise ERR_UNCERTIFIED_RECORD
            qa = qm.choose_quorum_for(self.qs, variable, qm.AUTH)
            with trace.span("gateway.verify_fill"):
                try:
                    self.crypt.collective.verify(
                        pkt.tbss(raw), p.ss, qa, self.crypt.keyring
                    )
                except Exception:
                    # Dual-epoch migration window (DESIGN.md §15): a
                    # record the OLD owner clique certified while it
                    # owned the bucket is still a sound fill — retry
                    # against the dual quorum the route table names.
                    # Outside a window alt_quorums_for is empty and the
                    # failure stands (the Byzantine-fill signal).
                    alts = getattr(
                        self.qs, "alt_quorums_for", lambda *_a: []
                    )(variable, qm.AUTH)
                    if not alts:
                        raise
                    err = None
                    for alt in alts:
                        try:
                            self.crypt.collective.verify(
                                pkt.tbss(raw), p.ss, alt,
                                self.crypt.keyring,
                            )
                            err = None
                            break
                        except Exception as e:
                            err = e
                    if err is not None:
                        raise err
        except Exception:
            self._verify_fails += 1
            metrics.incr("gateway.cache.verify_fail")
            raise ERR_UNCERTIFIED_RECORD from None
        return p

    def _on_certified(self, variable: bytes, record: bytes) -> None:
        """Write-through fill from the client's certified-record plane
        (collapsed-write tails, batched writes).  Re-verified at the
        boundary — the certified-fill rule has no side doors."""
        try:
            p = self._verify_certified(variable, record)
        except Exception:
            return  # counted by _verify_certified; never cached
        if p.auth is not None:
            return  # proof-gated record: never cached
        if self.cache.put(variable, p.t, record):
            metrics.incr("gateway.cache.backfill_puts")

    # -- read path ---------------------------------------------------------

    def _shard_of(self, variable: bytes):
        shard_of = getattr(self.qs, "shard_of", None)
        if shard_of is None:
            return None
        try:
            return shard_of(variable)
        except Exception:
            return None

    def _gw_read(self, req: bytes, sender) -> bytes:
        p = pkt.parse(req)
        variable, proof = p.variable or b"", p.ss
        if variable.startswith(HIDDEN_PREFIX):
            raise ERR_PERMISSION_DENIED
        ent = self.cache.get(variable)
        if ent is not None:
            self._hits += 1
            metrics.incr("gateway.cache.hits")
            return ent.record
        if self._lease_s > 0.0 and time.monotonic() < self._lease_until:
            # Live freshness lease: a TTL-expired entry that survived
            # the last complete digest poll is still within the §21
            # staleness bound — serve it at cache latency instead of
            # paying a (possibly cross-region) quorum fill.
            leased = self.cache.get(variable, allow_stale=True)
            if leased is not None:
                self._lease_served += 1
                metrics.incr("gateway.cache.lease_served")
                return leased.record
        self._misses += 1
        metrics.incr("gateway.cache.misses")
        # Single-flight: concurrent misses on one hot key ride the
        # leader's fill instead of stampeding the quorum.
        while True:
            with self._fill_lock:
                ev = self._fills.get(variable)
                if ev is None:
                    self._fills[variable] = ev = threading.Event()
                    break
            ev.wait(self.FILL_WAIT)
            ent = self.cache.get(variable)
            if ent is not None:
                # Counted as the miss it was; the leader's fill served
                # it without a quorum round of this request's own.
                metrics.incr("gateway.fill.coalesced")
                return ent.record
            # Leader failed or the record was uncacheable: take over.
        try:
            return self._fill(variable, proof)
        finally:
            with self._fill_lock:
                self._fills.pop(variable, None)
            ev.set()

    def _fill(self, variable: bytes, proof) -> bytes:
        if not self.admission.acquire("read"):
            raise ERR_GATEWAY_OVERLOADED
        try:
            with trace.span("gateway.fill"):
                value, t, record = self.client.read_certified(
                    variable, proof
                )
        except Exception:
            # Degraded owner shard: a certified-but-expired entry beats
            # an error when the fleet snapshot says the quorum is over
            # its fault budget (stale serving is flagged, never silent).
            sh = self._shard_of(variable)
            if sh is not None and sh in self._degraded_shards:
                stale = self.cache.get(variable, allow_stale=True)
                if stale is not None:
                    metrics.incr("gateway.cache.stale_served")
                    return stale.record
            raise
        finally:
            self.admission.release()
        if record is None:
            if value:
                # The read resolved a value but its certified bytes
                # could not be collected (races only — read_certified
                # re-collects them itself): failing honestly beats
                # serving "no data" for a variable that has one.
                metrics.incr("gateway.fill.record_missing")
                raise ERR_UNCERTIFIED_RECORD
            return b""  # empty read: nothing stored (never cached)
        parsed = self._verify_certified(variable, record)
        if parsed.auth is None:
            self.cache.put(variable, t, record)
            metrics.incr("gateway.cache.fills")
        return record

    # -- write path --------------------------------------------------------

    def _gw_write(self, req: bytes, sender) -> bytes | None:
        p = pkt.parse(req)
        variable, value = p.variable or b"", p.value or b""
        if variable.startswith(HIDDEN_PREFIX):
            raise ERR_PERMISSION_DENIED
        if not self.admission.acquire("write"):
            raise ERR_GATEWAY_OVERLOADED
        # Drop the stale entry BEFORE the round: the on_certified
        # write-through delivers the new record mid-flush, and the
        # cache's newer-t-wins rule lets a racing re-fill of the old
        # version lose to it — invalidating after the commit would
        # instead discard the freshly delivered record.
        self.cache.invalidate(variable)
        try:
            err = self.coalescer.submit_wait(variable, value)
        finally:
            self.admission.release()
        if err is not None:
            raise err
        metrics.incr("gateway.write.ok")
        return None

    # -- operator API helpers (cmd/run_gateway.py) -------------------------

    def read_value(self, variable: bytes, proof=None) -> bytes | None:
        """The gateway's own serving path, value-shaped — what the
        run_gateway HTTP API's ``/read/`` uses.  Same cache → admission
        → certified fill pipeline as a GW_READ."""
        raw = self._gw_read(
            pkt.serialize(variable, None, 0, None, proof), None
        )
        return pkt.parse(raw).value if raw else None

    def write_value(self, variable: bytes, value: bytes) -> None:
        self._gw_write(
            pkt.serialize(variable, value, 0, None, None), None
        )

    # -- fleet-snapshot routing (DESIGN.md §14.4) --------------------------

    def apply_fleet_snapshot(self, health: dict) -> None:
        """Feed a ``/fleet`` health document in: down members drop to
        the back of the upstream staging order (the client's own
        health-aware ranking), and shards whose f-budget is EXHAUSTED
        are marked degraded — their read misses prefer the
        stale-cache fallback over a fill that would stack more load on
        a quorum already past its masking bound."""
        self.client.apply_fleet_snapshot(health)
        degraded: set = set()
        for sh, sd in (health.get("shards") or {}).items():
            fb = sd.get("f_budget") or {}
            remaining = fb.get("remaining")
            if remaining is not None and remaining < 0:
                try:
                    degraded.add(int(sh))
                except (TypeError, ValueError):
                    degraded.add(sh)
        self._degraded_shards = degraded

    # -- anti-entropy invalidation (DESIGN.md §14.3) -----------------------

    def _sync_groups(self) -> dict[object, list]:
        """Addressed non-gateway peers grouped by shard (a digest only
        describes the serving replica's own slice, so every shard needs
        its own poll target)."""
        my_uid = getattr(self.self_node, "uid", None)
        peers = [
            n
            for n in self.self_node.get_peers()
            if getattr(n, "address", "") and getattr(n, "active", True)
            # Peer gateways share this tier's uid and answer
            # ERR_UNKNOWN_COMMAND to SYNC_DIGEST — skip them.
            and getattr(n, "uid", None) != my_uid
        ]
        idx_of = getattr(self.qs, "shard_index_of", None)
        seat_info = getattr(self.qs, "seat_info", None)
        groups: dict[object, list] = {}
        for n in peers:
            key = idx_of(n.id) if idx_of is not None else None
            groups.setdefault(key, []).append(n)
        # Storage-plane peers first: a collapsed write's certified
        # record lands there via the back-fill within the round, while
        # clique members keep commit-PENDING residue (invisible to
        # their digests) until the repair plane sweeps — polling a
        # clique member would lag the invalidation by a repair cycle.
        if seat_info is not None:
            def plane(n):
                # Addressed non-clique peers ARE the storage plane
                # (role is None for them on unsharded graphs).
                try:
                    return 1 if seat_info(n.id)["role"] == "clique" else 0
                except Exception:
                    return 0

            for key in groups:
                groups[key].sort(key=plane)
        return groups

    def sync_invalidate_round(self) -> int:
        """One cheap invalidation poll: SYNC_DIGEST from ONE sticky
        peer per shard group (a digest only diffs meaningfully against
        the same peer's previous one; the cursor advances only when
        that peer stops answering), dropping every cached entry whose
        bucket hash changed.  Returns entries dropped.  The TTL remains
        the backstop; this shortens the staleness window to ~one poll
        interval for write traffic the gateway never carried itself."""
        dropped = 0
        groups = self._sync_groups()
        polled_ok = bool(groups)
        for key, peers in sorted(
            groups.items(), key=lambda kv: str(kv[0])
        ):
            cursor = self._sync_cursor.setdefault(key, 0)
            peer = peers[cursor % len(peers)]
            box: dict = {}

            def cb(res: tp.MulticastResponse) -> bool:
                box["res"] = res
                return True

            self.tr.multicast(tp.SYNC_DIGEST, [peer], b"", cb)
            res = box.get("res")
            if res is None or res.err is not None or res.data is None:
                self._sync_cursor[key] = cursor + 1  # dead: move on
                polled_ok = False
                continue
            try:
                theirs = pkt.parse_digest(res.data)
            except Exception:
                self._sync_cursor[key] = cursor + 1
                polled_ok = False
                continue
            prev = self._digests.get(peer.id)
            self._digests[peer.id] = theirs
            if prev is None:
                continue  # first sighting: nothing to diff against
            changed = [
                b
                for b in set(theirs) | set(prev)
                if theirs.get(b) != prev.get(b)
            ]
            dropped += sum(
                self.cache.invalidate_bucket(b) for b in changed
            )
        if dropped:
            metrics.incr("gateway.cache.sync_invalidated", dropped)
        if self._lease_s > 0.0 and polled_ok:
            # Every shard group answered: surviving cache entries were
            # confirmed unchanged (changed buckets just dropped), so
            # the freshness lease renews.  A failed poll lets the lease
            # lapse — stale serving must never outrun the digest plane.
            self._lease_until = time.monotonic() + self._lease_s
        return dropped

    def start_sync_invalidation(self, interval: float = 5.0) -> None:
        if self._sync_thread is not None:
            return
        self._sync_stop = threading.Event()

        def loop():
            while not self._sync_stop.wait(interval):
                try:
                    self.sync_invalidate_round()
                except Exception:
                    log.exception("gateway sync-invalidation failed")

        self._sync_thread = threading.Thread(
            target=loop, daemon=True, name="bftkv-gw-sync"
        )
        self._sync_thread.start()

    # -- observability -----------------------------------------------------

    def info(self) -> dict:
        """The ``/info`` document the fleet collector scrapes.  ``role``
        = "gateway" keeps this member OUT of the clique f-budget math —
        a gateway is not a quorum seat (obs/collector.py)."""
        g = self.self_node
        inflight, waiting = self.admission.depth()
        return {
            "name": getattr(g, "name", ""),
            "id": f"{g.get_self_id():016x}",
            "addr": getattr(self, "address", ""),
            "role": "gateway",
            "shard": None,
            "clique": None,
            "region": rg.self_region(getattr(g, "name", None)),
            "gateway": {
                **self.cache.stats(),
                "lease_served": self._lease_served,
                "lease_live": (
                    self._lease_s > 0.0
                    and time.monotonic() < self._lease_until
                ),
                # Per-INSTANCE counters: several gateways in one
                # process share the metrics registry, so snapshot
                # totals would report the whole tier as each member.
                "hits": self._hits,
                "misses": self._misses,
                "verify_fail": self._verify_fails,
                "shed": self.admission.shed,
                "inflight": inflight,
                "queued": waiting,
                "degraded_shards": sorted(
                    str(s) for s in self._degraded_shards
                ),
            },
        }
