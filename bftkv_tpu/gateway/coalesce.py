"""Write coalescing: burst traffic collapses into shared quorum rounds.

Every caller's write lands in one queue; a flusher drains it with a
tiny linger window and turns one drained batch into the FEWEST quorum
rounds that commit it:

- a **same-variable burst** keeps only its newest value — one
  piggybacked WRITE_SIGN round (the PR 8 collapsed path via
  ``Client.write``) commits it, and every caller of a superseded value
  is acked off that same round (``gateway.write.coalesced`` counts the
  writes that never paid a round of their own).  Within one burst the
  intermediate values were each durably superseded before any reader
  could require them — the same contract as a client overwriting its
  own variable back-to-back, minus the abandoned rounds;
- a **cross-variable burst** goes through ``Client.write_many``, which
  splits the batch by owning shard (``choose_quorum_for``) and runs
  one batched pipeline per shard.

The coalescer never re-orders across flushes and never merges across
variables, so per-variable semantics are exactly the underlying
client's.
"""

from __future__ import annotations

import logging
import queue
import threading

from bftkv_tpu import trace
from bftkv_tpu.metrics import registry as metrics
from bftkv_tpu.devtools.lockwatch import named_lock

__all__ = ["WriteCoalescer"]

log = logging.getLogger("bftkv_tpu.gateway")


class _Waiter:
    __slots__ = ("variable", "value", "event", "error")

    def __init__(self, variable: bytes, value: bytes):
        self.variable = variable
        self.value = value
        self.event = threading.Event()
        self.error: Exception | None = None


class WriteCoalescer:
    LINGER = 0.003
    MAX_BATCH = 256

    def __init__(self, client, linger: float | None = None):
        self.client = client
        self.linger = self.LINGER if linger is None else linger
        self._q: "queue.SimpleQueue[_Waiter]" = queue.SimpleQueue()
        self._lock = named_lock("gateway.coalesce")
        self._thread: threading.Thread | None = None
        self._stopped = False

    def stop(self) -> None:
        self._stopped = True

    def submit_wait(
        self, variable: bytes, value: bytes, timeout: float = 30.0
    ) -> Exception | None:
        """Enqueue one write and block until its burst commits (or
        fails).  Returns None on success, the per-write error
        otherwise; a flusher wedged past ``timeout`` reports as a
        TimeoutError rather than hanging the caller."""
        w = _Waiter(variable, value)
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, daemon=True, name="bftkv-gw-coalesce"
                )
                self._thread.start()
        self._q.put(w)
        if not w.event.wait(timeout):
            return TimeoutError("gateway write coalescer timed out")
        return w.error

    def _run(self) -> None:
        import time

        while not self._stopped:
            try:
                batch = [self._q.get(timeout=5.0)]
            except queue.Empty:
                continue  # daemon thread: cheap to keep parked
            deadline = time.monotonic() + self.linger
            while len(batch) < self.MAX_BATCH:
                try:
                    batch.append(
                        self._q.get(
                            timeout=max(0.0, deadline - time.monotonic())
                        )
                    )
                except queue.Empty:
                    break
            try:
                self._flush(batch)
            except Exception as e:  # defensive: never strand waiters
                log.exception("gateway coalescer flush failed")
                for w in batch:
                    if not w.event.is_set():
                        w.error = e
                        w.event.set()

    def _flush(self, batch: list[_Waiter]) -> None:
        # Same-variable collapse: the LAST submitted value wins its
        # variable; every earlier waiter rides the winning write.
        by_var: "dict[bytes, list[_Waiter]]" = {}
        for w in batch:
            by_var.setdefault(w.variable, []).append(w)
        coalesced = len(batch) - len(by_var)
        if coalesced:
            metrics.incr("gateway.write.coalesced", coalesced)
        items = [(var, ws[-1].value) for var, ws in by_var.items()]
        with trace.span(
            "gateway.write_flush",
            attrs={"batch": len(batch), "variables": len(items)},
        ):
            if len(items) == 1:
                var, val = items[0]
                err = None
                try:
                    # ONE piggybacked WRITE_SIGN round (PR 8's path).
                    self.client.write(var, val)
                except Exception as e:
                    err = e
                errs = {var: err}
            else:
                # Cross-variable burst: one batched pipeline per owning
                # shard (write_many splits by choose_quorum_for).
                metrics.incr("gateway.write.batched_rounds")
                try:
                    res = self.client.write_many(items)
                    errs = {var: e for (var, _v), e in zip(items, res)}
                except Exception as e:
                    errs = {var: e for var, _v in items}
        for var, ws in by_var.items():
            err = errs.get(var)
            for w in ws:
                w.error = err
                w.event.set()
