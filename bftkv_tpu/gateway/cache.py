"""Certified-record cache: bounded LRU + TTL, certified entries only.

The cache stores RAW record bytes ``<x, t, v, ss>`` whose completed
collective signature the gateway has already verified against the owner
quorum (gateway.py enforces that before every ``put`` — this module
just keeps the soundness-preserving bookkeeping):

- ``put`` never lets an older version clobber a newer one (a slow fill
  racing a write-through of the next timestamp must lose);
- entries expire after ``ttl`` seconds and evict LRU past
  ``max_entries`` — the backstop for invalidation traffic the gateway
  never saw (a direct client write, another gateway's write);
- every entry is indexed by its anti-entropy digest bucket
  (``sync.digest.bucket_of`` — the same ``sha256(x)[0]`` the routing
  plane uses), so a divergent-bucket signal from the sync plane
  invalidates exactly the affected 1/256th of the cache.

TPA-protected records must never be cached (the gateway would serve a
proof-gated value prooflessly); gateway.py filters them before ``put``.
"""

from __future__ import annotations

import time
from collections import OrderedDict

from bftkv_tpu.metrics import registry as metrics
from bftkv_tpu.sync.digest import bucket_of
from bftkv_tpu.devtools.lockwatch import named_lock

__all__ = ["CertifiedCache"]


class _Entry:
    __slots__ = ("t", "record", "expires")

    def __init__(self, t: int, record: bytes, expires: float):
        self.t = t
        self.record = record
        self.expires = expires


class CertifiedCache:
    def __init__(self, max_entries: int = 65536, ttl: float = 30.0):
        self.max_entries = max_entries
        self.ttl = ttl
        self._lock = named_lock("gateway.cache")
        self._od: "OrderedDict[bytes, _Entry]" = OrderedDict()
        self._buckets: dict[int, set[bytes]] = {}

    def __len__(self) -> int:
        with self._lock:
            return len(self._od)

    def get(
        self, variable: bytes, *, allow_stale: bool = False
    ) -> _Entry | None:
        """The live entry for ``variable`` (LRU-touched), or None.
        ``allow_stale`` also returns a TTL-expired entry — the
        degraded-shard fallback: the bytes are still CERTIFIED, only
        their freshness window has lapsed (gateway.py counts
        ``gateway.cache.stale_served`` when it uses one)."""
        now = time.monotonic()
        with self._lock:
            ent = self._od.get(variable)
            if ent is None:
                return None
            if ent.expires <= now and not allow_stale:
                return None
            self._od.move_to_end(variable)
            return ent

    def put(self, variable: bytes, t: int, record: bytes) -> bool:
        """Install a CERTIFIED record (caller has verified ``ss``).
        Returns False when a same-or-newer version is already cached —
        a stale fill racing a fresher write-through must not regress
        the entry (the TTL clock does restart on an exact-t refresh)."""
        now = time.monotonic()
        with self._lock:
            ent = self._od.get(variable)
            if ent is not None and ent.t > t:
                return False
            self._od[variable] = _Entry(t, record, now + self.ttl)
            self._od.move_to_end(variable)
            self._buckets.setdefault(bucket_of(variable), set()).add(
                variable
            )
            while len(self._od) > self.max_entries:
                old_var, _old = self._od.popitem(last=False)
                self._unindex_locked(old_var)
                metrics.incr("gateway.cache.evictions")
        return True

    def _unindex_locked(self, variable: bytes) -> None:
        b = bucket_of(variable)
        vs = self._buckets.get(b)
        if vs is not None:
            vs.discard(variable)
            if not vs:
                self._buckets.pop(b, None)

    def invalidate(self, variable: bytes) -> bool:
        with self._lock:
            ent = self._od.pop(variable, None)
            if ent is not None:
                self._unindex_locked(variable)
        if ent is not None:
            metrics.incr("gateway.cache.invalidations")
        return ent is not None

    def invalidate_bucket(self, bucket: int) -> int:
        """Drop every entry whose variable hashes into ``bucket`` (the
        anti-entropy invalidation hook).  Returns the count dropped."""
        with self._lock:
            vs = self._buckets.pop(bucket, None)
            if not vs:
                return 0
            for v in vs:
                self._od.pop(v, None)
            n = len(vs)
        metrics.incr("gateway.cache.invalidations", n)
        return n

    def clear(self) -> None:
        with self._lock:
            self._od.clear()
            self._buckets.clear()

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._od),
                "max_entries": self.max_entries,
                "ttl_s": self.ttl,
            }
