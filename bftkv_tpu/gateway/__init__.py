"""Edge gateway tier: the stateless front door in front of the quorums.

Every client today pays a full quorum fan-out per read and a WRITE_SIGN
round per write.  The gateway (ROADMAP item 1; "The Latency Price of
Threshold Cryptosystems" frames the win — keep threshold-crypto rounds
off the client-facing critical path) multiplexes that traffic:

- **certified read-through cache** (:mod:`bftkv_tpu.gateway.cache`):
  the gateway fills from the quorums through the client's resolve path
  and VERIFIES the collective signature against the owner quorum on
  every fill — only a certified ``<x, t, v, ss>`` is ever cached or
  served, so a compromised gateway cannot forge reads (and the
  :class:`GatewayClient` re-verifies what it is served);
- **write coalescing** (:mod:`bftkv_tpu.gateway.coalesce`): a
  same-variable write burst collapses into ONE piggybacked WRITE_SIGN
  round with per-caller acks fanned back out; cross-variable bursts
  batch per shard via ``choose_quorum_for``;
- **admission control / load shedding**: a bounded admission queue
  sheds excess load instantly (``gateway.shed``) instead of queueing
  it onto the quorums; gateway→quorum RPCs ride the hedged,
  health-ranked staged fan-out (DESIGN.md §13) and a fleet snapshot
  routes reads of degraded shards onto the stale-cache fallback.

Gateways are stateless (the cache is a soundness-checked accelerator,
never a source of truth) and horizontally stackable with zero
coordination: N gateways share one TOFU uid (topology.build_universe
``n_gateways``), so a variable written through one can be overwritten
through any other.  DESIGN.md §14.
"""

from bftkv_tpu.gateway.cache import CertifiedCache
from bftkv_tpu.gateway.client import GatewayClient, GatewayPeer
from bftkv_tpu.gateway.coalesce import WriteCoalescer
from bftkv_tpu.gateway.gateway import AdmissionQueue, Gateway

__all__ = [
    "AdmissionQueue",
    "CertifiedCache",
    "Gateway",
    "GatewayClient",
    "GatewayPeer",
    "WriteCoalescer",
]
