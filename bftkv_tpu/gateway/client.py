"""GatewayClient: the end-client side of the front door.

Wraps an ordinary protocol :class:`~bftkv_tpu.protocol.client.Client`'s
transport/crypto/quorum state and talks to a SET of gateways over the
same encrypted session envelope every other command uses — one post per
operation instead of a quorum fan-out.

Routing is rendezvous (HRW) per variable over the gateway set: the same
variable always lands on the same gateway first, so cache hit rates do
not dilute as gateways are added, and write bursts for one variable
meet in one coalescer.  Transport-level failures fail over down the
HRW order (the tier is stateless — any gateway can serve anything);
protocol errors are answers and raise immediately.

Trust: the gateway is NOT trusted.  Every non-empty read is re-verified
here — the served record must name the requested variable and carry a
completed collective signature that verifies against the owner quorum
from THIS client's keyring (memoized by the process verify cache, so
repeat reads of one record cost a dict hit, not RSA).  A compromised
gateway can therefore serve stale-but-certified state at worst, never a
fabricated value — the same bound a Byzantine quorum member already
has against a direct reader.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict

from bftkv_tpu import packet as pkt
from bftkv_tpu import quorum as qm
from bftkv_tpu import trace
from bftkv_tpu import transport as tp
from bftkv_tpu.errors import ERR_UNCERTIFIED_RECORD, Error
from bftkv_tpu.metrics import registry as metrics
from bftkv_tpu.devtools.lockwatch import named_lock

__all__ = ["GatewayClient", "GatewayPeer"]


class GatewayPeer:
    """A gateway certificate paired with its dial address.

    Gateway certificates deliberately carry NO address (an addressed
    vertex would enter the quorum planes' ``U`` — see
    ``topology.Universe.gateways``), so the transport-facing peer
    object is this wrapper: ``address`` comes from deployment config,
    everything else (id, keys, name) delegates to the certificate."""

    def __init__(self, cert, address: str):
        self.cert = cert
        self.address = address

    def __getattr__(self, name):
        return getattr(object.__getattribute__(self, "cert"), name)

    def __repr__(self) -> str:  # pragma: no cover
        return f"GatewayPeer({self.cert.name} @ {self.address})"

#: Transport-failure messages that trigger failover to the next
#: gateway; anything else is an answer from a live gateway and raises.
_FAILOVER = {
    tp.ERR_UNREACHABLE.message,
    tp.ERR_RPC_TIMEOUT.message,
    tp.ERR_SERVER_ERROR.message,
    tp.ERR_PEER_OPEN.message,
    tp.ERR_NO_ADDRESS.message,
}


class GatewayClient:
    def __init__(self, client, gateways: list, *, verify: bool = True):
        """``client``: the protocol client whose transport, keyring and
        quorum system this front end rides (it is NOT used for quorum
        fan-outs here).  ``gateways``: peer objects with ``.id``,
        key material, and ``.address`` — typically
        :class:`GatewayPeer` wrappers pairing a gateway certificate
        with its configured dial address."""
        if not gateways:
            raise ValueError("GatewayClient needs at least one gateway")
        self.client = client
        self.gateways = list(gateways)
        self.verify = verify
        # Verified-record memo, keyed by sha256(variable | record):
        # repeat serves of one cached record re-verify as a dict hit.
        # Content-addressed, so it can never validate different bytes;
        # bounded LRU, so a hostile gateway can at worst evict entries.
        self._verified: "OrderedDict[bytes, None]" = OrderedDict()
        self._verified_lock = named_lock("gateway.client.verified")

    _VERIFIED_MAX = 4096

    def _route(self, variable: bytes) -> list:
        """HRW order for ``variable`` over the gateway set."""
        def score(gw):
            h = hashlib.sha256()
            h.update(variable)
            h.update(int(getattr(gw, "id", 0)).to_bytes(8, "big"))
            return h.digest()

        return sorted(self.gateways, key=score)

    def _post(self, cmd: int, gw, req: bytes):
        box: dict = {}

        def cb(res: tp.MulticastResponse) -> bool:
            box["res"] = res
            return True

        self.client.tr.multicast(cmd, [gw], req, cb)
        return box.get("res")

    def _call(self, cmd: int, variable: bytes, req: bytes) -> bytes | None:
        last: Exception | None = None
        for gw in self._route(variable):
            res = self._post(cmd, gw, req)
            if res is None:
                continue
            if res.err is None:
                return res.data
            last = res.err
            if getattr(res.err, "message", None) in _FAILOVER:
                metrics.incr("gateway.client.failover")
                continue  # dead gateway: any other can serve
            raise res.err  # an answer, not an outage
        raise last if last is not None else tp.ERR_UNREACHABLE

    # -- reads -------------------------------------------------------------

    def read(self, variable: bytes, proof=None) -> bytes | None:
        with metrics.timer("gateway.client.read.latency"), trace.span(
            "gateway_client.read"
        ):
            req = pkt.serialize(variable, None, 0, None, proof)
            raw = self._call(tp.GW_READ, variable, req)
            if not raw:
                return None
            p = self._check_served(variable, raw)
            return p.value

    def read_record(
        self, variable: bytes, proof=None
    ) -> tuple[bytes | None, int, bytes | None]:
        """Like :meth:`read` but returns ``(value, t, raw record)`` —
        callers that persist or forward certified records use this."""
        req = pkt.serialize(variable, None, 0, None, proof)
        raw = self._call(tp.GW_READ, variable, req)
        if not raw:
            return None, 0, None
        p = self._check_served(variable, raw)
        return p.value, p.t, raw

    def _check_served(self, variable: bytes, raw: bytes):
        """The client-side half of the certified rule: a served record
        must name the requested variable and (with ``verify`` on)
        carry a completed collective signature endorsed by the owner
        quorum — verified HERE, against this client's own keyring."""
        h = None
        if self.verify:
            h = hashlib.sha256(
                len(variable).to_bytes(8, "big") + variable + raw
            ).digest()
            with self._verified_lock:
                if h in self._verified:
                    self._verified.move_to_end(h)
                    return pkt.parse(raw)
        try:
            p = pkt.parse(raw)
        except Exception:
            raise ERR_UNCERTIFIED_RECORD from None
        if (p.variable or b"") != variable or p.ss is None or (
            not p.ss.completed
        ):
            metrics.incr("gateway.client.verify_fail")
            raise ERR_UNCERTIFIED_RECORD
        if self.verify:
            qa = qm.choose_quorum_for(self.client.qs, variable, qm.AUTH)
            try:
                with trace.span("gateway_client.verify"):
                    self.client.crypt.collective.verify(
                        pkt.tbss(raw), p.ss, qa, self.client.crypt.keyring
                    )
            except Exception:
                metrics.incr("gateway.client.verify_fail")
                raise ERR_UNCERTIFIED_RECORD from None
            with self._verified_lock:
                self._verified[h] = None
                self._verified.move_to_end(h)
                while len(self._verified) > self._VERIFIED_MAX:
                    self._verified.popitem(last=False)
        return p

    # -- writes ------------------------------------------------------------

    def write(self, variable: bytes, value: bytes) -> None:
        """Write through the front door: the HRW-primary gateway signs
        and commits the value upstream (coalescing same-variable
        bursts into one round).  Raises the per-write error on
        failure, exactly like ``Client.write``."""
        with metrics.timer("gateway.client.write.latency"), trace.span(
            "gateway_client.write"
        ):
            req = pkt.serialize(variable, value, 0, None, None)
            self._call(tp.GW_WRITE, variable, req)

    def read_many(self, variables: list[bytes], proof=None) -> list:
        """Convenience sequential batch (one post per variable; the
        gateway's cache makes the common case one dict hit each).
        Returns value / None / the per-item :class:`Error`."""
        out: list = []
        for v in variables:
            try:
                out.append(self.read(v, proof))
            except Error as e:
                out.append(e)
        return out
