"""Process-wide metrics registry: counters, gauges, latency histograms.

The reference has no metrics beyond ``log.Printf`` (SURVEY.md §5); the
TPU framework needs them to steer batching — sig-verifies/sec, device
batch occupancy, quorum latencies are the signals the dispatcher and
the benchmark harness read.  Deliberately dependency-free and cheap:
one lock, plain dicts, snapshot on demand.

Every instrument takes optional ``labels`` (a small dict of low-
cardinality dimensions — command names, transport kind, never
variables or peer addresses; cardinality rules in docs/DESIGN.md §7).
Two export surfaces:

- :meth:`Metrics.snapshot` — the historical flat JSON dict; labeled
  series flatten to ``name{k=v,...}`` keys, unlabeled keys are
  unchanged so existing consumers keep working;
- :meth:`Metrics.prometheus` — Prometheus text exposition (0.0.4):
  counters as ``bftkv_<name>_total``, gauges as ``bftkv_<name>``,
  ``observe()`` series as summaries (``_count``/``_sum`` + quantiles).
"""

from __future__ import annotations

import re
import threading
import time
from collections import defaultdict

__all__ = ["Metrics", "registry"]

#: Label sets are stored as sorted (key, value) tuples; () = unlabeled.
_NO_LABELS: tuple = ()


def _key(name: str, labels: dict | None) -> tuple[str, tuple]:
    if not labels:
        return (name, _NO_LABELS)
    return (name, tuple(sorted(labels.items())))


def _flat(name: str, labels: tuple) -> str:
    """Flat JSON-snapshot key: ``name`` or ``name{k=v,...}``."""
    if not labels:
        return name
    return name + "{" + ",".join(f"{k}={v}" for k, v in labels) + "}"


def _prom_name(name: str) -> str:
    return "bftkv_" + re.sub(r"[^a-zA-Z0-9_:]", "_", name)


def _prom_labels(labels: tuple, extra: tuple = ()) -> str:
    items = tuple(labels) + tuple(extra)
    if not items:
        return ""

    def esc(v) -> str:
        return (
            str(v)
            .replace("\\", "\\\\")
            .replace('"', '\\"')
            .replace("\n", "\\n")
        )

    return "{" + ",".join(f'{k}="{esc(v)}"' for k, v in items) + "}"


def _prom_value(v) -> str:
    return repr(v) if isinstance(v, float) else str(v)


class Metrics:
    def __init__(self):
        self._lock = threading.Lock()
        # Counters are sharded PER THREAD: ``incr`` is the hottest call
        # in the process (several per RPC from every handler, fan-out
        # worker and writer thread), and a single shared lock made each
        # contended acquire a blocking GIL round trip — profiled at
        # ~14 ms per blocked incr on the cluster_4 bench.  Each thread
        # mutates only its own dict (GIL-atomic for str/tuple keys);
        # readers sum the shards.  Totals are exact at read time.
        # Shards of finished threads stay in the list (their counts
        # must keep counting); growth is bounded by the process's peak
        # thread count, and the fan-out pool reuses threads.
        self._tl = threading.local()
        self._counter_shards: list[dict] = []
        self._gauges: dict[tuple, float] = {}
        self._counts: dict[tuple, int] = defaultdict(int)
        self._sums: dict[tuple, float] = defaultdict(float)
        self._samples: dict[tuple, list[float]] = defaultdict(list)
        # Ring-buffer write cursors: the histogram must keep admitting
        # values forever.  The old append-until-full behavior froze each
        # series at its first 65536 samples, so a daemon's p50/p99
        # reported startup behavior for the rest of its life.
        self._sample_pos: dict[tuple, int] = defaultdict(int)
        self._max_samples = 65536

    def _local_counters(self) -> dict:
        d = getattr(self._tl, "counters", None)
        if d is None:
            d = self._tl.counters = defaultdict(int)
            with self._lock:
                self._counter_shards.append(d)
        return d

    def _counter_totals(self) -> dict:
        totals: dict[tuple, int] = defaultdict(int)
        with self._lock:
            shards = list(self._counter_shards)
        for d in shards:
            # dict.copy() is a single C-level operation under the GIL,
            # so a concurrently-incrementing owner thread cannot tear it.
            for k, v in d.copy().items():
                totals[k] += v
        return dict(totals)

    def incr(self, name: str, n: int = 1, labels: dict | None = None) -> None:
        self._local_counters()[_key(name, labels)] += n

    def gauge(
        self, name: str, value: float, labels: dict | None = None
    ) -> None:
        """Last-write-wins instantaneous value (queue depth, occupancy,
        throughput of the latest flush)."""
        with self._lock:
            self._gauges[_key(name, labels)] = value

    def observe(
        self, name: str, value: float, labels: dict | None = None
    ) -> None:
        """Record one sample (latency seconds, batch size, ...).

        Samples land in a per-series ring buffer: totals (`.count` /
        `.sum`) cover the whole run while percentiles reflect the most
        recent ``_max_samples`` window."""
        k = _key(name, labels)
        with self._lock:
            self._counts[k] += 1
            self._sums[k] += value
            s = self._samples[k]
            if len(s) < self._max_samples:
                s.append(value)
            else:
                s[self._sample_pos[k]] = value
                self._sample_pos[k] = (
                    self._sample_pos[k] + 1
                ) % self._max_samples

    class _Timer:
        def __init__(self, m: "Metrics", name: str):
            self.m, self.name = m, name

        def __enter__(self):
            self.t0 = time.perf_counter()
            return self

        def __exit__(self, *exc):
            self.m.observe(self.name, time.perf_counter() - self.t0)
            return False

    def timer(self, name: str) -> "Metrics._Timer":
        return Metrics._Timer(self, name)

    def percentile(
        self, name: str, q: float, labels: dict | None = None
    ) -> float | None:
        # Copy under the lock, sort outside: sorting up to 65536
        # samples while holding the lock stalled every concurrent
        # observe() for the duration of the sort.
        with self._lock:
            s = list(self._samples.get(_key(name, labels), ()))
        if not s:
            return None
        s.sort()
        i = min(len(s) - 1, int(q * len(s)))
        return s[i]

    def snapshot(self) -> dict:
        counters = self._counter_totals()
        with self._lock:
            # Copy everything under the lock — concurrent incr/observe
            # of a *new* name would otherwise mutate dicts
            # mid-iteration — but sort OUTSIDE it (see percentile()).
            gauges = dict(self._gauges)
            counts = dict(self._counts)
            sums = dict(self._sums)
            series = {k: list(s) for k, s in self._samples.items() if s}
        out: dict = {}
        for (name, labels), v in counters.items():
            out[_flat(name, labels)] = v
        for (name, labels), v in gauges.items():
            out[_flat(name, labels)] = v
        for (name, labels), v in counts.items():
            out[_flat(name + ".count", labels)] = v
        for (name, labels), v in sums.items():
            out[_flat(name + ".sum", labels)] = v
        for (name, labels), s in series.items():
            s.sort()
            for q, tag in ((0.5, "p50"), (0.99, "p99")):
                out[_flat(f"{name}.{tag}", labels)] = s[
                    min(len(s) - 1, int(q * len(s)))
                ]
        return out

    def prometheus(self) -> str:
        """Prometheus text exposition, format 0.0.4.

        Counter names end in ``_total``; ``observe()`` series render as
        summaries (``{quantile="..."}`` samples over the recent window,
        ``_sum``/``_count`` over the whole run); gauges are plain."""
        counters = self._counter_totals()
        with self._lock:
            gauges = dict(self._gauges)
            counts = dict(self._counts)
            sums = dict(self._sums)
            series = {k: list(s) for k, s in self._samples.items() if s}

        lines: list[str] = []

        def by_name(d: dict) -> dict[str, list]:
            g: dict[str, list] = {}
            for (name, labels), v in d.items():
                g.setdefault(name, []).append((labels, v))
            return g

        for name, rows in sorted(by_name(counters).items()):
            pn = _prom_name(name) + "_total"
            lines.append(f"# TYPE {pn} counter")
            for labels, v in sorted(rows):
                lines.append(f"{pn}{_prom_labels(labels)} {_prom_value(v)}")

        for name, rows in sorted(by_name(gauges).items()):
            pn = _prom_name(name)
            lines.append(f"# TYPE {pn} gauge")
            for labels, v in sorted(rows):
                lines.append(f"{pn}{_prom_labels(labels)} {_prom_value(v)}")

        for name, rows in sorted(by_name(series).items()):
            pn = _prom_name(name)
            lines.append(f"# TYPE {pn} summary")
            for labels, s in sorted(rows):
                s.sort()
                for q in (0.5, 0.9, 0.99):
                    v = s[min(len(s) - 1, int(q * len(s)))]
                    lines.append(
                        f"{pn}{_prom_labels(labels, (('quantile', q),))}"
                        f" {_prom_value(v)}"
                    )
                key = (name, labels)
                lines.append(
                    f"{pn}_sum{_prom_labels(labels)}"
                    f" {_prom_value(sums.get(key, 0.0))}"
                )
                lines.append(
                    f"{pn}_count{_prom_labels(labels)}"
                    f" {_prom_value(counts.get(key, 0))}"
                )

        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        with self._lock:
            shards = list(self._counter_shards)
            self._gauges.clear()
            self._counts.clear()
            self._sums.clear()
            self._samples.clear()
            self._sample_pos.clear()
        for d in shards:
            d.clear()


registry = Metrics()
