"""Process-wide metrics registry: counters, gauges, latency histograms.

The reference has no metrics beyond ``log.Printf`` (SURVEY.md §5); the
TPU framework needs them to steer batching — sig-verifies/sec, device
batch occupancy, quorum latencies are the signals the dispatcher and
the benchmark harness read.  Deliberately dependency-free and cheap:
one lock, plain dicts, snapshot on demand.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict

__all__ = ["Metrics", "registry"]


class Metrics:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, int] = defaultdict(int)
        self._sums: dict[str, float] = defaultdict(float)
        self._samples: dict[str, list[float]] = defaultdict(list)
        # Ring-buffer write cursors: the histogram must keep admitting
        # values forever.  The old append-until-full behavior froze each
        # series at its first 65536 samples, so a daemon's p50/p99
        # reported startup behavior for the rest of its life.
        self._sample_pos: dict[str, int] = defaultdict(int)
        self._max_samples = 65536

    def incr(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] += n

    def observe(self, name: str, value: float) -> None:
        """Record one sample (latency seconds, batch size, ...).

        Samples land in a per-series ring buffer: totals (`.count` /
        `.sum`) cover the whole run while percentiles reflect the most
        recent ``_max_samples`` window."""
        with self._lock:
            self._counters[name + ".count"] += 1
            self._sums[name + ".sum"] += value
            s = self._samples[name]
            if len(s) < self._max_samples:
                s.append(value)
            else:
                s[self._sample_pos[name]] = value
                self._sample_pos[name] = (
                    self._sample_pos[name] + 1
                ) % self._max_samples

    class _Timer:
        def __init__(self, m: "Metrics", name: str):
            self.m, self.name = m, name

        def __enter__(self):
            self.t0 = time.perf_counter()
            return self

        def __exit__(self, *exc):
            self.m.observe(self.name, time.perf_counter() - self.t0)
            return False

    def timer(self, name: str) -> "Metrics._Timer":
        return Metrics._Timer(self, name)

    def percentile(self, name: str, q: float) -> float | None:
        with self._lock:
            s = sorted(self._samples.get(name, ()))
        if not s:
            return None
        i = min(len(s) - 1, int(q * len(s)))
        return s[i]

    def snapshot(self) -> dict:
        with self._lock:
            out: dict = dict(self._counters)
            out.update(self._sums)
            # Copy the series under the lock: concurrent observe() of a
            # *new* name would otherwise mutate the dict mid-iteration.
            series = {n: sorted(s) for n, s in self._samples.items() if s}
        for name, s in series.items():
            for q, tag in ((0.5, "p50"), (0.99, "p99")):
                out[f"{name}.{tag}"] = s[min(len(s) - 1, int(q * len(s)))]
        return out

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._sums.clear()
            self._samples.clear()
            self._sample_pos.clear()


registry = Metrics()
