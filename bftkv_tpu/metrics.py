"""Process-wide metrics registry: counters, gauges, latency histograms.

The reference has no metrics beyond ``log.Printf`` (SURVEY.md §5); the
TPU framework needs them to steer batching — sig-verifies/sec, device
batch occupancy, quorum latencies are the signals the dispatcher and
the benchmark harness read.  Deliberately dependency-free and cheap:
one lock, plain dicts, snapshot on demand.

Every instrument takes optional ``labels`` (a small dict of low-
cardinality dimensions — command names, transport kind, shard indices,
never variables or peer addresses; cardinality rules in
docs/DESIGN.md §7).  Two export surfaces:

- :meth:`Metrics.snapshot` — the historical flat JSON dict; labeled
  series flatten to ``name{k=v,...}`` keys, unlabeled keys are
  unchanged so existing consumers keep working.  Each ``observe()``
  series additionally exports its fixed-bucket counts as
  ``name.bucket{le=...}`` keys;
- :meth:`Metrics.prometheus` — Prometheus text exposition (0.0.4):
  counters as ``bftkv_<name>_total``, gauges as ``bftkv_<name>``,
  ``observe()`` series as **histograms** (``_bucket{le=...}`` +
  ``_count``/``_sum``).

Histograms, not summaries: every daemon uses the same fixed bucket
bounds (:data:`BUCKETS`), so a fleet collector can sum bucket counts
across processes and compute fleet-wide quantile estimates — per-daemon
summary quantiles cannot be merged at all (the p99 of a set of p99s is
meaningless).  The in-process percentile()/snapshot p50/p99 keys stay
sample-exact for single-process consumers (bench.py).
"""

from __future__ import annotations

import re
import threading
import time
from collections import defaultdict
from bftkv_tpu.devtools.lockwatch import named_lock

__all__ = [
    "BUCKETS", "LABEL_KEYS", "Metrics", "histogram_quantile", "registry",
]

#: The CLOSED enum of label keys any instrument may carry.  Labels are
#: low-cardinality dimensions only (DESIGN.md §7); the key vocabulary
#: itself is fixed here so ``tools/bftlint``'s ``label-enum`` rule can
#: statically reject a call site inventing a new dimension (the
#: runtime cardinality tests bound the VALUES, this bounds the keys).
#: Adding a key is a deliberate schema change: extend this tuple and
#: document the dimension in DESIGN.md §7.
LABEL_KEYS = (
    "transport",  # backend: http / loop / visual / ws
    "side",       # client / server
    "cmd",        # protocol command name (closed command enum)
    "shard",      # shard index (int, < shard count)
    "op",         # gateway op (read/write) / sidecar op (verify/sign/modexp)
    "point",      # failpoint name (closed hook-site enum)
    "action",     # failpoint action kind
    "endpoint",   # daemon API endpoint (closed set + "other")
    "peer",       # normalized link name (bounded by fleet size)
    "event",      # visual/ws event type
    "kind",       # autopilot plan kind: split / retire
    "resource",   # capacity-plane resource (closed capacity.RESOURCES enum)
    "width",      # device batch limb-width group (bounded: few limb sizes + "ec")
    "le",         # histogram bucket bound (fixed BUCKETS ladder)
)

#: Fixed histogram bucket upper bounds, IDENTICAL in every process so
#: bucket counts sum across daemons.  The low end covers RPC/crypto
#: latencies (seconds), the high end covers the other observe() users
#: (batch sizes, items/s) coarsely — a count landing past 60 falls into
#: the wide tail buckets and the +Inf overflow.  Changing these bounds
#: is a fleet-wide flag day: collector merges require equal ladders.
BUCKETS: tuple = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 1000.0, 100000.0,
)


def histogram_quantile(q: float, buckets: list[int] | tuple) -> float | None:
    """Quantile estimate from per-bucket counts (len(BUCKETS)+1, the
    last being +Inf overflow): the upper bound of the bucket holding
    the q-th sample.  None on an empty histogram.  This is the merge
    side of the fixed-ladder design — sum per-daemon bucket vectors,
    then call this."""
    total = sum(buckets)
    if total <= 0:
        return None
    rank = q * total
    acc = 0
    for i, c in enumerate(buckets):
        acc += c
        if acc > rank or acc >= total:
            return BUCKETS[i] if i < len(BUCKETS) else float("inf")
    return float("inf")  # pragma: no cover


def _bucket_index(value: float) -> int:
    lo, hi = 0, len(BUCKETS)
    while lo < hi:
        mid = (lo + hi) // 2
        if value <= BUCKETS[mid]:
            hi = mid
        else:
            lo = mid + 1
    return lo  # == len(BUCKETS) -> +Inf overflow


#: Label sets are stored as sorted (key, value) tuples; () = unlabeled.
_NO_LABELS: tuple = ()


def _key(name: str, labels: dict | None) -> tuple[str, tuple]:
    if not labels:
        return (name, _NO_LABELS)
    return (name, tuple(sorted(labels.items())))


def _flat(name: str, labels: tuple) -> str:
    """Flat JSON-snapshot key: ``name`` or ``name{k=v,...}``."""
    if not labels:
        return name
    return name + "{" + ",".join(f"{k}={v}" for k, v in labels) + "}"


def _prom_name(name: str) -> str:
    return "bftkv_" + re.sub(r"[^a-zA-Z0-9_:]", "_", name)


def _prom_labels(labels: tuple, extra: tuple = ()) -> str:
    items = tuple(labels) + tuple(extra)
    if not items:
        return ""

    def esc(v) -> str:
        return (
            str(v)
            .replace("\\", "\\\\")
            .replace('"', '\\"')
            .replace("\n", "\\n")
        )

    return "{" + ",".join(f'{k}="{esc(v)}"' for k, v in items) + "}"


def _prom_value(v) -> str:
    return repr(v) if isinstance(v, float) else str(v)


class Metrics:
    def __init__(self):
        self._lock = named_lock("metrics")
        # Counters are sharded PER THREAD: ``incr`` is the hottest call
        # in the process (several per RPC from every handler, fan-out
        # worker and writer thread), and a single shared lock made each
        # contended acquire a blocking GIL round trip — profiled at
        # ~14 ms per blocked incr on the cluster_4 bench.  Each thread
        # mutates only its own dict (GIL-atomic for str/tuple keys);
        # readers sum the shards.  Totals are exact at read time.
        # Shards of finished threads stay in the list (their counts
        # must keep counting); growth is bounded by the process's peak
        # thread count, and the fan-out pool reuses threads.
        self._tl = threading.local()
        self._counter_shards: list[dict] = []
        self._gauges: dict[tuple, float] = {}
        self._counts: dict[tuple, int] = defaultdict(int)
        self._sums: dict[tuple, float] = defaultdict(float)
        self._samples: dict[tuple, list[float]] = defaultdict(list)
        # Fixed-bucket counts per observe() series (len(BUCKETS)+1; the
        # last slot is the +Inf overflow).  Unlike the sample ring these
        # cover the WHOLE run and merge across processes by summation.
        self._buckets: dict[tuple, list[int]] = defaultdict(
            lambda: [0] * (len(BUCKETS) + 1)
        )
        # Ring-buffer write cursors: the histogram must keep admitting
        # values forever.  The old append-until-full behavior froze each
        # series at its first 65536 samples, so a daemon's p50/p99
        # reported startup behavior for the rest of its life.
        self._sample_pos: dict[tuple, int] = defaultdict(int)
        self._max_samples = 65536

    def _local_counters(self) -> dict:
        d = getattr(self._tl, "counters", None)
        if d is None:
            d = self._tl.counters = defaultdict(int)
            with self._lock:
                self._counter_shards.append(d)
        return d

    def _counter_totals(self) -> dict:
        totals: dict[tuple, int] = defaultdict(int)
        with self._lock:
            shards = list(self._counter_shards)
        for d in shards:
            # dict.copy() is a single C-level operation under the GIL,
            # so a concurrently-incrementing owner thread cannot tear it.
            for k, v in d.copy().items():
                totals[k] += v
        return dict(totals)

    def incr(self, name: str, n: int = 1, labels: dict | None = None) -> None:
        self._local_counters()[_key(name, labels)] += n

    def gauge(
        self, name: str, value: float, labels: dict | None = None
    ) -> None:
        """Last-write-wins instantaneous value (queue depth, occupancy,
        throughput of the latest flush)."""
        with self._lock:
            self._gauges[_key(name, labels)] = value

    def observe(
        self, name: str, value: float, labels: dict | None = None
    ) -> None:
        """Record one sample (latency seconds, batch size, ...).

        Samples land in a per-series ring buffer: totals (`.count` /
        `.sum`) cover the whole run while percentiles reflect the most
        recent ``_max_samples`` window."""
        k = _key(name, labels)
        with self._lock:
            self._counts[k] += 1
            self._sums[k] += value
            self._buckets[k][_bucket_index(value)] += 1
            s = self._samples[k]
            if len(s) < self._max_samples:
                s.append(value)
            else:
                s[self._sample_pos[k]] = value
                self._sample_pos[k] = (
                    self._sample_pos[k] + 1
                ) % self._max_samples

    class _Timer:
        def __init__(self, m: "Metrics", name: str):
            self.m, self.name = m, name

        def __enter__(self):
            self.t0 = time.perf_counter()
            return self

        def __exit__(self, *exc):
            self.m.observe(self.name, time.perf_counter() - self.t0)
            return False

    def timer(self, name: str) -> "Metrics._Timer":
        return Metrics._Timer(self, name)

    def percentile(
        self, name: str, q: float, labels: dict | None = None
    ) -> float | None:
        # Copy under the lock, sort outside: sorting up to 65536
        # samples while holding the lock stalled every concurrent
        # observe() for the duration of the sort.
        with self._lock:
            s = list(self._samples.get(_key(name, labels), ()))
        if not s:
            return None
        s.sort()
        i = min(len(s) - 1, int(q * len(s)))
        return s[i]

    def snapshot(self) -> dict:
        counters = self._counter_totals()
        with self._lock:
            # Copy everything under the lock — concurrent incr/observe
            # of a *new* name would otherwise mutate dicts
            # mid-iteration — but sort OUTSIDE it (see percentile()).
            gauges = dict(self._gauges)
            counts = dict(self._counts)
            sums = dict(self._sums)
            series = {k: list(s) for k, s in self._samples.items() if s}
            buckets = {k: list(b) for k, b in self._buckets.items()}
        out: dict = {}
        for (name, labels), v in counters.items():
            out[_flat(name, labels)] = v
        for (name, labels), v in gauges.items():
            out[_flat(name, labels)] = v
        for (name, labels), v in counts.items():
            out[_flat(name + ".count", labels)] = v
        for (name, labels), v in sums.items():
            out[_flat(name + ".sum", labels)] = v
        for (name, labels), s in series.items():
            s.sort()
            for q, tag in ((0.5, "p50"), (0.99, "p99")):
                out[_flat(f"{name}.{tag}", labels)] = s[
                    min(len(s) - 1, int(q * len(s)))
                ]
        # Fixed-bucket counts, one flat key per non-empty bucket (the
        # collector's merge input; empty buckets are elided to keep the
        # snapshot small).  ``le`` joins the series' own labels so the
        # key parses with the same name{k=v,...} grammar.
        for (name, labels), b in buckets.items():
            for i, c in enumerate(b):
                if not c:
                    continue
                le = BUCKETS[i] if i < len(BUCKETS) else "+Inf"
                out[
                    _flat(f"{name}.bucket", labels + (("le", le),))
                ] = c
        return out

    def histograms(self) -> dict:
        """Structured fixed-bucket export: flat series key →
        ``{"count", "sum", "buckets"}`` with ``buckets`` the raw
        per-bucket counts (len(BUCKETS)+1, last = +Inf overflow).
        In-process convenience view; the fleet collector itself merges
        from the snapshot's ``name.bucket{le=}`` flat keys, since that
        is the only form that crosses the daemon ``/metrics`` wire."""
        with self._lock:
            counts = dict(self._counts)
            sums = dict(self._sums)
            buckets = {k: list(b) for k, b in self._buckets.items()}
        return {
            _flat(name, labels): {
                "count": counts.get((name, labels), 0),
                "sum": sums.get((name, labels), 0.0),
                "buckets": b,
            }
            for (name, labels), b in buckets.items()
        }

    def prometheus(self) -> str:
        """Prometheus text exposition, format 0.0.4.

        Counter names end in ``_total``; ``observe()`` series render as
        fixed-bucket HISTOGRAMS (cumulative ``_bucket{le="..."}`` +
        ``_sum``/``_count`` over the whole run) so any scraper — and
        the fleet collector — can aggregate latency across daemons;
        gauges are plain.  (Summaries were the original exposition;
        per-daemon quantiles cannot be merged, DESIGN.md §11.)"""
        counters = self._counter_totals()
        with self._lock:
            gauges = dict(self._gauges)
            counts = dict(self._counts)
            sums = dict(self._sums)
            series = {k: list(b) for k, b in self._buckets.items()}

        lines: list[str] = []

        def by_name(d: dict) -> dict[str, list]:
            g: dict[str, list] = {}
            for (name, labels), v in d.items():
                g.setdefault(name, []).append((labels, v))
            return g

        for name, rows in sorted(by_name(counters).items()):
            pn = _prom_name(name) + "_total"
            lines.append(f"# TYPE {pn} counter")
            for labels, v in sorted(rows):
                lines.append(f"{pn}{_prom_labels(labels)} {_prom_value(v)}")

        for name, rows in sorted(by_name(gauges).items()):
            pn = _prom_name(name)
            lines.append(f"# TYPE {pn} gauge")
            for labels, v in sorted(rows):
                lines.append(f"{pn}{_prom_labels(labels)} {_prom_value(v)}")

        for name, rows in sorted(by_name(series).items()):
            pn = _prom_name(name)
            lines.append(f"# TYPE {pn} histogram")
            for labels, b in sorted(rows):
                acc = 0
                for i, c in enumerate(b):
                    acc += c
                    le = BUCKETS[i] if i < len(BUCKETS) else "+Inf"
                    lines.append(
                        f"{pn}_bucket{_prom_labels(labels, (('le', le),))}"
                        f" {acc}"
                    )
                key = (name, labels)
                lines.append(
                    f"{pn}_sum{_prom_labels(labels)}"
                    f" {_prom_value(sums.get(key, 0.0))}"
                )
                lines.append(
                    f"{pn}_count{_prom_labels(labels)}"
                    f" {_prom_value(counts.get(key, 0))}"
                )

        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        with self._lock:
            shards = list(self._counter_shards)
            self._gauges.clear()
            self._counts.clear()
            self._sums.clear()
            self._samples.clear()
            self._sample_pos.clear()
            self._buckets.clear()
        for d in shards:
            d.clear()


registry = Metrics()
