"""Planet-scale universe generation + the scaling profiler.

Real identities pay an RSA keygen (~100 ms each) — a 10k-node universe
would spend 20 minutes minting keys before the first routing question.
This module generates SYNTHETIC principals instead: lightweight cert
objects satisfying exactly the duck-type the trust graph consumes
(``id`` / ``name`` / ``address`` / ``signers()`` / ``serialize()``),
streamed in per-shard cliques, so clique discovery, ``_ShardTopo``
build, and ``choose_quorum_for`` can be exercised and profiled at
10k–100k nodes.  The routing plane is a pure function of the edge set
— no signature is ever verified to build a topology — so synthetic
certs measure the real code paths.

Membership churn and revocation storms are SCHEDULES (deterministic
event lists from the sha256(seed|stream|counter) discipline), applied
as graph mutations; each bumps ``graph.generation`` and the §18
scaling question is how fast the generation-guard memos rebuild.

The profiler (`python -m bftkv_tpu.workload.universe --nodes 10000`)
verifies the acceptance bar directly: steady-state ``choose_quorum_for``
must do NO O(universe) work per op — counted, not timed: the O(V)
graph traversals (``get_disjoint_cliques``, ``get_reachable_nodes``,
``get_peers``) are instrumented and must not fire once the memos are
warm.
"""

from __future__ import annotations

import hashlib
import json
import time
from dataclasses import dataclass

from bftkv_tpu import quorum as q
from bftkv_tpu.graph import Graph
from bftkv_tpu.quorum.wotqs import WotQS

__all__ = [
    "SynthCert", "ChurnEvent", "synthetic_certs", "build_synthetic_graph",
    "churn_schedule", "apply_churn", "profile_universe", "main",
]

#: Synthetic ids live above 2^62 so a grafted REAL universe (random
#: 64-bit cert ids are overwhelmingly below this on test fixtures)
#: keeps the smallest ids — shard order, which sorts by min member id,
#: then puts real cliques first deterministically.
_SYNTH_ID_BASE = 1 << 62


class SynthCert:
    """A certificate-shaped principal without the cryptography: the
    trust graph only reads identity and the signer-id list."""

    __slots__ = ("id", "name", "address", "active", "_signers")

    def __init__(self, nid: int, name: str, address: str,
                 signers: list[int]):
        self.id = nid
        self.name = name
        self.address = address
        self.active = True
        self._signers = signers

    def signers(self) -> list[int]:
        return self._signers

    def serialize(self) -> bytes:
        return b"synth:%016x" % self.id

    def __repr__(self) -> str:  # pragma: no cover
        return f"SynthCert({self.name})"


def synthetic_certs(
    n_nodes: int, *, shard_size: int = 4, seed: int = 0,
    id_base: int = _SYNTH_ID_BASE,
) -> list[SynthCert]:
    """``n_nodes`` synthetic principals in disjoint cliques of
    ``shard_size``: every member's signer list is its clique peers, so
    ``Graph.add_nodes`` materializes the full bidirectional clique edge
    set.  Generation is streamed — O(n) time, O(n) memory, no pairwise
    scans.  A trailing partial clique below the b-masking floor (4) is
    still generated; ``get_disjoint_cliques(min_size=4)`` drops it."""
    if shard_size < 1:
        raise ValueError("shard_size must be >= 1")
    # A seed offset keeps distinct universes disjoint in id space.
    base = id_base + (seed % 4096) * (1 << 40)
    out: list[SynthCert] = []
    for c0 in range(0, n_nodes, shard_size):
        members = list(range(c0, min(c0 + shard_size, n_nodes)))
        ids = [base + m for m in members]
        for m, nid in zip(members, ids):
            out.append(SynthCert(
                nid,
                f"syn{seed}-{m}",
                f"syn://{m}",
                [i for i in ids if i != nid],
            ))
    return out


def build_synthetic_graph(
    n_nodes: int, *, shard_size: int = 4, seed: int = 0,
) -> tuple[Graph, list[SynthCert]]:
    """A standalone synthetic universe with the first node as self."""
    certs = synthetic_certs(n_nodes, shard_size=shard_size, seed=seed)
    g = Graph()
    g.set_self_nodes([certs[0]])
    g.add_peers(certs[1:])
    return g, certs


# -- churn / revocation-storm schedules ----------------------------------

@dataclass(frozen=True)
class ChurnEvent:
    t_s: float      # seconds from universe t0
    kind: str       # join | leave | revoke
    index: int      # node index (leave/revoke) or join sequence number


def churn_schedule(
    n_events: int, *, n_nodes: int, duration_s: float, seed: int = 0,
    storm_start_frac: float | None = None, storm_frac: float = 0.1,
    storm_revokes: int = 0,
) -> list[ChurnEvent]:
    """A deterministic membership-churn schedule: ``n_events`` draws of
    join/leave/revoke spread over the run, plus an optional revocation
    STORM (``storm_revokes`` revokes packed into a burst window) —
    the workload-event form of the §23 churn model.  Every draw is
    sha256(seed|churn|i); one seed replays one schedule."""
    events: list[ChurnEvent] = []
    kinds = ("join", "leave", "revoke")
    for i in range(n_events):
        h = hashlib.sha256(f"{seed}|churn|{i}".encode()).digest()
        u_t = int.from_bytes(h[:8], "big") / 2**64
        u_k = int.from_bytes(h[8:16], "big") / 2**64
        u_n = int.from_bytes(h[16:24], "big") / 2**64
        events.append(ChurnEvent(
            t_s=round(u_t * duration_s, 4),
            kind=kinds[int(u_k * len(kinds))],
            index=int(u_n * n_nodes),
        ))
    if storm_start_frac is not None and storm_revokes > 0:
        a = duration_s * storm_start_frac
        w = duration_s * storm_frac
        for i in range(storm_revokes):
            h = hashlib.sha256(f"{seed}|storm|{i}".encode()).digest()
            u_t = int.from_bytes(h[:8], "big") / 2**64
            u_n = int.from_bytes(h[8:16], "big") / 2**64
            events.append(ChurnEvent(
                t_s=round(a + u_t * w, 4),
                kind="revoke",
                index=int(u_n * n_nodes),
            ))
    events.sort(key=lambda e: (e.t_s, e.kind, e.index))
    return events


def apply_churn(
    graph: Graph, certs: list[SynthCert], ev: ChurnEvent, *,
    shard_size: int = 4, seed: int = 0,
) -> None:
    """Apply one schedule event to a live graph.  ``join`` adds a
    whole fresh clique (membership grows in quorum-capable units);
    ``leave`` removes a node; ``revoke`` revokes one.  Each bumps the
    graph generation — the memo-rebuild cost the profiler charges."""
    if ev.kind == "join":
        new = synthetic_certs(
            shard_size, shard_size=shard_size, seed=seed,
            id_base=_SYNTH_ID_BASE + (1 << 50) + ev.index * (1 << 20),
        )
        graph.add_peers(new)
        certs.extend(new)
    elif certs:
        target = certs[ev.index % len(certs)]
        if ev.kind == "leave":
            graph.remove_nodes([target])
        else:
            graph.revoke(target)


# -- the scaling profiler ------------------------------------------------

class _CallCounter:
    """Count invocations of the O(universe) graph traversals — the
    per-op acceptance oracle: once the generation-guard memos are warm,
    steady-state routing must not call any of these."""

    WRAPPED = ("get_disjoint_cliques", "get_reachable_nodes", "get_peers")

    def __init__(self, graph: Graph):
        self.graph = graph
        self.counts = {name: 0 for name in self.WRAPPED}
        self._orig: dict = {}

    def __enter__(self) -> "_CallCounter":
        for name in self.WRAPPED:
            orig = getattr(self.graph, name)
            self._orig[name] = orig

            def wrapped(*a, _n=name, _f=orig, **kw):
                self.counts[_n] += 1
                return _f(*a, **kw)

            setattr(self.graph, name, wrapped)
        return self

    def __exit__(self, *exc) -> None:
        for name, orig in self._orig.items():
            setattr(self.graph, name, orig)

    def total(self) -> int:
        return sum(self.counts.values())


def profile_universe(
    n_nodes: int, *, shard_size: int = 4, ops: int = 2000,
    churn_events: int = 4, seed: int = 0,
) -> dict:
    """Build an ``n_nodes`` synthetic universe and profile the routing
    plane at that size: graph build, clique discovery, ``_ShardTopo``
    build, steady-state ``choose_quorum_for`` per-op cost, and the
    amortized memo-rebuild cost under churn.  The per-op O(universe)
    check is counted (see :class:`_CallCounter`), not inferred from
    wall time."""
    t0 = time.perf_counter()
    graph, certs = build_synthetic_graph(
        n_nodes, shard_size=shard_size, seed=seed
    )
    build_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    cliques = graph.get_disjoint_cliques(min_size=4)
    cliques_s = time.perf_counter() - t0

    qs = WotQS(graph)
    t0 = time.perf_counter()
    topo = qs._topology()
    topo_s = time.perf_counter() - t0

    rw = q.WRITE
    # Warm the per-shard quorum memos on every bucket the op loop hits
    # (first touch of a shard pays its one-time quorum build; steady
    # state is what production serves and what the oracle counts).
    keys = [b"uni/%d/%d" % (seed, i) for i in range(ops)]
    for k in keys:
        qs.choose_quorum_for(k, rw)

    with _CallCounter(graph) as counter:
        t0 = time.perf_counter()
        for k in keys:
            qs.choose_quorum_for(k, rw)
        steady_s = time.perf_counter() - t0
    per_op_us = steady_s / max(ops, 1) * 1e6

    # Churn: each event invalidates the generation memos; the next op
    # pays one topology rebuild, every following op rides the memo.
    sched = churn_schedule(
        churn_events, n_nodes=len(certs), duration_s=1.0, seed=seed
    )
    t0 = time.perf_counter()
    rebuilds = 0
    for ev in sched:
        apply_churn(graph, certs, ev, shard_size=shard_size, seed=seed)
        qs.choose_quorum_for(b"uni/churn/%d" % rebuilds, rw)
        rebuilds += 1
    churn_s = time.perf_counter() - t0

    return {
        "n_nodes": n_nodes,
        "shard_size": shard_size,
        "n_cliques": len(cliques),
        "route_buckets": len(topo.table),
        "build_s": round(build_s, 3),
        "cliques_s": round(cliques_s, 3),
        "topo_s": round(topo_s, 3),
        "steady_ops": ops,
        "steady_per_op_us": round(per_op_us, 2),
        # The acceptance oracle: O(universe) traversals during the
        # steady window.  Must be 0.
        "o_universe_calls_steady": counter.total(),
        "o_universe_call_counts": counter.counts,
        "churn_events": rebuilds,
        "churn_rebuild_s_per_event": round(churn_s / max(rebuilds, 1), 3),
    }


def main(argv: list[str] | None = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="profile the routing plane at planet scale"
    )
    ap.add_argument("--nodes", type=int, default=10000)
    ap.add_argument("--shard-size", type=int, default=4)
    ap.add_argument("--ops", type=int, default=2000)
    ap.add_argument("--churn", type=int, default=4)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)
    res = profile_universe(
        args.nodes, shard_size=args.shard_size, ops=args.ops,
        churn_events=args.churn, seed=args.seed,
    )
    print(json.dumps(res, indent=1, sort_keys=True))
    return 0 if res["o_universe_calls_steady"] == 0 else 1


if __name__ == "__main__":
    raise SystemExit(main())
