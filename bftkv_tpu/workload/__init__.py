"""Production workload engine (DESIGN.md §23).

Three cooperating layers, all seeded-deterministic:

- :mod:`bftkv_tpu.workload.spec` — declarative :class:`WorkloadSpec`
  (op mix, key popularity, value sizes, arrival program), every
  probabilistic draw via the sha256(seed|stream|counter) discipline the
  faults registry already uses, so one seed replays one workload;
- :mod:`bftkv_tpu.workload.driver` — open-loop execution with
  coordinated-omission-corrected latency on the fleet-wide
  ``metrics.BUCKETS`` ladder, in-process (threads) and multi-process
  (worker processes over the HTTP transport), merged by bucket-vector
  summation;
- :mod:`bftkv_tpu.workload.universe` — planet-scale synthetic trust
  universes (10k–100k nodes) with churn / revocation-storm schedules
  and the scaling profiler.
"""

from bftkv_tpu.workload.spec import (  # noqa: F401
    OP_KINDS,
    Op,
    PRESETS,
    WorkloadSpec,
    parse_spec,
)
from bftkv_tpu.workload.driver import (  # noqa: F401
    LatencyHist,
    OpenLoop,
    merge_reports,
    run_in_process,
    run_multiprocess,
)
