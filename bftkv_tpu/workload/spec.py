"""Declarative, seeded-deterministic workload specifications.

A :class:`WorkloadSpec` is a pure description: op mix (read / write /
scan / write_many / gateway-read ratios), key-popularity model
(uniform, zipf, bounded hot set with churn), value-size distribution
(fixed / lognormal) and arrival program (constant open-loop rate,
diurnal ramp, hot-key storm burst, step overload).  Every
probabilistic draw is a pure function of ``(seed, stream, counter)``
through sha256 — the same discipline as ``faults.failpoint._draws`` —
so one seed replays one workload bit-for-bit, across runs AND across
worker counts.

The op stream is indexed by a GLOBAL op index ``g``: worker ``ci`` of
``W`` executes indices ``ci, ci+W, ci+2W, …``, so re-partitioning the
same spec over a different worker count permutes nothing — the op at
index ``g`` (kind, key, size, due time) is identical.  TOFU safety
rides the same arithmetic: a key's owner slot is ``g % owners`` and a
worker count that divides ``owners`` maps every owner slot to exactly
one worker identity (``g ≡ o (mod owners)`` ⇒ ``g ≡ o (mod W)``), so
no variable is ever written by two identities.

Arrival programs compile to a short piecewise-constant segment list
(duration, rate); an op's due time is resolved by walking the ≤10
segments — O(1) per op, never O(ops).
"""

from __future__ import annotations

import hashlib
import math
from dataclasses import dataclass, fields
from statistics import NormalDist

__all__ = ["OP_KINDS", "Op", "PRESETS", "WorkloadSpec", "flag_overrides",
           "parse_spec"]

#: The closed op-kind enum, in cumulative-draw order.
OP_KINDS = ("write", "read", "scan", "write_many", "gateway_read")

_NORM = NormalDist()


def _uniforms(seed: int, stream: str, counter: int) -> tuple:
    """Four uniforms in [0, 1), a pure function of (seed, stream,
    counter) — the faults-registry draw discipline."""
    h = hashlib.sha256(f"{seed}|{stream}|{counter}".encode()).digest()
    return tuple(
        int.from_bytes(h[8 * i:8 * i + 8], "big") / 2**64 for i in range(4)
    )


@dataclass(frozen=True)
class Op:
    """One scheduled operation: everything the driver needs, resolved
    from the global index alone."""

    index: int
    due_s: float       # scheduled start, seconds from workload t0
    kind: str          # one of OP_KINDS
    owner: int         # owner slot (g % owners): the writing identity
    rank: int          # key rank within the popularity model
    size: int          # value bytes (writes; 0 for reads)


@dataclass
class WorkloadSpec:
    """One workload, fully described.  Mutating a spec after handing it
    to a driver is unsupported (lazy caches assume immutability)."""

    name: str = "custom"
    seed: int = 0
    # -- op mix (weights; normalized, order = OP_KINDS) -------------------
    write: float = 1.0
    read: float = 0.0
    scan: float = 0.0
    write_many: float = 0.0
    gateway_read: float = 0.0
    # -- key popularity ---------------------------------------------------
    keys: str = "uniform"        # uniform | zipf | hotset
    keyspace: int = 512          # ranks per spec (shared namespace)
    zipf_s: float = 1.1
    hot_keys: int = 4            # hotset: bounded hot-set size
    hot_frac: float = 0.9        # hotset: P(draw lands in the hot set)
    churn_every: int = 0         # hotset: ops per hot-set rotation (0=never)
    # -- value sizes ------------------------------------------------------
    values: str = "fixed"        # fixed | lognormal
    value_size: int = 256
    lognorm_mu: float = 5.5      # ln(bytes); e^5.5 ≈ 245 B median
    lognorm_sigma: float = 1.0
    size_min: int = 16
    size_max: int = 65536
    # -- arrival program --------------------------------------------------
    arrival: str = "constant"    # constant | ramp | storm | step
    rate: float = 50.0           # baseline offered ops/s
    duration_s: float = 5.0
    ramp_peak_x: float = 3.0     # ramp: peak rate multiplier (diurnal)
    ramp_steps: int = 8
    storm_start_frac: float = 0.4
    storm_frac: float = 0.2      # storm window, as fractions of duration
    storm_x: float = 4.0         # storm: rate multiplier in the window
    step_at_frac: float = 0.5
    step_x: float = 3.0          # step: overload multiplier after step_at
    # -- structure --------------------------------------------------------
    owners: int = 16             # logical writer-identity slots
    scan_width: int = 4          # keys per scan (read_many)
    wm_batch: int = 3            # items per write_many

    def __post_init__(self):
        if self.keys not in ("uniform", "zipf", "hotset"):
            raise ValueError(f"unknown key model {self.keys!r}")
        if self.values not in ("fixed", "lognormal"):
            raise ValueError(f"unknown value model {self.values!r}")
        if self.arrival not in ("constant", "ramp", "storm", "step"):
            raise ValueError(f"unknown arrival program {self.arrival!r}")
        if self.rate <= 0 or self.duration_s <= 0:
            raise ValueError("rate and duration_s must be positive")
        if self.owners < 1 or self.keyspace < 1:
            raise ValueError("owners and keyspace must be >= 1")
        if abs(self.write + self.read + self.scan + self.write_many
               + self.gateway_read) < 1e-12:
            raise ValueError("op mix is all-zero")
        self._segments: list | None = None
        self._zipf_cdf: list | None = None
        self._hot_cache: tuple | None = None  # (epoch, ranks)

    # -- op mix -----------------------------------------------------------

    def mix_cdf(self) -> tuple:
        w = [getattr(self, k) for k in OP_KINDS]
        total = sum(w)
        acc, out = 0.0, []
        for x in w:
            acc += x / total
            out.append(acc)
        out[-1] = 1.0
        return tuple(out)

    # -- arrival ----------------------------------------------------------

    def segments(self) -> list:
        """Piecewise-constant arrival program:
        ``[(t_start, duration, rate, first_op_index), …]``."""
        if self._segments is not None:
            return self._segments
        d, r = self.duration_s, self.rate
        if self.arrival == "constant":
            raw = [(d, r)]
        elif self.arrival == "ramp":
            # Diurnal half-sine: rate ramps baseline → peak → baseline.
            n = max(self.ramp_steps, 2)
            raw = []
            for i in range(n):
                m = 1.0 + (self.ramp_peak_x - 1.0) * math.sin(
                    math.pi * (i + 0.5) / n
                )
                raw.append((d / n, r * m))
        elif self.arrival == "storm":
            a = d * self.storm_start_frac
            b = d * self.storm_frac
            raw = [(a, r), (b, r * self.storm_x), (d - a - b, r)]
        else:  # step overload
            a = d * self.step_at_frac
            raw = [(a, r), (d - a, r * self.step_x)]
        segs, t, n0 = [], 0.0, 0.0
        for dur, rate in raw:
            if dur <= 0:
                continue
            segs.append((t, dur, rate, n0))
            t += dur
            n0 += dur * rate
        self._segments = segs
        return segs

    def total_ops(self) -> int:
        segs = self.segments()
        t, dur, rate, n0 = segs[-1]
        return int(n0 + dur * rate)

    def mean_rate(self) -> float:
        return round(self.total_ops() / self.duration_s, 2)

    def due(self, g: int) -> float:
        """Scheduled start of op ``g`` (seconds from t0) — walks the
        ≤10 arrival segments, O(1) per op."""
        segs = self.segments()
        for t, dur, rate, n0 in reversed(segs):
            if g >= n0:
                return t + (g - n0) / rate
        t, dur, rate, n0 = segs[0]
        return t + g / rate

    def in_storm(self, t: float) -> bool:
        if self.arrival != "storm":
            return False
        a = self.duration_s * self.storm_start_frac
        return a <= t < a + self.duration_s * self.storm_frac

    # -- key popularity ---------------------------------------------------

    def _zipf_rank(self, u: float) -> int:
        if self._zipf_cdf is None:
            p = [1.0 / (i + 1) ** self.zipf_s for i in range(self.keyspace)]
            total = sum(p)
            acc, cdf = 0.0, []
            for x in p:
                acc += x / total
                cdf.append(acc)
            cdf[-1] = 1.0
            self._zipf_cdf = cdf
        # Binary search: popularity rank 0 is the hottest key.
        cdf = self._zipf_cdf
        lo, hi = 0, len(cdf) - 1
        while lo < hi:
            mid = (lo + hi) // 2
            if u <= cdf[mid]:
                hi = mid
            else:
                lo = mid + 1
        return lo

    def hot_set(self, epoch: int) -> list[int]:
        """The bounded hot set of ``epoch`` — churn rotates epochs every
        ``churn_every`` ops.  Deterministic, cached for the last epoch."""
        if self._hot_cache is not None and self._hot_cache[0] == epoch:
            return self._hot_cache[1]
        ranks, j = [], 0
        while len(ranks) < min(self.hot_keys, self.keyspace):
            h = hashlib.sha256(
                f"{self.seed}|hotset|{epoch}|{j}".encode()
            ).digest()
            r = int.from_bytes(h[:8], "big") % self.keyspace
            j += 1
            if r not in ranks:
                ranks.append(r)
        self._hot_cache = (epoch, ranks)
        return ranks

    def _rank(self, g: int, due: float, u_key: float, u_hot: float) -> int:
        if self.keys == "zipf":
            return self._zipf_rank(u_key)
        if self.keys == "hotset":
            epoch = g // self.churn_every if self.churn_every > 0 else 0
            # A storm burst concentrates on the hot set entirely.
            frac = 1.0 if self.in_storm(due) else self.hot_frac
            if u_hot < frac:
                hot = self.hot_set(epoch)
                return hot[int(u_key * len(hot))]
        return int(u_key * self.keyspace)

    # -- value sizes ------------------------------------------------------

    def _size(self, u: float) -> int:
        if self.values == "fixed":
            return self.value_size
        # Clamp the uniform off the exact 0/1 poles (inv_cdf is ±inf).
        u = min(max(u, 1e-12), 1.0 - 1e-12)
        b = math.exp(self.lognorm_mu + self.lognorm_sigma * _NORM.inv_cdf(u))
        return max(self.size_min, min(self.size_max, int(b)))

    # -- the op stream ----------------------------------------------------

    def op_at(self, g: int) -> Op:
        u_kind, u_key, u_size, u_hot = _uniforms(self.seed, "op", g)
        cdf = self.mix_cdf()
        kind = OP_KINDS[-1]
        for i, c in enumerate(cdf):
            if u_kind <= c:
                kind = OP_KINDS[i]
                break
        due = self.due(g)
        size = self._size(u_size) if kind in ("write", "write_many") else 0
        return Op(
            index=g,
            due_s=due,
            kind=kind,
            owner=g % self.owners,
            rank=self._rank(g, due, u_key, u_hot),
            size=size,
        )

    def iter_ops(self, start: int = 0, stride: int = 1, limit=None):
        """Worker ``start`` of ``stride``'s slice of the stream: ops
        ``start, start+stride, …`` up to the arrival program's total
        (or ``limit`` ops from this slice)."""
        total = self.total_ops()
        g, done = start, 0
        while g < total and (limit is None or done < limit):
            yield self.op_at(g)
            g += stride
            done += 1

    def key_bytes(self, owner: int, rank: int) -> bytes:
        """Concrete variable name.  The spec name partitions presets
        into disjoint TOFU namespaces; the owner slot pins each key to
        one writing identity."""
        return b"wl/%s/%d/%d" % (self.name.encode(), owner, rank % self.keyspace)

    # -- serialization ----------------------------------------------------

    def canonical(self) -> str:
        """Full ``k=v,…`` string: parses back to an identical spec —
        the subprocess handoff format."""
        out = []
        for f in fields(self):
            v = getattr(self, f.name)
            out.append(f"{f.name}={v}")
        return ",".join(out)

    @classmethod
    def preset(cls, name: str, **over) -> "WorkloadSpec":
        base = PRESETS.get(name)
        if base is None:
            raise ValueError(
                f"unknown workload preset {name!r} "
                f"(have: {', '.join(sorted(PRESETS))})"
            )
        kw = dict(base)
        kw.update(over)
        kw.setdefault("name", name)
        return cls(**kw)


#: Named presets — the bench / nemesis / CLI vocabulary.
PRESETS: dict = {
    # Production read-dominant mix: zipf-popular keys, lognormal values.
    "read_heavy": dict(
        read=0.85, write=0.08, scan=0.03, write_many=0.02,
        gateway_read=0.02, keys="zipf", zipf_s=1.1,
        values="lognormal",
    ),
    # Ingest-dominant mix with batched writes.
    "write_heavy": dict(
        write=0.70, read=0.20, scan=0.02, write_many=0.06,
        gateway_read=0.02, keys="zipf", zipf_s=0.9, value_size=512,
    ),
    # Hot-key storm: a bounded churning hot set, plus a mid-run burst
    # window where the rate multiplies AND every draw lands hot.
    "storm": dict(
        write=0.55, read=0.40, scan=0.02, write_many=0.03,
        keys="hotset", hot_keys=4, hot_frac=0.5, churn_every=64,
        arrival="storm", storm_x=4.0,
    ),
    # Diurnal ramp: baseline → 3x peak → baseline over the run.
    "ramp": dict(
        write=0.40, read=0.55, scan=0.03, write_many=0.02,
        arrival="ramp", ramp_peak_x=3.0,
    ),
    # Write-only constant-rate preset: the cluster_shards fixed-load
    # driver (uniform per-owner keys — no hot-key TOFU races, so the
    # scaling ratio measures sharding, not conflict retries).
    "shards": dict(write=1.0, keys="uniform"),
}

_FIELD_TYPES = {f.name: f.type for f in fields(WorkloadSpec)}


def parse_spec(s: str) -> WorkloadSpec:
    """Parse ``"preset[,k=v,…]"`` or ``"k=v,…"`` into a spec.

    The first comma token may name a preset; every following ``k=v``
    overrides a :class:`WorkloadSpec` field (typed by the dataclass).
    ``parse_spec(spec.canonical())`` round-trips."""
    parts = [p.strip() for p in s.split(",") if p.strip()]
    if not parts:
        raise ValueError("empty workload spec")
    over: dict = {}
    rest = parts
    preset = None
    if "=" not in parts[0]:
        preset = parts[0]
        rest = parts[1:]
    for p in rest:
        if "=" not in p:
            raise ValueError(f"workload spec token {p!r} is not k=v")
        k, v = p.split("=", 1)
        k = k.strip()
        t = _FIELD_TYPES.get(k)
        if t is None:
            raise ValueError(f"unknown workload spec field {k!r}")
        if t in ("int", int):
            over[k] = int(v)
        elif t in ("float", float):
            over[k] = float(v)
        else:
            over[k] = v
    if preset is not None:
        return WorkloadSpec.preset(preset, **over)
    return WorkloadSpec(**over)


def flag_overrides() -> dict:
    """The ``BFTKV_WORKLOAD_SEED`` / ``BFTKV_WORKLOAD_RATE`` /
    ``BFTKV_WORKLOAD_DURATION`` env knobs (flags.py, "Workload
    engine"), resolved into spec-field overrides.  One read path for
    every consumer — the bench sections splice the returned dict over
    their per-section defaults, so an operator can re-seed or re-rate
    a round without editing configs.  Unset flags are absent from the
    dict (callers keep their defaults)."""
    from bftkv_tpu import flags

    over: dict = {}
    seed = flags.get_int("BFTKV_WORKLOAD_SEED")
    if seed is not None:
        over["seed"] = seed
    rate = flags.get_float("BFTKV_WORKLOAD_RATE")
    if rate is not None:
        over["rate"] = rate
    duration = flags.get_float("BFTKV_WORKLOAD_DURATION")
    if duration is not None:
        over["duration_s"] = duration
    return over
