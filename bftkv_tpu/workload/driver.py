"""Workload execution: open-loop scheduling, CO-corrected latency,
in-process and multi-process drivers.

Latency discipline (the coordinated-omission correction): every op has
a SCHEDULED start from the spec's arrival program, and its recorded
latency runs from that due time — a backed-up system shows its queueing
delay instead of quietly slowing the offered load the way a closed
loop does.  When the scheduler itself falls behind (sustained
overload), the op still charges from its due time AND the backlog is
reported (``ops_behind``, ``max_sched_lag_s``) — never silently
absorbed.

Latencies land on the fleet-wide ``metrics.BUCKETS`` ladder
(:class:`LatencyHist`), so per-worker and per-process histograms merge
by bucket-vector summation into one offered/achieved/p50/p99 report —
the same fixed-ladder design the fleet collector uses (per-worker
sample quantiles cannot be merged; the p99 of a set of p99s is
meaningless).

The multi-process driver escapes the in-process GIL wall (PR 11): each
worker is its own interpreter with its own identity (a saved home
directory), talking to the cluster over the real HTTP transport.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

from bftkv_tpu.metrics import BUCKETS, histogram_quantile
from bftkv_tpu.workload.spec import WorkloadSpec, parse_spec

__all__ = [
    "LatencyHist", "OpenLoop", "Pacer", "execute_op", "merge_reports",
    "run_in_process", "run_multiprocess",
]


class Pacer:
    """Wall-clock gate for scheduled ops, with backlog accounting.

    ``wait_until(due_s, ci)`` sleeps until ``t0 + due_s`` and returns
    the absolute due time.  A worker arriving LATE does not sleep —
    the op runs immediately, its latency is still measured from the
    scheduled start, and the scheduling lag is recorded per worker
    (plain per-slot writes: no lock needed, merged on read).  Lag
    under 1 ms is scheduler noise (op 0 is due exactly at t0), not
    backlog."""

    GRACE_S = 1e-3

    def __init__(self, workers: int, t0: float | None = None):
        self.t0 = time.perf_counter() if t0 is None else t0
        self._behind = [0] * workers
        self._lag = [0.0] * workers

    def wait_until(self, due_s: float, ci: int = 0) -> float:
        due = self.t0 + due_s
        delay = due - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        elif delay < -self.GRACE_S:
            self._behind[ci] += 1
            if -delay > self._lag[ci]:
                self._lag[ci] = -delay
        return due

    def backlog(self) -> dict:
        return {
            "ops_behind": sum(self._behind),
            "max_sched_lag_s": round(max(self._lag), 4),
        }


class OpenLoop:
    """Constant-rate open-loop schedule for one worker pool: ``rate``
    ops/s spread evenly over ``workers`` workers; worker ``ci``'s
    ``k``-th op is DUE at ``t0 + (k·workers + ci)/rate``.  The bench
    harness's historical ``_OpenLoop``, now with the :class:`Pacer`
    backlog accounting — at sustained overload the scheduler reports
    how far behind it ran instead of silently absorbing it."""

    def __init__(self, rate: float, workers: int):
        self.rate = rate
        self.workers = workers
        self._pacer = Pacer(workers)

    @property
    def t0(self) -> float:
        return self._pacer.t0

    def due(self, ci: int, k: int) -> float:
        return self.t0 + (k * self.workers + ci) / self.rate

    def wait(self, ci: int, k: int) -> float:
        """Sleep until op (ci, k) is due; returns the due time (the
        latency measurement origin, behind or not)."""
        return self._pacer.wait_until(
            (k * self.workers + ci) / self.rate, ci
        )

    def backlog(self) -> dict:
        return self._pacer.backlog()


class LatencyHist:
    """Fixed-ladder latency histogram on ``metrics.BUCKETS`` — the
    mergeable unit of the multi-process report."""

    __slots__ = ("counts", "n", "total")

    def __init__(self, counts=None, n: int = 0, total: float = 0.0):
        self.counts = list(counts) if counts else [0] * (len(BUCKETS) + 1)
        if len(self.counts) != len(BUCKETS) + 1:
            raise ValueError("bucket vector does not match the ladder")
        self.n = n
        self.total = total

    def observe(self, v: float) -> None:
        lo, hi = 0, len(BUCKETS)
        while lo < hi:
            mid = (lo + hi) // 2
            if v <= BUCKETS[mid]:
                hi = mid
            else:
                lo = mid + 1
        self.counts[lo] += 1
        self.n += 1
        self.total += v

    def merge(self, other: "LatencyHist") -> "LatencyHist":
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        self.n += other.n
        self.total += other.total
        return self

    def quantile(self, q: float):
        return histogram_quantile(q, self.counts)

    def mean(self) -> float:
        return self.total / self.n if self.n else 0.0

    def to_dict(self) -> dict:
        return {"counts": self.counts, "n": self.n,
                "total": round(self.total, 6)}

    @classmethod
    def from_dict(cls, d: dict) -> "LatencyHist":
        return cls(d["counts"], d["n"], d["total"])


def execute_op(client, spec: WorkloadSpec, op, blob: bytes,
               gateway=None) -> str:
    """Run one op against ``client``; returns the kind actually
    executed (``gateway_read`` degrades to ``read`` without a
    gateway).  Values are slices of ``blob`` offset by the op index —
    cheap, size-exact, content-irrelevant."""
    key = spec.key_bytes(op.owner, op.rank)
    if op.kind == "write":
        off = op.index % max(len(blob) - op.size, 1)
        client.write(key, blob[off:off + op.size])
        return "write"
    if op.kind == "write_many":
        nb = min(spec.wm_batch, spec.keyspace)
        off = op.index % max(len(blob) - op.size, 1)
        val = blob[off:off + op.size]
        items = [
            (spec.key_bytes(op.owner, op.rank + j), val) for j in range(nb)
        ]
        res = client.write_many(items)
        errs = [e for e in res if e is not None]
        if errs:
            raise errs[0]
        return "write_many"
    if op.kind == "scan":
        keys = [
            spec.key_bytes(op.owner, op.rank + j)
            for j in range(min(spec.scan_width, spec.keyspace))
        ]
        client.read_many(keys)
        return "scan"
    if op.kind == "gateway_read" and gateway is not None:
        gateway.read(key)
        return "gateway_read"
    client.read(key)
    return "read"


def _run_slice(
    spec: WorkloadSpec, client, ci: int, stride: int, pacer: Pacer,
    hist: LatencyHist, kinds: dict, errors: list, blob: bytes,
    gateway=None, max_ops=None,
) -> int:
    """Worker ``ci``'s slice of the global op stream.  Returns the op
    count executed (errors included — an errored op still consumed its
    arrival slot)."""
    done = 0
    for op in spec.iter_ops(ci, stride, max_ops):
        due = pacer.wait_until(op.due_s, ci)
        try:
            kind = execute_op(client, spec, op, blob, gateway)
            kinds[kind] = kinds.get(kind, 0) + 1
        except Exception as e:
            if len(errors) < 8:
                errors.append(f"{op.kind}@{op.index}: "
                              f"{type(e).__name__}: {e}")
            kinds["error"] = kinds.get("error", 0) + 1
        hist.observe(time.perf_counter() - due)
        done += 1
    return done


def _report(spec: WorkloadSpec, hist: LatencyHist, kinds: dict,
            errors: list, backlog: dict, elapsed: float, done: int,
            workers: int, mode: str) -> dict:
    return {
        "spec": spec.canonical(),
        "preset": spec.name,
        "mode": mode,
        "workers": workers,
        "offered_rate_per_sec": spec.mean_rate(),
        "offered_ops": done,
        "achieved_rate_per_sec": round(done / elapsed, 2) if elapsed else 0,
        "elapsed_s": round(elapsed, 3),
        # Ladder quantiles measured from each op's SCHEDULED start —
        # bucket upper bounds, mergeable across processes.
        "p50_offered_s": hist.quantile(0.5) or 0,
        "p99_offered_s": hist.quantile(0.99) or 0,
        "mean_offered_s": round(hist.mean(), 4),
        "lat_buckets": list(hist.counts),
        "ops": dict(sorted(kinds.items())),
        "errors": kinds.get("error", 0),
        "error_samples": errors,
        "backlog": backlog,
    }


def run_in_process(
    spec: WorkloadSpec, clients: list, *, workers: int | None = None,
    gateway=None, max_ops_per_worker=None,
) -> dict:
    """Drive ``spec`` with ``workers`` threads over in-process clients
    (worker ``ci`` owns every owner slot ≡ ci mod workers, so TOFU
    ownership is single-writer by construction)."""
    w = workers or len(clients)
    if w < 1 or w > len(clients):
        raise ValueError(f"workers={w} outside 1..{len(clients)}")
    if spec.owners % w:
        raise ValueError(
            f"worker count {w} must divide spec.owners={spec.owners} "
            "(owner→identity stability across worker counts)"
        )
    blob = os.urandom(spec.size_max + 1)
    pacer = Pacer(w)
    hists = [LatencyHist() for _ in range(w)]
    kinds: list[dict] = [{} for _ in range(w)]
    errors: list[list] = [[] for _ in range(w)]
    counts = [0] * w

    def run(ci: int) -> None:
        counts[ci] = _run_slice(
            spec, clients[ci], ci, w, pacer, hists[ci], kinds[ci],
            errors[ci], blob, gateway, max_ops_per_worker,
        )

    threads = [
        threading.Thread(target=run, args=(ci,), daemon=True)
        for ci in range(w)
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.perf_counter() - t0
    hist = LatencyHist()
    all_kinds: dict = {}
    all_errors: list = []
    for ci in range(w):
        hist.merge(hists[ci])
        for k, v in kinds[ci].items():
            all_kinds[k] = all_kinds.get(k, 0) + v
        all_errors.extend(errors[ci][: max(0, 8 - len(all_errors))])
    return _report(
        spec, hist, all_kinds, all_errors, pacer.backlog(), elapsed,
        sum(counts), w, "in_process",
    )


def merge_reports(reports: list[dict], spec: WorkloadSpec,
                  workers: int) -> dict:
    """Fleet merge: bucket-vector summation across worker processes.
    Quantiles come from the merged vector — identical to a
    single-stream histogram of the same observations (the fixed-ladder
    merge law the tests pin down)."""
    hist = LatencyHist()
    kinds: dict = {}
    errors: list = []
    done = 0
    elapsed = 0.0
    behind, lag = 0, 0.0
    for r in reports:
        hist.merge(LatencyHist(r["lat_buckets"],
                               sum(r["lat_buckets"]), 0.0))
        hist.total += r.get("lat_total_s", 0.0)
        for k, v in r.get("ops", {}).items():
            kinds[k] = kinds.get(k, 0) + v
        errors.extend(r.get("error_samples", [])[: max(0, 8 - len(errors))])
        done += r.get("offered_ops", 0)
        elapsed = max(elapsed, r.get("elapsed_s", 0.0))
        b = r.get("backlog", {})
        behind += b.get("ops_behind", 0)
        lag = max(lag, b.get("max_sched_lag_s", 0.0))
    hist.n = sum(hist.counts)
    return _report(
        spec, hist, kinds, errors,
        {"ops_behind": behind, "max_sched_lag_s": round(lag, 4)},
        elapsed, done, workers, "multi_process",
    )


def run_multiprocess(
    spec: WorkloadSpec, cluster, homes_dir: str, *, procs: int | None = None,
    timeout_s: float = 300.0,
) -> dict:
    """Drive ``spec`` with ``procs`` WORKER PROCESSES over the HTTP
    transport against a running cluster (tests/cluster_utils shape,
    ``transport="http"``, at least ``procs`` users).

    Each worker loads its own saved home (its identity + the full
    certificate view), builds a real client, and executes its slice of
    the same global op stream; the parent merges the per-process
    bucket vectors.  This is the GIL escape: interpreter-parallel
    clients, one offered-load schedule.

    ``procs=None`` reads the ``BFTKV_WORKLOAD_PROCS`` flag (default 2)
    — the operator knob for sizing the driver pair to the box."""
    from bftkv_tpu import flags, topology

    if procs is None:
        procs = flags.get_int("BFTKV_WORKLOAD_PROCS") or 2
    uni = cluster.universe
    if len(uni.users) < procs:
        raise ValueError(f"cluster has {len(uni.users)} users < {procs}")
    if spec.owners % procs:
        raise ValueError(
            f"procs={procs} must divide spec.owners={spec.owners}"
        )
    homes = []
    for i in range(procs):
        ident = uni.users[i]
        home = os.path.join(homes_dir, f"worker{i}")
        if not os.path.isdir(home):
            topology.save_home(home, ident, uni.view_of(ident))
        homes.append(home)
    start_at = time.time() + 2.0 + 0.25 * procs  # overlap gate
    outs, children = [], []
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    for i, home in enumerate(homes):
        out = os.path.join(homes_dir, f"worker{i}.json")
        outs.append(out)
        children.append(subprocess.Popen(
            [
                sys.executable, "-m", "bftkv_tpu.workload.driver",
                "--home", home, "--spec", spec.canonical(),
                "--worker", str(i), "--workers", str(procs),
                "--start-at", str(start_at), "--out", out,
            ],
            env=env,
        ))
    reports = []
    for child, out in zip(children, outs):
        try:
            child.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            child.kill()
            child.wait()
        try:
            with open(out) as f:
                reports.append(json.load(f))
        except Exception:
            pass
    if not reports:
        raise RuntimeError("every workload worker process failed")
    merged = merge_reports(reports, spec, procs)
    merged["worker_reports"] = len(reports)
    return merged


def _worker_main(argv: list[str]) -> None:
    """One worker process: load home, dial the cluster, run the slice."""
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--home", required=True)
    ap.add_argument("--spec", required=True)
    ap.add_argument("--worker", type=int, required=True)
    ap.add_argument("--workers", type=int, required=True)
    ap.add_argument("--start-at", type=float, default=0.0)
    ap.add_argument("--out", required=True)
    ap.add_argument("--max-ops", type=int, default=0)
    args = ap.parse_args(argv)

    from bftkv_tpu import topology
    from bftkv_tpu.ops import dispatch
    from bftkv_tpu.protocol.client import Client
    from bftkv_tpu.transport.http import TrHTTP

    # Dispatcher parity with the in-process harness: each worker
    # interpreter batches its own signs/verifies, so the thread-vs-
    # process pair measures interpreter parallelism, not a missing
    # batching plane in the children.
    dispatch.install(dispatch.VerifyDispatcher(max_batch=256))
    dispatch.install_signer(dispatch.SignDispatcher(max_batch=128))
    spec = parse_spec(args.spec)
    graph, crypt, qs = topology.load_home(args.home)
    tr = TrHTTP(crypt)
    tr.link_id = graph.name
    client = Client(graph, qs, tr, crypt)
    blob = os.urandom(spec.size_max + 1)
    # Warm transport sessions + route caches outside the window (the
    # bench warmup rule: bootstrap envelopes are connection setup, not
    # steady-state op cost).  The warm key is owner-slot-correct for
    # this worker, so TOFU stays single-writer.
    warm = spec.key_bytes(args.worker % spec.owners, 0)
    try:
        client.write(warm, b"warm")
        client.read(warm)
    except Exception:
        pass
    if hasattr(client, "drain_tails"):
        client.drain_tails()
    now = time.time()
    if args.start_at > now:
        time.sleep(args.start_at - now)
    # Full-width slot array: _run_slice indexes the pacer by the
    # GLOBAL worker index, same as the in-process thread pool.
    pacer = Pacer(args.workers)
    hist = LatencyHist()
    kinds: dict = {}
    errors: list = []
    t0 = time.perf_counter()
    done = _run_slice(
        spec, client, args.worker, args.workers, pacer, hist, kinds,
        errors, blob, None, args.max_ops or None,
    )
    elapsed = time.perf_counter() - t0
    if hasattr(client, "drain_tails"):
        client.drain_tails()
    rep = _report(spec, hist, kinds, errors, pacer.backlog(), elapsed,
                  done, 1, "worker")
    rep["lat_total_s"] = round(hist.total, 6)
    with open(args.out, "w") as f:
        json.dump(rep, f)
    tr.stop()


if __name__ == "__main__":
    _worker_main(sys.argv[1:])
