"""Residue-preserving compaction for the log engine (DESIGN.md §19.3).

An append-only log accumulates dead bytes two ways: the same
``(variable, t)`` rewritten in place (every piggybacked write persists
a pending record that the async back-fill later overwrites with the
certified bytes — §12), and pending residue superseded by a newer
certified version.  Compaction rewrites the sealed segments keeping
only what the protocol can still need:

- the **latest version of every variable** — certified or not:
  uncertified latest residue is exactly what the repair daemon
  certifies-or-demotes (§13), and §10.4's inert stale copies (records
  a routing change stranded here) must stay serveable for migration
  pulls, so compaction is deliberately shard-blind;
- **every certified version** — the read path scans back to them past
  in-progress sign records, and explicit ``read(variable, t)`` serves
  certified history;
- **anything that is not a syncable protocol record** — unparsable
  bytes, TPA-protected (``auth``) records, hidden threshold-CA shares,
  legacy sign-phase shapes without ``ss``: the compactor never guesses
  about bytes it does not understand;
- DROPPED: a **pending** version (partial collective signature)
  strictly below a newer **certified** version of the same variable —
  §12's certified-beats-residue rule says such residue can never be
  upgraded into serving state again (``_stale_version_upgrade``
  declines it), so it is unreachable by every read/repair/sync path.

Crash safety: survivors stream into a ``.tmp``, fsync, then one rename
publishes the compacted segment; the input segments are unlinked only
after.  A crash between rename and unlink leaves both — segment.py's
open-time supersede rule deletes the covered inputs (idempotent).
"""

from __future__ import annotations

import os
import time

from bftkv_tpu import flags
from bftkv_tpu import packet as pkt
from bftkv_tpu.errors import ERR_NOT_FOUND
from bftkv_tpu.metrics import registry as metrics
from bftkv_tpu.storage import segment as seg

__all__ = ["compact_store"]


class _RateGovernor:
    """Token-bucket IO governor for the compactor's copy loop.

    ``BFTKV_LOG_COMPACT_MBPS`` caps the sustained copy rate: each
    record written debits its bytes, and whenever the copy runs ahead
    of the configured rate the compactor sleeps off the surplus —
    between record copies, never while holding the store lock, so
    foreground writes and the fsync barrier keep their own pace while
    compaction IO stops competing with them for the disk.  Unset or 0
    = ungoverned (the pre-governor behaviour).  Throttle sleeps are
    observable (``storage.compact.throttle``) so a governed compaction
    that can't keep up with dead-byte accrual shows as compact_io
    saturation in the capacity plane rather than as mystery latency.
    """

    def __init__(self, mbps: float | None):
        self.rate = max(0.0, (mbps or 0.0)) * 1024 * 1024
        self._t0 = time.monotonic()
        self._bytes = 0

    def debit(self, n: int) -> None:
        if self.rate <= 0:
            return
        self._bytes += n
        ahead = self._bytes / self.rate - (time.monotonic() - self._t0)
        if ahead > 0.001:
            metrics.observe("storage.compact.throttle", ahead)
            time.sleep(ahead)


def _max_certified(store, variable: bytes, cache: dict) -> int | None:
    """Newest version of ``variable`` whose stored record carries a
    completed collective signature, or None — the §12 bar a pending
    version must be UNDER for compaction to drop it."""
    if variable in cache:
        return cache[variable]
    best = None
    for t in sorted(store.versions(variable), reverse=True):
        try:
            raw = store.read(variable, t)
        except ERR_NOT_FOUND:
            continue
        try:
            p = pkt.parse(raw)
        except Exception:
            continue  # non-record bytes cannot certify anything
        if p.ss is not None and p.ss.completed:
            best = t
            break
    cache[variable] = best
    return best


def _keep(store, variable: bytes, t: int, value: bytes, cache: dict) -> bool:
    ts = store.versions(variable)
    if ts and t == ts[-1]:
        return True  # latest version always survives (incl. residue)
    try:
        p = pkt.parse(value)
    except Exception:
        return True  # not a protocol record: never the compactor's call
    if p.auth is not None or p.ss is None:
        return True  # TPA-protected / legacy shape: conservative
    if p.ss.completed:
        return True  # certified history stays readable
    mc = _max_certified(store, variable, cache)
    return mc is None or mc < t


def compact_store(store) -> dict:
    """Rewrite the sealed segments of a LogStorage into one compacted
    segment, dropping dead copies and §12-reclaimable pending residue.
    Runs concurrently with writes: a record whose index entry moved
    mid-flight is simply left where the index says it is."""
    with store._lock:
        inputs = sorted(
            (fkey, p)
            for fkey, p in store._paths.items()
            if p != store._active_path
        )
    if not inputs:
        return {"inputs": 0, "kept": 0, "dropped": 0, "reclaimed_bytes": 0}

    first = min(fk[0] for fk, _p in inputs)
    # parse_segment_name gives the true covered range for compacted
    # inputs; plain inputs cover just their own seq.
    last = max(
        seg.parse_segment_name(os.path.basename(p))[1] for _fk, p in inputs
    )
    gen = max(fk[1] for fk, _p in inputs) + 1
    out_path = seg.segment_path(store.path, first, last, gen)
    tmp = out_path + ".tmp"

    cert_cache: dict = {}
    survivors: list[tuple[bytes, int, tuple[int, int], int, int, int]] = []
    dropped: list[tuple[bytes, int, tuple[int, int], int]] = []
    in_bytes = 0
    out_size = 0
    gov = _RateGovernor(flags.get_float("BFTKV_LOG_COMPACT_MBPS"))
    copy_t0 = time.monotonic()
    with open(tmp, "wb") as out:
        for fkey, path in inputs:
            in_bytes += os.path.getsize(path)
            try:
                f = open(path, "rb")
            except OSError:
                continue  # raced another compaction's unlink
            with f:
                for variable, t, value, voff, vlen in seg.iter_records(f):
                    with store._lock:
                        entry = store._data.get(variable)
                        loc = entry[1].get(t) if entry else None
                        live = (
                            loc is not None
                            and loc[0] == fkey
                            and loc[1] == voff
                        )
                    if not live:
                        continue  # superseded copy: dead bytes
                    if not _keep(store, variable, t, value, cert_cache):
                        dropped.append((variable, t, fkey, voff))
                        continue
                    buf = seg.encode_record(variable, t, value)
                    new_voff = (
                        out_size + seg.HEADER.size + len(variable)
                    )
                    out.write(buf)
                    gov.debit(len(buf))
                    survivors.append(
                        (variable, t, fkey, voff, new_voff, len(buf))
                    )
                    out_size += len(buf)
        out.flush()
        os.fsync(out.fileno())
    os.replace(tmp, out_path)
    dfd = os.open(store.path, os.O_RDONLY)
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)

    new_fkey = (first, gen)
    stale_copy_bytes = 0
    with store._lock:
        store._paths[new_fkey] = out_path
        for variable, t, fkey, voff, new_voff, rec_len in survivors:
            entry = store._data.get(variable)
            loc = entry[1].get(t) if entry else None
            if loc is not None and loc[0] == fkey and loc[1] == voff:
                vlen = loc[2]
                entry[1][t] = (new_fkey, new_voff, vlen)
                store._rec_len[(variable, t)] = rec_len
            else:
                # Overwritten while we copied: the fresh copy in the
                # compacted file is immediately dead.
                stale_copy_bytes += rec_len
        for variable, t, fkey, voff in dropped:
            entry = store._data.get(variable)
            loc = entry[1].get(t) if entry else None
            if loc is not None and loc[0] == fkey and loc[1] == voff:
                entry[1].pop(t)
                entry[0].remove(t)
                store._rec_len.pop((variable, t), None)
        input_paths = [p for _fk, p in inputs]
        for fkey, _p in inputs:
            store._paths.pop(fkey, None)
        store._drop_fds_locked(input_paths)
        store._sealed_bytes = max(
            0, store._sealed_bytes - in_bytes + out_size
        )
        store._dead_bytes = stale_copy_bytes
    for p in input_paths:
        try:
            os.unlink(p)
        except OSError:
            pass  # already gone (open-time supersede recovery raced us)
    # Compaction IO accounting (capacity plane: compact_io resource).
    metrics.incr("storage.compact.read_bytes", in_bytes)
    metrics.incr("storage.compact.written_bytes", out_size)
    dt = max(1e-9, time.monotonic() - copy_t0)
    metrics.gauge(
        "storage.compact.mbps", (in_bytes + out_size) / dt / (1024 * 1024)
    )
    return {
        "inputs": len(inputs),
        "kept": len(survivors),
        "dropped": len(dropped),
        "reclaimed_bytes": max(0, in_bytes - out_size),
    }
