"""Versioned key-value storage backends.

Capability parity with the reference storage layer
(reference: storage/storage.go:14-17): ``read(variable, t)`` with
``t == 0`` meaning "the latest version", ``write(variable, t, value)``
appending a version. Every version is retained — the store *is* the
durable state of a replica (SURVEY.md §5 "Checkpoint / resume").

Backends:

- :class:`bftkv_tpu.storage.plain.PlainStorage` — one file per version
  (reference: storage/plain/plain.go:22-90);
- :class:`bftkv_tpu.storage.memkv.MemStorage` — in-process sorted map,
  used by tests and simulated clusters;
- :class:`bftkv_tpu.storage.native.NativeStorage` — C++ log-structured
  engine (the leveldb-equivalent, reference: storage/leveldb/leveldb.go),
  loaded via ctypes when the shared library has been built;
- :class:`bftkv_tpu.storage.logkv.LogStorage` — append-only group-commit
  segment log with compaction and snapshot shipping (DESIGN.md §19),
  the planet-scale engine (`--storage log`).

Optional seams (feature-detected with ``getattr``, never required —
the Protocol below stays the contract every backend must meet):

- ``write_batch(items)`` — persist a coalesced batch under ONE
  durability barrier (group commit).  The server's persist-many path
  and ``admit_records`` use it when present and fall back to per-item
  ``write`` when not;
- ``sorted_keys(after=None, limit=None)`` — a cheap sorted-keyspace
  cursor for the windowed ``pending_variables`` repair scan, replacing
  a full ``sorted(keys())`` per round;
- ``snapshot_records(pred)`` / ``seal_active()`` — sealed-segment bulk
  streaming, the §15 migration pre-copy transfer unit;
- ``reopen()`` / ``close()`` — crash-restart onto the same directory
  (index rebuild, torn-tail truncation) and clean shutdown.
"""

from __future__ import annotations

from typing import Protocol, runtime_checkable

from bftkv_tpu.errors import ERR_NOT_FOUND

__all__ = ["Storage", "ERR_NOT_FOUND"]


@runtime_checkable
class Storage(Protocol):
    """The storage interface (reference: storage/storage.go:14-17)."""

    def read(self, variable: bytes, t: int = 0) -> bytes:
        """Return the value at timestamp ``t``; ``t == 0`` means latest.

        Raises ``ERR_NOT_FOUND`` if the variable (or that version) does
        not exist.
        """
        ...

    def write(self, variable: bytes, t: int, value: bytes) -> None:
        """Store ``value`` as version ``t`` of ``variable``."""
        ...

    def versions(self, variable: bytes) -> list[int]:
        """All stored version timestamps for ``variable`` (any order;
        empty if unknown).

        Part of the storage contract: the server's read path scans back
        past in-progress sign records with it (the reference walks the
        leveldb key range the same way, storage/leveldb/leveldb.go:30-46).
        A backend without it degrades to a bounded countdown that cannot
        reach completed versions more than 1024 timestamps behind an
        incomplete write-once record.
        """
        ...

    def keys(self) -> list[bytes]:
        """Every stored variable, each exactly once (any order).

        The keyspace-enumeration half of the anti-entropy contract
        (``bftkv_tpu.sync``): a replica's digest tree is computed from
        ``keys()`` × ``versions()`` × ``read()``.  The reference has no
        analog — its repair plane is client read-repair only — so this
        is a genuine contract extension all three backends implement.
        """
        ...

    def scan(self) -> list[tuple[bytes, int]]:
        """Every stored ``(variable, t)`` pair (any order) — the full
        version inventory in one call, for digest builds and
        differential backend tests.  Equivalent to
        ``[(v, t) for v in keys() for t in versions(v)]`` but a backend
        may implement it with one index walk."""
        ...
