"""ctypes binding for the C++ log-structured KV engine.

The native analog of the reference's leveldb backend
(reference: storage/leveldb/leveldb.go:22-53). The shared library is
built on demand from ``native/kvstore.cpp`` (no pybind11 in the image;
plain C ABI + ctypes).
"""

from __future__ import annotations

import ctypes
import os
import subprocess

from bftkv_tpu.errors import ERR_NOT_FOUND, new_error
from bftkv_tpu.devtools.lockwatch import named_lock

ERR_STORAGE_IO = new_error("storage I/O failure")

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")
_LIB_PATH = os.path.abspath(os.path.join(_NATIVE_DIR, "libbftkvstore.so"))
_lib = None
_lib_lock = named_lock("storage.native.lib")


def _load() -> ctypes.CDLL:
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        # Always invoke make: the Makefile dependency on kvstore.cpp makes
        # an up-to-date build a no-op, and a stale prebuilt .so would
        # otherwise fail symbol binding below when the C ABI grows.
        subprocess.run(
            ["make", "-s"],
            cwd=os.path.abspath(_NATIVE_DIR),
            check=True,
        )
        lib = ctypes.CDLL(_LIB_PATH)
        lib.kv_open.restype = ctypes.c_void_p
        lib.kv_open.argtypes = [ctypes.c_char_p]
        lib.kv_close.argtypes = [ctypes.c_void_p]
        lib.kv_write.restype = ctypes.c_int
        lib.kv_write.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_uint32,
            ctypes.c_uint64,
            ctypes.c_char_p,
            ctypes.c_uint64,
        ]
        lib.kv_read.restype = ctypes.c_int64
        lib.kv_read.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_uint32,
            ctypes.c_uint64,
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.kv_versions.restype = ctypes.c_int64
        lib.kv_versions.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_uint32,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_uint64,
        ]
        lib.kv_keys.restype = ctypes.c_int64
        lib.kv_keys.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_uint64,
        ]
        _lib = lib
        return lib


class NativeStorage:
    def __init__(self, path: str):
        self._lib = _load()
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        handle = self._lib.kv_open(path.encode())
        if not handle:
            raise ERR_STORAGE_IO
        self._handle = handle
        self._lock = named_lock("storage.native")

    def close(self) -> None:
        with self._lock:
            if self._handle:
                self._lib.kv_close(self._handle)
                self._handle = None

    def __del__(self):  # pragma: no cover - GC timing
        try:
            self.close()
        except Exception:
            pass

    def read(self, variable: bytes, t: int = 0) -> bytes:
        with self._lock:
            # Protocol threads (read-repair, late sign persists) can
            # outlive a close(); a NULL handle into the C ABI would be a
            # use-after-free — fail as storage I/O instead.
            if not self._handle:
                raise ERR_STORAGE_IO
            t_out = ctypes.c_uint64(0)
            n = self._lib.kv_read(
                self._handle, variable, len(variable), t, None, ctypes.byref(t_out)
            )
            if n == -1:
                raise ERR_NOT_FOUND
            if n < 0:
                raise ERR_STORAGE_IO
            buf = ctypes.create_string_buffer(int(n))
            # Re-read pinned at the resolved timestamp so a concurrent
            # write of a newer version between the two calls is harmless.
            n2 = self._lib.kv_read(
                self._handle, variable, len(variable), t_out.value, buf, None
            )
            if n2 < 0 or n2 != n:
                raise ERR_STORAGE_IO
            return buf.raw[: int(n)]

    def versions(self, variable: bytes) -> list[int]:
        """All stored version timestamps, descending (storage contract —
        the server read path's scan past in-progress sign records)."""
        with self._lock:
            if not self._handle:
                return []
            cap = 64
            while True:
                buf = (ctypes.c_uint64 * cap)()
                n = self._lib.kv_versions(
                    self._handle, variable, len(variable), buf, cap
                )
                if n < 0:
                    return []
                if n <= cap:
                    return list(buf[: int(n)])
                cap = int(n)

    def keys(self) -> list[bytes]:
        """Every stored variable (storage contract — anti-entropy):
        length-prefixed names out of the C index, two-call sizing like
        :meth:`versions`."""
        with self._lock:
            if not self._handle:
                return []
            cap = 0
            buf = None
            while True:
                n = self._lib.kv_keys(self._handle, buf, cap)
                if n < 0:
                    return []
                if n <= cap:
                    break
                # A concurrent write may grow the index between the
                # sizing and filling calls; loop until it fits.
                cap = int(n)
                buf = ctypes.create_string_buffer(cap)
            out: list[bytes] = []
            data = buf.raw[: int(n)] if buf is not None else b""
            off = 0
            while off + 4 <= len(data):
                ln = int.from_bytes(data[off : off + 4], "little")
                off += 4
                out.append(data[off : off + ln])
                off += ln
            return out

    def scan(self) -> list[tuple[bytes, int]]:
        """Every stored ``(variable, t)`` pair."""
        return [(var, t) for var in self.keys() for t in self.versions(var)]

    def write(self, variable: bytes, t: int, value: bytes) -> None:
        with self._lock:
            if not self._handle:
                raise ERR_STORAGE_IO
            rc = self._lib.kv_write(
                self._handle, variable, len(variable), t, value, len(value)
            )
            if rc != 0:
                raise ERR_STORAGE_IO
