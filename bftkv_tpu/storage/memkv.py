"""In-memory versioned KV store.

The simulated-cluster backend: hundreds of replicas in one process each
get an isolated ``MemStorage`` (the analog of the reference tests running
one leveldb per key directory). Layout mirrors the leveldb backend's key
order — per-variable versions kept sorted so "latest" is O(1)
(reference: storage/leveldb/leveldb.go:30-46, prefix iterator ``Last()``).
"""

from __future__ import annotations

import bisect

from bftkv_tpu.errors import ERR_NOT_FOUND
from bftkv_tpu.faults import failpoint as fp
from bftkv_tpu.devtools.lockwatch import named_lock


class MemStorage:
    def __init__(self):
        # variable -> (sorted list of t, {t: value})
        self._data: dict[bytes, tuple[list[int], dict[int, bytes]]] = {}
        self._lock = named_lock("storage.mem")

    def read(self, variable: bytes, t: int = 0) -> bytes:
        with self._lock:
            entry = self._data.get(variable)
            if entry is None:
                raise ERR_NOT_FOUND
            ts, values = entry
            if t == 0:
                t = ts[-1]
            value = values.get(t)
            if value is None:
                raise ERR_NOT_FOUND
            return value

    def versions(self, variable: bytes) -> list[int]:
        """All stored timestamps for ``variable`` (ascending)."""
        with self._lock:
            entry = self._data.get(variable)
            return list(entry[0]) if entry else []

    def keys(self) -> list[bytes]:
        """Every stored variable (storage contract — anti-entropy)."""
        with self._lock:
            return list(self._data)

    def scan(self) -> list[tuple[bytes, int]]:
        """Every stored ``(variable, t)`` pair, one index walk."""
        with self._lock:
            return [
                (var, t)
                for var, (ts, _values) in self._data.items()
                for t in ts
            ]

    def write(self, variable: bytes, t: int, value: bytes) -> None:
        if fp.ARMED:
            # ``storage.write`` failpoint: the in-memory backend can
            # only fail whole ("torn" is meaningless without files).
            act = fp.fire("storage.write", backend="mem", op="write")
            if act is not None and act.kind in ("io_error", "torn"):
                raise OSError("injected storage I/O error")
        with self._lock:
            entry = self._data.get(variable)
            if entry is None:
                entry = ([], {})
                self._data[variable] = entry
            ts, values = entry
            if t not in values:
                bisect.insort(ts, t)
            values[t] = value
