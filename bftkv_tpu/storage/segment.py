"""Segment files for the log-structured store (DESIGN.md §19).

One segment is an append-only file of checksummed records::

    crc32(u32) | key_len(u32) | t(u64) | value_len(u32) | key | value

The CRC covers everything after itself (header tail + key + value), so
a torn append — a crash mid-write — is detectable at exactly the first
bad record: replay truncates there and every byte before it is intact.
Compare PlainStorage, where the same crash safety costs a temp file,
two fsyncs and a rename *per record*; here the unit of durability is
the segment tail, and one fsync covers every record appended since the
last (the group-commit amortization, DESIGN.md §19.2).

Naming carries the replay order and the compaction lineage:

- ``seg-<seq>.log`` — a plain segment, covering sequence range
  ``[seq, seq]``, generation 0;
- ``seg-<first>-<last>.c<gen>.log`` — a compacted segment replacing
  every lower-generation segment whose range it covers.

Replay order is ``(first, gen)`` ascending; within a file, byte order.
That equals append order, so same-``(variable, t)`` overwrites resolve
last-writer-wins exactly as they were issued.  A crash between a
compaction's rename and its input unlinks leaves both on disk; open
detects the covered inputs and deletes them (idempotent recovery).
"""

from __future__ import annotations

import os
import re
import struct
import zlib

__all__ = [
    "HEADER",
    "encode_record",
    "iter_records",
    "scan_segment",
    "segment_path",
    "parse_segment_name",
    "list_segments",
]

#: crc32 | key_len | t | value_len
HEADER = struct.Struct(">IIQI")

_NAME = re.compile(
    r"^seg-(\d{12})(?:-(\d{12})\.c(\d+))?\.log$"
)


def encode_record(variable: bytes, t: int, value: bytes) -> bytes:
    """One framed record; the CRC seals header tail + key + value."""
    tail = struct.pack(">IQI", len(variable), t, len(value))
    crc = zlib.crc32(tail)
    crc = zlib.crc32(variable, crc)
    crc = zlib.crc32(value, crc)
    return struct.pack(">I", crc) + tail + variable + value


def iter_records(f, *, base: int = 0):
    """Yield ``(variable, t, value, value_off, value_len)`` from an open
    binary file positioned at ``base``, stopping at EOF **or at the
    first record that fails its checksum** — the torn tail.  The
    generator's ``good_end`` attribute is not expressible; use
    :func:`scan_segment` when the truncation offset matters."""
    for rec in _scan(f, base):
        yield rec[:5]


def _scan(f, base: int):
    f.seek(base)
    off = base
    while True:
        head = f.read(HEADER.size)
        if len(head) < HEADER.size:
            return
        crc, klen, t, vlen = HEADER.unpack(head)
        body = f.read(klen + vlen)
        if len(body) < klen + vlen:
            return  # short body: torn tail
        want = zlib.crc32(head[4:])
        want = zlib.crc32(body, want)
        if want != crc:
            return  # checksum mismatch: torn tail (or bit rot) — stop
        variable = body[:klen]
        value = body[klen:]
        rec_len = HEADER.size + klen + vlen
        yield (variable, t, value, off + HEADER.size + klen, vlen, rec_len)
        off += rec_len


def scan_segment(path: str):
    """Replay one segment: returns ``(entries, good_end)`` where
    ``entries`` is ``[(variable, t, value_off, value_len, rec_len)]``
    (values stay on disk — the rebuild is index-only, spill-safe for
    keyspaces whose values dwarf RAM) and ``good_end`` is the offset
    past the last intact record.  ``good_end < file size`` means a torn
    tail the caller should truncate before appending."""
    entries: list[tuple[bytes, int, int, int, int]] = []
    good_end = 0
    with open(path, "rb") as f:
        for variable, t, _value, voff, vlen, rec_len in _scan(f, 0):
            entries.append((variable, t, voff, vlen, rec_len))
            good_end += rec_len
    return entries, good_end


def segment_path(root: str, first: int, last: int, gen: int) -> str:
    if gen == 0 and first == last:
        return os.path.join(root, f"seg-{first:012d}.log")
    return os.path.join(root, f"seg-{first:012d}-{last:012d}.c{gen}.log")


def parse_segment_name(name: str) -> tuple[int, int, int] | None:
    """``(first, last, gen)`` for a segment file name, else None."""
    m = _NAME.match(name)
    if m is None:
        return None
    first = int(m.group(1))
    if m.group(2) is None:
        return first, first, 0
    return first, int(m.group(2)), int(m.group(3))


def list_segments(root: str) -> list[tuple[int, int, int, str]]:
    """Segments in replay order, after compaction-crash recovery:
    returns ``[(first, last, gen, path)]`` sorted by ``(first, gen)``,
    having deleted any segment fully covered by a higher-generation
    compacted segment (the leftover inputs of a compaction that crashed
    after its rename but before its unlinks) and any stale ``.tmp``."""
    found: list[tuple[int, int, int, str]] = []
    try:
        names = os.listdir(root)
    except FileNotFoundError:
        return []
    for name in names:
        if name.endswith(".tmp"):
            os.unlink(os.path.join(root, name))
            continue
        parsed = parse_segment_name(name)
        if parsed is None:
            continue
        first, last, gen = parsed
        found.append((first, last, gen, os.path.join(root, name)))
    # Supersede: (first,last,gen) is dead if another file covers its
    # whole range at a strictly higher generation.
    live: list[tuple[int, int, int, str]] = []
    for first, last, gen, path in found:
        covered = any(
            f2 <= first and last <= l2 and g2 > gen
            for f2, l2, g2, _p in found
        )
        if covered:
            os.unlink(path)
        else:
            live.append((first, last, gen, path))
    live.sort(key=lambda e: (e[0], e[2]))
    return live
