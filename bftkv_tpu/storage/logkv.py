"""Log-structured storage: group-commit segment log (DESIGN.md §19).

PlainStorage pays four syscalls and two fsyncs *per record* and its
write cost grows with the directory (ROADMAP: "hopeless at millions of
users").  This engine appends every record to one active segment file
and amortizes the fsync: a single durability barrier covers every
record appended since the last one (group commit — the same move "The
Latency Price of Threshold Cryptosystems" makes for signing cost: keep
the expensive step off the per-op critical path).  Write cost is
O(record), independent of keyspace size.

Three cooperating pieces:

- :mod:`bftkv_tpu.storage.segment` — checksummed record framing, torn
  tails detectable at the first bad CRC;
- this module — the engine: sparse in-RAM index (latest-t plus version
  offsets; values stay on disk, so memory is bounded by the version
  *count*, not the data), group-commit fsync, restart rebuild from a
  sequential segment scan, sealed-segment snapshot shipping;
- :mod:`bftkv_tpu.storage.compact` — background compaction preserving
  the §12 commit-pending residue semantics.

Durability policy: **durable by default** — the engine exists to make
fsync cheap, so unlike PlainStorage there is no daemon opt-in split;
pass ``fsync=False`` only where the harness explicitly trades
power-cut durability for speed (in-process chaos clusters, fill
microbenches).  Single writes fsync before returning; concurrent
writers share one barrier (the caller that loses the leader race waits
for the winner's fsync instead of issuing its own); ``write_batch``
appends the whole batch then fsyncs once.

Crash model: a record is either fully replayed or truncated at the
torn tail — the index is rebuilt from the segments on open, so "died
after append, before index update" recovers the append, and "died
mid-append" loses only the unacknowledged record.
"""

from __future__ import annotations

import bisect
import os
import threading
import time
from collections import OrderedDict

from bftkv_tpu.errors import ERR_NOT_FOUND
from bftkv_tpu.faults import failpoint as fp
from bftkv_tpu import flags
from bftkv_tpu.devtools import lockwatch
from bftkv_tpu.devtools.lockwatch import named_lock
from bftkv_tpu.metrics import registry as metrics
from bftkv_tpu.storage import segment as seg

__all__ = ["LogStorage"]

#: Open read-fds kept per store (LRU) — sealed segments are immutable,
#: so a cached descriptor can never serve stale bytes.
_FD_CACHE = 64


class LogStorage:
    def __init__(
        self,
        path: str,
        *,
        fsync: bool | None = None,
        segment_bytes: int | None = None,
        group_commit_s: float | None = None,
        compact_trigger: float | None = None,
    ):
        self.path = path
        self.fsync = True if fsync is None else fsync
        if segment_bytes is None:
            segment_bytes = (
                flags.get_int("BFTKV_LOG_SEGMENT_MB") * 1024 * 1024
            )
        self.segment_bytes = max(1, segment_bytes)
        if group_commit_s is None:
            group_commit_s = (
                flags.get_float("BFTKV_LOG_GROUP_COMMIT_MS") / 1000.0
            )
        self.group_commit_s = max(0.0, group_commit_s)
        # Published once: the capacity plane reads the linger window as
        # the commit-wait saturation denominator (DESIGN.md §20).
        metrics.gauge("storage.log.linger_ms", self.group_commit_s * 1000.0)
        if compact_trigger is None:
            compact_trigger = flags.get_float("BFTKV_LOG_COMPACT_TRIGGER")
        self.compact_trigger = compact_trigger
        # Index + active-segment state.  Appends MUST serialize (one
        # tail), so unlike PlainStorage the data write happens under
        # the store lock — but it is a buffered-to-OS file write, not
        # a patched blocking call; the fsync barrier runs outside.
        self._lock = named_lock("storage.log")
        # variable -> (sorted ts, {t: ((first, gen), value_off, value_len)})
        self._data: dict[
            bytes, tuple[list[int], dict[int, tuple[tuple[int, int], int, int]]]
        ] = {}
        self._rec_len: dict[tuple[bytes, int], int] = {}
        self._paths: dict[tuple[int, int], str] = {}
        self._fds: "OrderedDict[str, int]" = OrderedDict()
        self._sorted: list[bytes] | None = None
        self._sealed_bytes = 0
        self._dead_bytes = 0
        # Group-commit state: (seq, offset) durable high-water mark.
        self._cv = threading.Condition()
        self._flushed: tuple[int, int] = (0, 0)
        self._flushing = False
        self._pending_truncate = False
        self._compact_thread: threading.Thread | None = None
        self.compactions = 0
        os.makedirs(path, exist_ok=True)
        self._open_state()

    # -- open / rebuild ----------------------------------------------------

    def _open_state(self) -> None:
        """Rebuild the index from one sequential scan of the segments
        (spill-safe: offsets only, values stay on disk), truncate the
        torn tail of the last segment, and pick/create the active
        segment.  Runs in ``__init__``/``reopen`` only — no store lock
        exists to hold yet."""
        segments = seg.list_segments(self.path)
        last_i = len(segments) - 1
        for i, (first, last, gen, p) in enumerate(segments):
            fkey = (first, gen)
            self._paths[fkey] = p
            entries, good_end = seg.scan_segment(p)
            size = os.path.getsize(p)
            if good_end < size:
                if i == last_i:
                    # Torn tail: the crash the checksum exists to
                    # catch.  Truncate so future appends replay.
                    os.truncate(p, good_end)
                    metrics.incr("storage.log.torn_truncated")
                else:
                    # A sealed segment should never tear (fsynced at
                    # seal); bit rot loses its tail records only.
                    metrics.incr("storage.log.sealed_tear")
            for variable, t, voff, vlen, rec_len in entries:
                self._index_put(variable, t, fkey, voff, vlen, rec_len)
        # Active segment: the last plain (gen 0) segment, if it is
        # last in replay order and still has room; else a fresh one.
        active = None
        if segments:
            first, last, gen, p = segments[-1]
            if gen == 0 and os.path.getsize(p) < self.segment_bytes:
                active = (first, p)
        if active is None:
            nxt = (segments[-1][1] + 1) if segments else 0
            p = seg.segment_path(self.path, nxt, nxt, 0)
            active = (nxt, p)
            self._paths[(nxt, 0)] = p
        self._seq, self._active_path = active
        # buffering=0: every append is pushed to the OS immediately,
        # so read fds and the fsync barrier see it without a flush.
        self._f = open(self._active_path, "ab", buffering=0)
        self._size = os.path.getsize(self._active_path)
        self._sealed_bytes = sum(
            os.path.getsize(p)
            for k, p in self._paths.items()
            if p != self._active_path
        )
        self._flushed = (self._seq, 0)

    def _index_put(
        self,
        variable: bytes,
        t: int,
        fkey: tuple[int, int],
        voff: int,
        vlen: int,
        rec_len: int,
    ) -> None:
        entry = self._data.get(variable)
        if entry is None:
            entry = ([], {})
            self._data[variable] = entry
            self._sorted = None  # new key: sorted-keys cache is stale
        ts, locs = entry
        if t not in locs:
            bisect.insort(ts, t)
        else:
            # Same (variable, t) rewritten (pending -> certified
            # back-fill): the superseded bytes are dead for compaction.
            self._dead_bytes += self._rec_len.get((variable, t), 0)
        locs[t] = (fkey, voff, vlen)
        self._rec_len[(variable, t)] = rec_len

    # -- append / group commit ---------------------------------------------

    def _append_locked(self, variable: bytes, t: int, value: bytes) -> None:
        """Append one record and index it; caller holds the lock and
        owns the commit barrier.  Rotation (seal + new segment) happens
        here when the active segment fills."""
        if self._pending_truncate:
            # A prior injected torn append left garbage past _size in
            # a process that kept running; roll the tail back first.
            os.ftruncate(self._f.fileno(), self._size)
            self._pending_truncate = False
        buf = seg.encode_record(variable, t, value)
        if fp.ARMED:
            # ``storage.write`` failpoint: torn = half the record
            # lands and the "process" dies before the index update —
            # exactly the crash the CRC framing recovers from.
            act = fp.fire("storage.write", backend="log", op="write")
            if act is not None:
                if act.kind == "torn":
                    self._f.write(buf[: max(1, len(buf) // 2)])
                    self._pending_truncate = True
                    raise OSError("injected torn write")
                if act.kind == "io_error":
                    raise OSError("injected storage I/O error")
        voff = self._size + seg.HEADER.size + len(variable)
        self._f.write(buf)
        self._index_put(
            variable, t, (self._seq, 0), voff, len(value), len(buf)
        )
        self._size += len(buf)
        if self._size >= self.segment_bytes:
            self._rotate_locked()

    def _rotate_locked(self) -> None:
        """Seal the active segment and start the next one.  Rare (once
        per BFTKV_LOG_SEGMENT_MB of appends), so the seal fsync runs
        under the store lock — appends must not interleave with the
        writer swap."""
        with lockwatch.waiver(
            "log: segment seal fsyncs + opens under the store lock; "
            "rare (once per segment) and appends must not interleave "
            "with the writer swap"
        ):
            if self.fsync:
                os.fsync(self._f.fileno())
            self._f.close()
            nxt = self._seq + 1
            p = seg.segment_path(self.path, nxt, nxt, 0)
            self._f = open(p, "ab", buffering=0)
        self._sealed_bytes += self._size
        self._paths[(nxt, 0)] = p
        self._seq, self._active_path, self._size = nxt, p, 0
        with self._cv:
            # Everything in older segments is durable once sealed.
            if self.fsync and self._flushed < (nxt, 0):
                self._flushed = (nxt, 0)
        metrics.incr("storage.log.seals")
        self._maybe_compact_locked()

    def _commit(self, pos: tuple[int, int]) -> None:
        """Group-commit barrier: return once every byte up to ``pos``
        is fsynced.  One caller at a time leads the fsync; everyone who
        lost the race piggybacks on the leader's barrier instead of
        issuing their own — N concurrent writers, one fsync."""
        t0 = time.monotonic()
        try:
            self._commit_inner(pos)
        finally:
            # Commit-wait = linger + fsync + barrier queueing; its p99
            # against the configured linger is the log_commit
            # saturation signal (capacity plane, DESIGN.md §20).
            metrics.observe(
                "storage.log.commit_wait", time.monotonic() - t0
            )

    def _commit_inner(self, pos: tuple[int, int]) -> None:
        while True:
            with self._cv:
                if self._flushed >= pos:
                    return
                if self._flushing:
                    self._cv.wait(timeout=5.0)
                    continue
                self._flushing = True
            target = None
            try:
                if self.group_commit_s:
                    # The linger window: let concurrent writers join
                    # this barrier (outside every lock).
                    time.sleep(self.group_commit_s)
                with self._lock:
                    snap = (self._seq, self._size)
                    f = self._f
                if fp.ARMED:
                    # ``storage.fsync`` failpoint: a stalled durability
                    # barrier — every writer joined on this group
                    # commit waits it out (slow-disk model; the
                    # capacity plane must name log_commit for it).
                    act = fp.fire("storage.fsync", backend="log")
                    if act is not None and act.kind == "stall":
                        time.sleep(fp.delay_seconds(act))
                try:
                    os.fsync(f.fileno())
                except ValueError:
                    # Rotation closed this writer after the snapshot;
                    # the seal path fsynced it — the barrier holds.
                    pass
                target = snap
                metrics.incr("storage.log.fsync")
            finally:
                with self._cv:
                    self._flushing = False
                    if target is not None and self._flushed < target:
                        self._flushed = target
                    self._cv.notify_all()

    # -- storage contract ---------------------------------------------------

    def write(self, variable: bytes, t: int, value: bytes) -> None:
        with self._lock:
            self._append_locked(variable, t, value)
            pos = (self._seq, self._size)
        if self.fsync:
            self._commit(pos)

    def write_batch(self, items) -> None:
        """The group-commit seam: append every ``(variable, t, value)``
        then fsync ONCE — the whole coalesced batch (gateway write
        coalescer, sync back-fill, ``admit_records``) shares a single
        durability barrier."""
        items = list(items)
        if not items:
            return
        if fp.ARMED:
            # Batch-level failpoint eval: one fate for the whole batch
            # (a real torn batch tears at one record; the per-record
            # path in _append_locked models that — here the injected
            # error fails the batch before any index update).
            act = fp.fire("storage.write", backend="log", op="write_batch")
            if act is not None and act.kind in ("io_error", "torn"):
                raise OSError("injected storage I/O error")
        with self._lock:
            for variable, t, value in items:
                self._append_locked(variable, t, value)
            pos = (self._seq, self._size)
        metrics.observe("storage.log.batch", len(items))
        if self.fsync:
            self._commit(pos)

    def read(self, variable: bytes, t: int = 0) -> bytes:
        with self._lock:
            entry = self._data.get(variable)
            if entry is None:
                raise ERR_NOT_FOUND
            ts, locs = entry
            if t == 0:
                t = ts[-1]
            loc = locs.get(t)
            if loc is None:
                raise ERR_NOT_FOUND
            fkey, voff, vlen = loc
            path = self._paths[fkey]
        data = os.pread(self._fd(path), vlen, voff)
        if len(data) < vlen:
            # Compaction swapped the file under a stale fd (unlinked
            # files keep serving, but a re-resolve is the safe path).
            with self._lock:
                entry = self._data.get(variable)
                loc = entry[1].get(t) if entry else None
                if loc is None:
                    raise ERR_NOT_FOUND
                fkey, voff, vlen = loc
                path = self._paths[fkey]
            data = os.pread(self._fd(path), vlen, voff)
        return data

    def versions(self, variable: bytes) -> list[int]:
        """All stored timestamps (ascending) — one index lookup; no
        directory listing, no file I/O."""
        with self._lock:
            entry = self._data.get(variable)
            return list(entry[0]) if entry else []

    def keys(self) -> list[bytes]:
        with self._lock:
            return list(self._data)

    def scan(self) -> list[tuple[bytes, int]]:
        with self._lock:
            return [
                (var, t)
                for var, (ts, _locs) in self._data.items()
                for t in ts
            ]

    def sorted_keys(
        self, after: bytes | None = None, limit: int | None = None
    ) -> list[bytes]:
        """Sorted keyspace slice — the cheap ``pending_variables``
        cursor seam: the sort is cached and only invalidated when a NEW
        variable appears, so a steady-state repair round costs one
        bisect + slice instead of re-sorting the whole keyspace."""
        with self._lock:
            if self._sorted is None:
                self._sorted = sorted(self._data)
            keys = self._sorted
            lo = 0
            if after is not None:
                lo = bisect.bisect_right(keys, after)
            hi = len(keys) if limit is None else min(len(keys), lo + limit)
            return keys[lo:hi]

    # -- snapshot shipping (DESIGN.md §19.4) --------------------------------

    def seal_active(self) -> None:
        """Force-seal the active segment (if non-empty) so its records
        become part of the sealed snapshot set."""
        with self._lock:
            if self._size:
                self._rotate_locked()

    def sealed_segment_paths(self) -> list[str]:
        with self._lock:
            return [
                p for p in self._paths.values() if p != self._active_path
            ]

    def snapshot_records(self, pred=None):
        """Stream ``(variable, t, value)`` for every LIVE record whose
        variable passes ``pred`` — the §15 pre-copy bulk transfer unit.
        Seals the active segment first, then reads the sealed segments
        *sequentially* (bulk I/O, no per-key seeks); a record yields
        only if the index still points at it, so superseded duplicates
        and compacted-away residue never ship."""
        self.seal_active()
        with self._lock:
            files = [
                (fkey, p)
                for fkey, p in sorted(self._paths.items())
                if p != self._active_path
            ]
        for fkey, path in files:
            try:
                f = open(path, "rb")
            except OSError:
                continue  # compacted away mid-stream: its records moved
            with f:
                for variable, t, value, voff, _vlen in seg.iter_records(f):
                    if pred is not None and not pred(variable):
                        continue
                    with self._lock:
                        entry = self._data.get(variable)
                        loc = entry[1].get(t) if entry else None
                        live = loc is not None and loc[0] == fkey and (
                            loc[1] == voff
                        )
                    if live:
                        yield variable, t, value

    # -- compaction hooks ---------------------------------------------------

    def dead_ratio(self) -> float:
        with self._lock:
            if not self._sealed_bytes:
                return 0.0
            return self._dead_bytes / self._sealed_bytes

    def _maybe_compact_locked(self) -> None:
        """Arm background compaction when the sealed dead-byte ratio
        crosses the trigger (0 disables).  One flight at a time; the
        caller holds the store lock (the trigger check is field reads,
        the work runs on the spawned thread)."""
        if self.compact_trigger <= 0 or not self._sealed_bytes:
            return
        if self._dead_bytes / self._sealed_bytes < self.compact_trigger:
            return
        t = self._compact_thread
        if t is not None and t.is_alive():
            return
        t = threading.Thread(
            target=self._compact_quiet, name="logkv-compact", daemon=True
        )
        self._compact_thread = t
        t.start()

    def _compact_quiet(self) -> None:
        try:
            self.compact()
        except Exception:
            # Background compaction must never take the store down —
            # the log stays append-correct without it; the failure is
            # counted and the next trigger retries.
            metrics.incr("storage.log.compact_failed")

    def compact(self) -> dict:
        """Synchronous compaction (tests call this directly; the
        trigger path runs it on a background thread)."""
        from bftkv_tpu.storage.compact import compact_store

        stats = compact_store(self)
        self.compactions += 1
        metrics.incr("storage.log.compactions")
        return stats

    # -- lifecycle ----------------------------------------------------------

    def _fd(self, path: str) -> int:
        with self._lock:
            fd = self._fds.get(path)
            if fd is not None:
                self._fds.move_to_end(path)
                return fd
        fd = os.open(path, os.O_RDONLY)
        with self._lock:
            have = self._fds.get(path)
            if have is not None:
                os.close(fd)
                return have
            self._fds[path] = fd
            while len(self._fds) > _FD_CACHE:
                _p, old = self._fds.popitem(last=False)
                os.close(old)
            return fd

    def _drop_fds_locked(self, paths) -> None:
        for p in paths:
            fd = self._fds.pop(p, None)
            if fd is not None:
                os.close(fd)

    def close(self) -> None:
        """Clean shutdown: one final barrier, then drop descriptors.
        The on-disk log IS the store — reopen rebuilds the index."""
        t = self._compact_thread
        if t is not None and t.is_alive():
            t.join(timeout=10.0)
        with self._lock:
            if self.fsync:
                with lockwatch.waiver(
                    "log: close-time fsync under the store lock — "
                    "shutdown path, no concurrent appends to stall"
                ):
                    try:
                        os.fsync(self._f.fileno())
                    except (OSError, ValueError):
                        pass  # already closed/rotated: nothing to sync
            self._f.close()
            self._drop_fds_locked(list(self._fds))

    def reopen(self) -> None:
        """Crash-restart onto the same log directory: drop every
        descriptor and the whole in-RAM index, then rebuild from the
        segment scan (truncating any torn tail) — what a restarted
        daemon does on its data dir, exercisable in-process."""
        self.close()
        with self._lock:
            with lockwatch.waiver(
                "log: crash-restart rebuild scans the segment files "
                "under the store lock — no reader may observe a "
                "half-built index"
            ):
                self._data.clear()
                self._rec_len.clear()
                self._paths.clear()
                self._sorted = None
                self._dead_bytes = 0
                self._pending_truncate = False
                self._open_state()
