"""Plain-file storage: one file per version.

File name is ``hex(variable).t`` inside the store directory; the latest
version is found by scanning for the maximum ``t`` suffix
(reference: storage/plain/plain.go:28-60). Writes are **crash-safe**:
write-to-temp, fsync the file, rename, fsync the directory — a torn
write can only ever leave a partial ``.tmp`` the read/inventory paths
ignore, never a half-written version (the reference renames but never
fsyncs, plain.go:62-75, so a power cut could publish an empty rename or
lose the directory entry).  The whole store is guarded by a lock the
same way the reference serializes file I/O with a mutex
(reference: storage/plain/plain.go:19).
"""

from __future__ import annotations

import bisect
import os
import threading
from collections import OrderedDict

from bftkv_tpu.errors import ERR_NOT_FOUND
from bftkv_tpu.faults import failpoint as fp
from bftkv_tpu import flags
from bftkv_tpu.devtools import lockwatch
from bftkv_tpu.devtools.lockwatch import named_lock


class PlainStorage:
    def __init__(self, path: str, *, fsync: bool | None = None):
        self.path = path
        self._lock = named_lock("storage.plain")
        # The write *ordering* (temp + rename) is always on — a crash
        # can never publish a torn version.  The per-write fsync pair
        # (file + directory) is a durability policy: ~5 ms/write on
        # commodity disks, so the library default matches the
        # reference's leveldb stance (WriteOptions.sync=false) and the
        # daemon opts IN (cmd/bftkv.py) — power-cut durability is a
        # deployment property, not a test-harness one.
        # BFTKV_PLAIN_FSYNC=1/0 overrides either way.
        if fsync is None:
            env = flags.raw("BFTKV_PLAIN_FSYNC", "")
            fsync = env == "1"
        self.fsync = fsync
        # stem -> max stored t.  ``read(variable, 0)`` used to list the
        # WHOLE directory to find the latest version — O(total files)
        # of GIL-dropping syscalls per read, quadratic over a write
        # burst.  The index is rebuilt from one listing on first use
        # (so a restart onto an existing store stays correct) and
        # maintained by ``write``; the store is single-process by
        # contract (the reference serializes it behind one mutex too),
        # so no other writer can stale it.
        self._latest: dict[str, int] | None = None
        # stem -> sorted stored ts, rebuilt by the same one-time listing
        # and maintained by ``write``.  ``versions()`` used to list the
        # whole directory per call — profiled hot in repair scans,
        # where every pending variable asks for its version set.
        self._versions: dict[str, list[int]] | None = None
        # Write-through record cache (the block-cache any storage
        # engine keeps): the protocol re-reads a variable's latest
        # record at every admission station, and on slow filesystems
        # those opens dominated the whole write path.  Bounded LRU of
        # (stem, t) -> bytes; entries are installed from durable state
        # only (after the atomic rename), so the cache can never serve
        # bytes a crash could lose that the file couldn't.
        # BFTKV_PLAIN_CACHE sizes it (entries; 0 disables).
        self._cache: "OrderedDict[tuple[str, int], bytes]" = OrderedDict()
        self._cache_max = int(flags.raw("BFTKV_PLAIN_CACHE", "1024") or 0)
        os.makedirs(path, exist_ok=True)

    def _prefix(self, variable: bytes) -> str:
        # hex(variable) as the file stem (reference: plain.go:28-33), but
        # long variables would blow the 255-byte filename limit — hash them.
        if len(variable) > 96:
            import hashlib

            return "h" + hashlib.sha256(variable).hexdigest()
        return variable.hex()

    def _index_locked(self) -> dict[str, int]:
        """The latest-version index; caller holds the lock.

        The FIRST-use rebuild lists the directory while holding the
        lock — deliberately: ``write()`` only maintains the index when
        it exists, so a rebuild racing a concurrent write outside the
        lock could publish an index missing that write's version
        forever.  One listing per process lifetime; lockwatch-waived
        with that reason."""
        idx = self._latest
        if idx is None:
            idx = {}
            vers: dict[str, list[int]] = {}
            try:
                with lockwatch.waiver(
                    "plain: one-time index rebuild must hold the store "
                    "lock (write() skips index updates while it is None)"
                ):
                    names = os.listdir(self.path)
            except FileNotFoundError:
                names = []
            for name in names:
                stem, sep, suffix = name.rpartition(".")
                if not sep:
                    continue
                try:
                    t = int(suffix)
                except ValueError:
                    continue  # .tmp / .k sidecars
                if t > idx.get(stem, -1):
                    idx[stem] = t
                vers.setdefault(stem, []).append(t)
            for ts in vers.values():
                ts.sort()
            self._versions = vers
            self._latest = idx
        return idx

    def _latest_t(self, variable: bytes) -> int | None:
        return self._index_locked().get(self._prefix(variable))

    def read(self, variable: bytes, t: int = 0) -> bytes:
        # The lock covers only index/cache state; the file I/O itself
        # runs outside it (data files are never deleted and renames are
        # atomic, so a concurrent writer cannot tear a read — but a
        # lock held across a ~10 ms open on a slow filesystem WOULD
        # serialize every concurrent handler touching this store).
        stem = self._prefix(variable)
        with self._lock:
            if t == 0:
                latest = self._latest_t(variable)
                if latest is None:
                    raise ERR_NOT_FOUND
                t = latest
            if self._cache_max:
                data = self._cache.get((stem, t))
                if data is not None:
                    self._cache.move_to_end((stem, t))
                    return data
        fn = os.path.join(self.path, f"{stem}.{t}")
        try:
            with open(fn, "rb") as f:
                data = f.read()
        except FileNotFoundError:
            raise ERR_NOT_FOUND from None
        with self._lock:
            self._cache_put_locked(stem, t, data)
        return data

    def _cache_put_locked(self, stem: str, t: int, data: bytes) -> None:
        if not self._cache_max:
            return
        self._cache[(stem, t)] = data
        self._cache.move_to_end((stem, t))
        while len(self._cache) > self._cache_max:
            self._cache.popitem(last=False)

    def _write_atomic(self, fn: str, data: bytes) -> None:
        """temp + fsync(file) + rename + fsync(dir).  After a crash at
        ANY point, readers see either the old state or the complete new
        file — never a torn version.  The temp name is per-thread: I/O
        runs outside the store lock, and two racing persists of one
        ``(variable, t)`` (a late staged-sign tail vs the write phase)
        must not interleave inside a shared temp file.  Non-integer
        suffixes are invisible to every read/inventory path."""
        tmp = f"{fn}.{threading.get_ident()}.tmp"
        with open(tmp, "wb") as f:
            f.write(data)
            if self.fsync:
                f.flush()
                os.fsync(f.fileno())
        os.replace(tmp, fn)
        if self.fsync:
            dfd = os.open(self.path, os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)

    def write(self, variable: bytes, t: int, value: bytes) -> None:
        # File I/O outside the lock (see read()): per-(variable, t) the
        # rename is atomic and last-writer-wins, and the index/cache
        # update re-takes the lock after the bytes are durable.
        stem = self._prefix(variable)
        if stem.startswith("h"):
            # Hash-stemmed long variable: the name is one-way, so
            # keys() needs a sidecar holding the raw bytes.  ".k"
            # never parses as a version (int("k") fails) and the
            # write is atomic like the data files'.
            kf = os.path.join(self.path, stem + ".k")
            if not os.path.exists(kf):
                self._write_atomic(kf, variable)
        fn = os.path.join(self.path, f"{stem}.{t}")
        if fp.ARMED:
            # ``storage.write`` failpoint: injected I/O error, or a
            # torn write — half the bytes land in the .tmp and the
            # "process" dies before rename (the crash the atomic
            # protocol exists to survive).
            act = fp.fire("storage.write", backend="plain", op="write")
            if act is not None:
                if act.kind == "torn":
                    with open(fn + ".tmp", "wb") as f:
                        f.write(value[: max(1, len(value) // 2)])
                    raise OSError("injected torn write")
                if act.kind == "io_error":
                    raise OSError("injected storage I/O error")
        self._write_atomic(fn, value)
        with self._lock:
            if self._latest is not None and t > self._latest.get(stem, -1):
                self._latest[stem] = t
            if self._versions is not None:
                ts = self._versions.setdefault(stem, [])
                i = bisect.bisect_left(ts, t)
                if i == len(ts) or ts[i] != t:
                    ts.insert(i, t)
            self._cache_put_locked(stem, t, value)

    def versions(self, variable: bytes) -> list[int]:
        """All stored timestamps for ``variable`` (ascending).

        Served from the version index — this used to list the WHOLE
        directory per call, and repair scans (which ask for every
        pending variable's version set) profiled it hot.  The lock
        covers only the index lookup; the one-time rebuild inside
        ``_index_locked`` carries the listing cost exactly once per
        process lifetime."""
        stem = self._prefix(variable)
        with self._lock:
            self._index_locked()
            vs = self._versions.get(stem) if self._versions else None
            return list(vs) if vs else []

    def _inventory(self) -> dict[bytes, list[int]]:
        """variable → timestamps, decoded from the directory listing.
        Lock-free (see :meth:`versions`): touches no shared state."""
        try:
            names = os.listdir(self.path)
        except FileNotFoundError:
            return {}
        stems: dict[str, list[int]] = {}
        for name in names:
            stem, sep, suffix = name.rpartition(".")
            if not sep:
                continue
            try:
                t = int(suffix)
            except ValueError:
                continue  # .tmp / .k sidecars
            stems.setdefault(stem, []).append(t)
        out: dict[bytes, list[int]] = {}
        for stem, ts in stems.items():
            if stem.startswith("h"):
                try:
                    with open(os.path.join(self.path, stem + ".k"), "rb") as f:
                        var = f.read()
                except OSError:
                    continue  # pre-sidecar legacy file: not enumerable
            else:
                try:
                    var = bytes.fromhex(stem)
                except ValueError:
                    continue
            out[var] = sorted(ts)
        return out

    def keys(self) -> list[bytes]:
        """Every stored variable (storage contract — anti-entropy)."""
        return list(self._inventory())

    def scan(self) -> list[tuple[bytes, int]]:
        """Every stored ``(variable, t)`` pair, one directory walk."""
        return [
            (var, t)
            for var, ts in self._inventory().items()
            for t in ts
        ]
