"""Plain-file storage: one file per version.

File name is ``hex(variable).t`` inside the store directory; the latest
version is found by scanning for the maximum ``t`` suffix
(reference: storage/plain/plain.go:28-60). Writes are atomic
(write-to-temp + rename) and the whole store is guarded by a lock the
same way the reference serializes file I/O with a mutex
(reference: storage/plain/plain.go:19).
"""

from __future__ import annotations

import os
import threading

from bftkv_tpu.errors import ERR_NOT_FOUND


class PlainStorage:
    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        os.makedirs(path, exist_ok=True)

    def _prefix(self, variable: bytes) -> str:
        # hex(variable) as the file stem (reference: plain.go:28-33), but
        # long variables would blow the 255-byte filename limit — hash them.
        if len(variable) > 96:
            import hashlib

            return "h" + hashlib.sha256(variable).hexdigest()
        return variable.hex()

    def _latest_t(self, variable: bytes) -> int | None:
        prefix = self._prefix(variable) + "."
        best: int | None = None
        try:
            names = os.listdir(self.path)
        except FileNotFoundError:
            return None
        for name in names:
            if not name.startswith(prefix):
                continue
            try:
                t = int(name[len(prefix) :])
            except ValueError:
                continue
            if best is None or t > best:
                best = t
        return best

    def read(self, variable: bytes, t: int = 0) -> bytes:
        with self._lock:
            if t == 0:
                latest = self._latest_t(variable)
                if latest is None:
                    raise ERR_NOT_FOUND
                t = latest
            fn = os.path.join(self.path, f"{self._prefix(variable)}.{t}")
            try:
                with open(fn, "rb") as f:
                    return f.read()
            except FileNotFoundError:
                raise ERR_NOT_FOUND from None

    def write(self, variable: bytes, t: int, value: bytes) -> None:
        with self._lock:
            fn = os.path.join(self.path, f"{self._prefix(variable)}.{t}")
            tmp = fn + ".tmp"
            with open(tmp, "wb") as f:
                f.write(value)
            os.replace(tmp, fn)

    def versions(self, variable: bytes) -> list[int]:
        """All stored timestamps for ``variable`` (ascending)."""
        prefix = self._prefix(variable) + "."
        out = []
        with self._lock:
            try:
                names = os.listdir(self.path)
            except FileNotFoundError:
                return out
            for name in names:
                if name.startswith(prefix) and not name.endswith(".tmp"):
                    try:
                        out.append(int(name[len(prefix) :]))
                    except ValueError:
                        continue
        return sorted(out)
