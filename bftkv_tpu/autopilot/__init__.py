"""Elastic topology autopilot (DESIGN.md §15).

Detect → decide → execute for live topology changes: epoched route
tables (``quorum/wotqs.RouteTable``), hot-shard splits, clique
retirement under traffic, and spare-replica admission — all riding the
background anti-entropy / repair planes, never the write's one-round
critical path.

- :mod:`bftkv_tpu.autopilot.plan` — pure decisions (split / retire);
- :mod:`bftkv_tpu.autopilot.daemon` — the 3-phase executor
  (pre-copy → flip → drain) and the watch loop;
- ``python -m bftkv_tpu.autopilot`` — standalone watcher over a
  ``/fleet`` endpoint (``run_cluster --autopilot`` boots it).

``BFTKV_AUTOPILOT=off`` disables automatic decisions.
"""

from bftkv_tpu.autopilot.daemon import Autopilot, autopilot_enabled
from bftkv_tpu.autopilot.plan import Plan, decide, next_table

__all__ = [
    "Autopilot",
    "Plan",
    "autopilot_enabled",
    "decide",
    "next_table",
]
