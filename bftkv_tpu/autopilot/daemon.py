"""The topology autopilot: detect → decide → execute, live.

One :class:`Autopilot` closes the loop PR 7 (detect: f-budgets, SLO
histograms, anomaly feed) and PR 9 (react primitives: repair, hedging,
health ranking) left open: it watches a fleet's health and route load,
decides (``plan.decide``) and executes topology changes while traffic
runs.  Every phase rides the background anti-entropy / repair planes —
the write's one-round critical path never waits on reconfiguration.

A migration executes in three phases (DESIGN.md §15):

1. **pre-copy** — the epoch-N+1 table (dual window open) installs on
   the NEW owners first; their sync daemons pull the moving buckets
   from the old owners (``dual_pull_shards`` widens their poll set)
   until residual divergence is at or below the watermark.  For a
   retirement, every certified record must additionally be READABLE
   from its new owner before the flip (``verify_handoff``) — the old
   clique keeps being routed to until that holds.
2. **flip** — the same table distributes fleet-wide.  Both owners
   accept the moving buckets (dual window): the new owner is the
   single serializer for NEW versions, the old owner keeps serving and
   certifying versions it already stored, and stale-routed clients
   re-route in-round off hinted declines.
3. **drain** — anti-entropy converges the window, the repair plane
   certifies residue, the new owners re-certify migrated records
   against their own cliques (``SyncDaemon.recertify_buckets``), and
   the epoch-N+2 finalize table (dual closed) goes out.  The old
   owner's copies are now inert: served if asked, never routed to,
   never synced by anyone who doesn't own them.

``BFTKV_AUTOPILOT=off`` disables decisions (the PR 8/9-style hatch);
the executor stays callable for operator-forced migrations.
"""

from __future__ import annotations

import logging
import random
import threading
import time

from bftkv_tpu.metrics import registry as metrics
from bftkv_tpu.quorum.wotqs import ROUTE_BUCKETS, RouteTable, route_bucket
from bftkv_tpu.autopilot.plan import HOT_SKEW, MIN_LOAD, Plan, decide
from bftkv_tpu import flags
from bftkv_tpu.devtools.lockwatch import named_lock

__all__ = ["Autopilot", "autopilot_enabled"]

log = logging.getLogger("bftkv_tpu.autopilot")


def autopilot_enabled() -> bool:
    """``BFTKV_AUTOPILOT`` — automatic topology decisions (default
    on).  Off disables DECIDING only; forced executes stay available."""
    return flags.raw("BFTKV_AUTOPILOT", "on").lower() not in (
        "off", "0", "false",
    )


class Autopilot:
    """In-process autopilot over a cluster of ``Server`` objects (and
    the clients that route to them).

    ``members``: every replica whose quorum system receives route
    tables; ``clients``: client objects (their quorum systems route
    writes, so they get tables too — and their ``bucket_load`` is the
    hot-bucket signal).  ``collector``: a FleetCollector for f-budget
    input; optional — load-only autopilots (benches) run without one.
    ``signer``: optional ``(private_key, certificate)`` pair; when set,
    every distributed table is signed and installs verify it."""

    #: Sync rounds per convergence attempt before giving up on the
    #: watermark (the dual window + drain close the remainder).
    MAX_SYNC_ROUNDS = 12

    def __init__(
        self,
        members: list,
        clients: list | None = None,
        *,
        collector=None,
        signer: tuple | None = None,
        watermark: int = 0,
        hot_skew: float = HOT_SKEW,
        min_load: int = MIN_LOAD,
        rng: random.Random | None = None,
    ):
        self._members = list(members)
        #: Optional provider of the CURRENT member list — the chaos
        #: harness replaces Server objects on crash-restart, and tables
        #: must reach the live instance, not a dead one's quorum system.
        self._members_provider = None
        self.clients = list(clients or [])
        #: The newest table this autopilot distributed — re-delivered
        #: to rejoining members by :meth:`reconcile`.
        self._current: RouteTable | None = None
        self.collector = collector
        self.signer = signer
        self.watermark = watermark
        self.hot_skew = hot_skew
        self.min_load = min_load
        self._rng = rng or random.Random(0)
        #: Principal names whose table delivery is suppressed — the
        #: nemesis route_flap fault window.
        self.suppressed: set[str] = set()
        self.last_decision: dict = {"kind": None}
        self.history: list[dict] = []
        self._retired: set[int] = set()
        self._lock = named_lock("autopilot")
        self._epoch_hwm = 0  # see alloc_epoch
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        if collector is not None:
            # The fleet document reports the autopilot's last decision
            # next to the budgets it decided from.
            collector.autopilot_status = self.status

    @property
    def members(self) -> list:
        if self._members_provider is not None:
            return list(self._members_provider())
        return self._members

    @classmethod
    def for_cluster(cls, cluster, collector=None, **kw) -> "Autopilot":
        """Wire an autopilot over a ChaosCluster / test Cluster: every
        server (both planes) gets tables; every client routes + feeds
        load.  Members resolve through the cluster LIVE, so a
        crash-restarted replica's fresh Server still receives tables."""
        ap = cls(
            [],
            list(cluster.clients),
            collector=collector,
            **kw,
        )
        ap._members_provider = lambda: list(cluster.all_servers)
        return ap

    # -- identity helpers --------------------------------------------------

    def _name_of(self, principal) -> str:
        node = getattr(principal, "self_node", None) or getattr(
            principal, "graph", None
        )
        return getattr(node, "name", "?") if node is not None else "?"

    def _qs_of(self, principal):
        return principal.qs

    def _servers_of_shard(self, idx: int) -> list:
        out = []
        for srv in self.members:
            qs = self._qs_of(srv)
            idx_of = getattr(qs, "shard_index_of", None)
            if idx_of is None:
                continue
            if idx_of(srv.self_node.get_self_id()) == idx:
                out.append(srv)
        return out

    def _reference_qs(self):
        for p in self.clients + self.members:
            qs = self._qs_of(p)
            if getattr(qs, "shard_count", lambda: 1)() > 1:
                return qs
        return None

    # -- distribution ------------------------------------------------------

    def _signed(self, rt: RouteTable) -> RouteTable:
        if self.signer is not None:
            key, cert = self.signer
            rt.sign(key, cert)
        return rt

    def distribute(
        self, rt: RouteTable, targets: list | None = None
    ) -> int:
        """Install ``rt`` on every (non-suppressed) target's quorum
        system; returns the number of accepting installs.  Tables are
        objects here (one process); a daemon fleet ships the same
        serialized+signed bytes — the install path verifies them
        identically."""
        installed = 0
        for p in targets if targets is not None else (
            self.members + self.clients
        ):
            if self._name_of(p) in self.suppressed:
                continue
            qs = self._qs_of(p)
            fn = getattr(qs, "install_route_table", None)
            if fn is None:
                continue
            keyring = (
                p.crypt.keyring if self.signer is not None else None
            )
            if fn(rt, keyring):
                installed += 1
        return installed

    def _base_route(self, qs) -> list[int]:
        """The bucket→shard-index base the NEXT table builds on: the
        newest table THIS autopilot issued (resolved against the
        current clique set), falling back to the reference quorum
        system's effective route.  Building on ``_current`` rather
        than on some member's installed view LINEARIZES table content:
        a route_flap window racing a migration's flip can no longer
        erase the flip's moves by building from a stale base — every
        issued table contains every earlier table's moves."""
        effective = qs.effective_route()
        cur = self._current
        if cur is None:
            return list(effective)
        cliques = qs.route_cliques()
        cid_to_idx = {c: i for i, c in enumerate(cliques)}
        owner = []
        for b in range(ROUTE_BUCKETS):
            idx = cid_to_idx.get(cur.cliques[cur.table[b]])
            owner.append(idx if idx is not None else effective[b])
        return owner

    def issue_table(
        self,
        assign: dict[int, int],
        *,
        dual: bool,
        retiring: set[int] | None = None,
        stage: bool = False,
    ) -> RouteTable:
        """Mint the next route table under ONE lock: epoch allocation
        and content derivation are atomic, so concurrent issuers (a
        migration in flight while a route_flap window ships its own
        table) produce distinct epochs whose contents CHAIN — the
        highest epoch supersedes the rest without losing their moves.
        ``dual=True`` opens the dual-epoch window for every bucket
        ``assign`` actually moves; ``dual=False`` closes every window
        (the finalize / abrupt-flap shape).

        ``stage=True`` mints a PRE-COPY table that stays OUT of the
        chain: it goes to the new owners only, and a concurrent issuer
        must not build on moves whose copy has not converged (that
        leak — a flap table inheriting an unfinished flip's moves and
        shipping them fleet-wide — is exactly how history goes
        unreadable).  The real flip re-issues at a fresh epoch."""
        qs = self._reference_qs()
        with self._lock:
            self._epoch_hwm = (
                max(self._epoch_hwm, qs.route_epoch() if qs else 0) + 1
            )
            epoch = self._epoch_hwm
            table = self._base_route(qs)
            cliques = qs.route_cliques()
            dual_map: dict[int, int] = {}
            for b, dest in assign.items():
                if table[b] != dest:
                    if dual:
                        dual_map[b] = table[b]
                    table[b] = dest
            rt = RouteTable(
                epoch, cliques, table, dual_map, retiring or set()
            )
            if not stage:
                self._current = rt
        return self._signed(rt)

    def reconcile(self) -> int:
        """Re-deliver the newest table to every member/client — how a
        crash-restarted replica (fresh quorum system, epoch 0) rejoins
        the current epoch instead of resurrecting HRW routing for
        buckets that migrated away.  Idempotent everywhere else."""
        if self._current is None:
            return 0
        return self.distribute(self._current)

    # -- detect + decide ---------------------------------------------------

    def _f_remaining(self) -> dict[int, int]:
        """Per-shard f-budget remaining, from the collector's health
        document (the same wotqs math the fleet plane reports)."""
        if self.collector is None:
            return {}
        doc = self.collector.health()
        out: dict[int, int] = {}
        for sh, sd in doc.get("shards", {}).items():
            try:
                out[int(sh)] = sd["f_budget"]["remaining"]
            except (KeyError, TypeError, ValueError):
                continue
        return out

    def _bucket_load(self) -> list[int]:
        """Client-side routed-ops per bucket, summed across clients —
        the hot-bucket signal (servers' own selections would double
        count the same traffic)."""
        load = [0] * ROUTE_BUCKETS
        for c in self.clients:
            get = getattr(self._qs_of(c), "bucket_load", None)
            if get is None:
                continue
            for b, n in enumerate(get()):
                load[b] += n
        return load

    def decide(self) -> Plan | None:
        if not autopilot_enabled():
            return None
        qs = self._reference_qs()
        if qs is None:
            return None
        owner_of = qs.effective_route()
        if not owner_of:
            return None
        plan = decide(
            self._f_remaining(),
            self._bucket_load(),
            owner_of,
            qs.shard_count(),
            hot_skew=self.hot_skew,
            min_load=self.min_load,
            retiring=set(self._retired),
        )
        return plan

    # -- execute -----------------------------------------------------------

    def _sync_daemons(self, servers: list) -> list:
        from bftkv_tpu.sync import SyncDaemon

        return [
            SyncDaemon(
                s, interval=999, rng=random.Random(self._rng.random())
            )
            for s in servers
        ]

    def _bucket_hashes(self, servers: list) -> list[dict]:
        out = []
        for s in servers:
            try:
                out.append(s._sync_tree().buckets())
            except Exception:
                out.append({})
        return out

    def _residual(
        self, moving: set[int], old_servers: list, new_servers: list
    ) -> int:
        """Moving buckets where no new owner matches any old owner's
        digest — the pre-copy divergence measure.  (Live traffic can
        keep a bucket nominally divergent forever; the watermark and
        the dual window absorb that tail.)"""
        olds = self._bucket_hashes(old_servers)
        news = self._bucket_hashes(new_servers)
        residual = 0
        for b in moving:
            have = {h.get(b) for h in olds if h.get(b) is not None}
            if not have:
                continue  # nothing stored: nothing to copy
            if not any(h.get(b) in have for h in news):
                residual += 1
        return residual

    def _converge(
        self, moving: set[int], old_servers: list, new_servers: list
    ) -> int:
        daemons = self._sync_daemons(new_servers)
        residual = len(moving)
        for _ in range(self.MAX_SYNC_ROUNDS):
            residual = self._residual(moving, old_servers, new_servers)
            if residual <= self.watermark:
                return residual
            for d in daemons:
                try:
                    d.run_round()
                except Exception:
                    log.exception("autopilot: pre-copy sync round failed")
        return self._residual(moving, old_servers, new_servers)

    def _snapshot_precopy(
        self, moving: set[int], old_servers: list, new_servers: list
    ) -> int:
        """Sealed-segment bulk ship (DESIGN.md §19.5): when an old
        owner's storage exposes ``snapshot_records``, stream its live
        records for the moving buckets straight into the new owners'
        admission path before any digest-round sync runs.  Sequential
        segment reads on the sender, the FULL admission path on the
        receiver (``admit_records`` parses, verifies, and gates every
        record — a snapshot is a transport optimization, never a trust
        shortcut).  Returns records shipped; 0 means the memory-backed
        fallback (sync rounds) does all the copying."""
        from bftkv_tpu.sync.daemon import MAX_PULL_RECORDS
        from bftkv_tpu.sync.digest import HIDDEN_PREFIX

        def pred(variable: bytes) -> bool:
            if variable.startswith(HIDDEN_PREFIX):
                return False
            return route_bucket(variable) in moving

        shipped = 0
        for old in old_servers:
            snap = getattr(old.storage, "snapshot_records", None)
            if snap is None:
                continue
            chunk: list[bytes] = []
            try:
                for _variable, _t, value in snap(pred):
                    chunk.append(value)
                    if len(chunk) >= MAX_PULL_RECORDS:
                        shipped += self._ship_chunk(chunk, new_servers)
                        chunk = []
                if chunk:
                    shipped += self._ship_chunk(chunk, new_servers)
            except Exception:
                # Snapshot source failed mid-stream (compaction race,
                # I/O fault): the digest-round sync below copies
                # whatever didn't ship — correctness never depends on
                # the fast path.
                log.exception("autopilot: snapshot pre-copy failed")
        if shipped:
            metrics.incr("autopilot.snapshot_shipped", shipped)
        return shipped

    @staticmethod
    def _ship_chunk(chunk: list[bytes], new_servers: list) -> int:
        from bftkv_tpu.sync.daemon import admit_records

        admitted = 0
        for new in new_servers:
            try:
                got = admit_records(new, chunk)
                admitted += got.get("admitted", 0)
            except Exception:
                log.exception("autopilot: snapshot admit failed")
        return admitted

    def verify_handoff(
        self,
        moving: set[int],
        old_servers: list,
        new_servers: list,
        strict: bool = True,
    ) -> list[str]:
        """The recorded-history check retirement gates on: every
        certified record an old-owner replica holds in a moving bucket
        must be READABLE (present, certified, at the same-or-newer
        timestamp) on at least one new owner.  Returns human-readable
        misses (empty = safe to stop routing to the old clique).

        ``strict=False`` (the SPLIT gate) requires existence of SOME
        certified version at the new owner rather than the newest: a
        saturating writer advances ``t`` continuously, so "caught up to
        this instant" is unreachable without pausing writes — which
        the critical path never does.  The dual-epoch window closes
        the remaining version gap via anti-entropy after the flip;
        retirement keeps the strict form (the old clique must owe
        NOTHING before it stops being routed to)."""
        from bftkv_tpu.sync.digest import HIDDEN_PREFIX, latest_completed

        misses: list[str] = []
        # Highest certified t per variable across EVERY old owner — a
        # pending-only copy on one replica must not mask the certified
        # copy on another (the write plane certifies before the sign
        # plane's residue is repaired, so the split is the common case).
        owed: dict[bytes, int] = {}
        for old in old_servers:
            # The digest tree's bucket index serves exactly the moving
            # variables — O(moving), not O(keyspace).  Fall back to the
            # full key listing only when the tree is unavailable.
            try:
                tree = old._sync_tree()
                keys = sorted(
                    v for b in moving for v in tree.bucket_variables(b)
                )
            except Exception:
                try:
                    keys = sorted(old.storage.keys())
                except Exception:
                    continue
            for variable in keys:
                if variable.startswith(HIDDEN_PREFIX):
                    continue
                if route_bucket(variable) not in moving:
                    continue
                rec = latest_completed(old.storage, variable)
                if rec is None:
                    continue  # nothing certified here: nothing owed
                if rec[0] > owed.get(variable, -1):
                    owed[variable] = rec[0]
        for variable, t_old in sorted(owed.items()):
            ok = False
            for new in new_servers:
                got = latest_completed(new.storage, variable)
                if got is not None and (not strict or got[0] >= t_old):
                    ok = True
                    break
            if not ok:
                misses.append(
                    f"{variable!r} certified at t={t_old} on the old "
                    "owners not readable from any new owner"
                )
        return misses

    def execute(self, plan: Plan, *, pace: float = 0.0) -> dict:
        """Run one plan through pre-copy → flip → drain.  ``pace``
        sleeps between phases (the chaos soak uses it to land faults
        INSIDE an in-flight migration).  Returns the phase report that
        also becomes ``last_decision``."""
        t0 = time.monotonic()
        moving = set(plan.assign)
        targets = sorted(set(plan.assign.values()))
        old_servers = self._servers_of_shard(plan.shard)
        new_servers = [
            s for idx in targets for s in self._servers_of_shard(idx)
        ]
        report: dict = {
            "kind": plan.kind,
            "shard": plan.shard,
            "targets": targets,
            "buckets": len(moving),
            "reason": plan.reason,
            "ok": False,
        }
        with self._lock:
            self.last_decision = report
            self.history.append(report)
        metrics.incr("autopilot.plans", labels={"kind": plan.kind})

        retiring = (
            {plan.shard} | self._retired
            if plan.kind == "retire"
            else set(self._retired)
        )

        # Phase 1 — pre-copy: a STAGED table (outside the issuance
        # chain) goes to the new owners only; the dual window makes
        # the moving buckets theirs to pull, and anti-entropy runs
        # until every certified record is readable from a new owner
        # (hash residual is reported, the handoff check is the gate —
        # exact digest equality is unreachable under live traffic).
        rt_stage = self.issue_table(
            plan.assign, dual=True, retiring=retiring, stage=True
        )
        strict = plan.kind == "retire"
        t_pre = time.monotonic()
        self.distribute(rt_stage, targets=new_servers)
        # §19.5 fast path first: bulk-ship sealed-segment snapshots of
        # the moving buckets through full admission, then let the
        # digest rounds close whatever the snapshot missed (records
        # appended after the seal, memory-backed old owners).
        report["snapshot_shipped"] = self._snapshot_precopy(
            moving, old_servers, new_servers
        )
        residual = self._converge(moving, old_servers, new_servers)
        misses = self.verify_handoff(
            moving, old_servers, new_servers, strict=strict
        )
        if misses:
            self._converge(moving, old_servers, new_servers)
            misses = self.verify_handoff(
                moving, old_servers, new_servers, strict=strict
            )
        report["precopy_s"] = round(time.monotonic() - t_pre, 3)
        report["residual"] = residual
        if misses:
            # The flip never outruns the copy: moving a populated
            # bucket before its certified history is readable from the
            # new owner would strand that history (readers route to
            # the new owner) — and a retiring clique must stay routed
            # to until it owes nothing.  Abort WITHOUT flipping and
            # rescind: a fresh no-move fleet table supersedes the
            # staged one everywhere, so the fleet lands back on one
            # consistent view and a later pass retries the plan.
            report["handoff_misses"] = misses[:20]
            report["aborted"] = "precopy_blocked"
            rescind = self.issue_table(
                {}, dual=False, retiring=set(self._retired)
            )
            self.distribute(rescind)
            report["rescind_epoch"] = rescind.epoch
            metrics.incr("autopilot.precopy_blocked")
            log.warning(
                "autopilot: %s of shard %d aborted: %d record(s) not "
                "yet readable from new owners",
                plan.kind, plan.shard, len(misses),
            )
            return report
        if pace:
            time.sleep(pace)

        # Phase 2 — flip: a FRESH epoch (chained on the fleet-wide
        # base, so concurrently issued tables keep their moves) goes
        # fleet-wide; stale clients re-route off hinted declines; both
        # owners hold the dual window.
        t_flip = time.monotonic()
        rt_flip = self.issue_table(
            plan.assign, dual=True, retiring=retiring
        )
        self.distribute(rt_flip)
        report["flip_s"] = round(time.monotonic() - t_flip, 3)
        report["epoch"] = rt_flip.epoch
        if pace:
            time.sleep(pace)

        # Phase 3 — drain: converge the window, certify residue,
        # re-certify migrated history against the new cliques, close.
        t_drain = time.monotonic()
        self._converge(moving, old_servers, new_servers)
        recert_failed = 0
        for attempt in range(3):
            recert_failed = 0
            for d in self._sync_daemons(new_servers):
                try:
                    got = d.recertify_buckets(moving)
                    recert_failed += got["failed"]
                except Exception:
                    recert_failed += 1
                    log.exception("autopilot: drain recertify failed")
            if recert_failed == 0:
                break
            # A fault window can make a recertify SIGN round time out;
            # the records stay readable through the dual window, so
            # retry rather than strand them.
            time.sleep(max(pace, 0.2))
        if plan.kind == "retire":
            # Forced residue repair ONLY when the old clique is going
            # away — its pending residue must certify-or-demote before
            # nobody routes to it.  A split's in-flight tails belong to
            # live writers; force-repairing them mid-write would demote
            # healthy residue the async tail is about to certify.
            for d in self._sync_daemons(old_servers):
                try:
                    d.repair_once()
                except Exception:
                    log.exception(
                        "autopilot: old-owner drain repair failed"
                    )
        if recert_failed:
            # Never close a window on un-recertified history: an
            # old-signature record would become inadmissible (alt
            # quorums empty) and its bucket permanently divergent.
            # The fleet stays consistently on the flip table — reads,
            # writes, and sync all work; a later pass closes it.
            report["drain_s"] = round(time.monotonic() - t_drain, 3)
            report["window_open"] = recert_failed
            report["elapsed_s"] = round(time.monotonic() - t0, 3)
            report["ok"] = True
            metrics.incr("autopilot.window_left_open")
            log.warning(
                "autopilot: %s done but dual window left open "
                "(%d record(s) not yet re-certified)",
                plan.kind, recert_failed,
            )
            if plan.kind == "retire":
                self._retired.add(plan.shard)
            return report
        # The finalize table chains on the flip (issue_table builds on
        # ``_current``), so re-applying ``assign`` is a no-op — what
        # changes is the dual map emptying: the window closes.
        rt_final = self.issue_table(
            plan.assign, dual=False, retiring=retiring
        )
        self.distribute(rt_final)
        report["drain_s"] = round(time.monotonic() - t_drain, 3)
        report["final_epoch"] = rt_final.epoch
        report["elapsed_s"] = round(time.monotonic() - t0, 3)
        report["ok"] = True
        if plan.kind == "retire":
            self._retired.add(plan.shard)
        metrics.incr("autopilot.migrations", labels={"kind": plan.kind})
        log.info("autopilot: %s done: %s", plan.kind, report)
        return report

    # -- spare admission ---------------------------------------------------

    def admit_spares(self, certs: list) -> int:
        """Admit quorum-certified spare replicas into every member's
        trust graph + keyring.  The graph mutation bumps
        ``graph.generation``, so every quorum/topology memo rebuilds —
        the existing guards do the invalidation work (DESIGN.md §10.3).
        Returns how many members accepted."""
        from bftkv_tpu.crypto import cert as certmod

        payload = certmod.serialize_many(certs)
        accepted = 0
        for p in self.members + self.clients:
            try:
                fresh = certmod.parse(payload)  # private copies per view
                p.self_node.add_peers(fresh)
                p.crypt.keyring.register(fresh)
                accepted += 1
            except Exception:
                log.exception(
                    "autopilot: admit failed at %s", self._name_of(p)
                )
        metrics.incr("autopilot.admitted", len(certs))
        return accepted

    # -- loop --------------------------------------------------------------

    def step(self, *, pace: float = 0.0) -> dict | None:
        """One detect→decide→execute pass (scrapes the collector when
        present).  Returns the migration report, or None when the
        topology needs nothing."""
        self.reconcile()  # rejoining members pick the current epoch up
        if self.collector is not None:
            try:
                self.collector.scrape_once()
            except Exception:
                pass
        plan = self.decide()
        if plan is None:
            return None
        return self.execute(plan, pace=pace)

    def force_split(self, shard: int | None = None, *, pace: float = 0.0) -> dict:
        """Operator/chaos hook: split ``shard`` (default: the busiest)
        in half by observed load, watermark rules intact."""
        qs = self._reference_qs()
        owner_of = qs.effective_route()
        load = self._bucket_load()
        nsh = qs.shard_count()
        if shard is None:
            shard = max(
                range(nsh),
                key=lambda i: sum(
                    load[b]
                    for b in range(ROUTE_BUCKETS)
                    if owner_of[b] == i
                ),
            )
        target = min(
            (i for i in range(nsh) if i != shard),
            key=lambda i: sum(
                load[b] for b in range(ROUTE_BUCKETS) if owner_of[b] == i
            ),
        )
        mine = sorted(
            (b for b in range(ROUTE_BUCKETS) if owner_of[b] == shard),
            key=lambda b: (-load[b], b),
        )
        assign = {b: target for b in mine[: max(1, len(mine) // 2)]}
        return self.execute(
            Plan("split", shard, assign, reason="forced split"),
            pace=pace,
        )

    def status(self) -> dict:
        qs = self._reference_qs()
        with self._lock:
            last = dict(self.last_decision)
        return {
            "enabled": autopilot_enabled(),
            "epoch": qs.route_epoch() if qs is not None else 0,
            "retired": sorted(self._retired),
            "last": last,
            "migrations": len(
                [h for h in self.history if h.get("ok")]
            ),
        }

    def start(self, interval: float = 2.0) -> "Autopilot":
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                try:
                    self.step()
                except Exception:
                    log.exception("autopilot step failed")
                self._stop.wait(interval)

        self._thread = threading.Thread(
            target=loop, name="bftkv-autopilot", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=10)
            self._thread = None
