"""``python -m bftkv_tpu.autopilot`` — standalone fleet watcher.

Consumes a fleet collector's ``/fleet`` document (cmd.fleet /
``run_cluster --fleet``) on an interval and prints the decisions the
autopilot would take — per-shard f-budget retirement triggers and
SLO-load split suggestions — as JSON lines.  Against a multi-process
fleet this mode is advisory (``--dry-run`` is the default and, for
now, the only mode): executing a migration needs the in-process
executor (:class:`bftkv_tpu.autopilot.Autopilot` — the chaos nemesis,
the benches, and ``tests/test_autopilot.py`` run it end to end), and
the daemon-fleet execute path ships the same signed
``RouteTable.serialize()`` bytes when it lands.

    python -m bftkv_tpu.autopilot --fleet-url http://127.0.0.1:7999/fleet
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.request

from bftkv_tpu.autopilot.daemon import autopilot_enabled
from bftkv_tpu.autopilot.plan import HOT_SKEW, MIN_LOAD

__all__ = ["main", "advise"]


def advise(
    doc: dict, hot_skew: float = HOT_SKEW, min_load: int = MIN_LOAD
) -> list[dict]:
    """Advisory decisions from one /fleet health document: retire any
    shard whose f-budget is spent; split the hottest shard when its
    SLO write count exceeds ``hot_skew`` × the fair share — but only
    past ``min_load`` total writes (the same twitchiness floor
    ``plan.decide`` applies: a fleet that has seen three writes has no
    meaningful skew)."""
    out: list[dict] = []
    shards = doc.get("shards", {})
    for sh, sd in sorted(shards.items()):
        fb = sd.get("f_budget") or {}
        if fb.get("remaining", 1) <= 0 and len(shards) > 1:
            out.append({
                "kind": "retire",
                "shard": int(sh),
                "reason": (
                    f"f-budget {fb.get('remaining')}/{fb.get('f')} "
                    f"(down: {','.join(fb.get('down', []))})"
                ),
            })
    loads = {
        int(sh): (sd.get("slo", {}).get("write") or {}).get("count", 0)
        for sh, sd in shards.items()
    }
    total = sum(loads.values())
    if total >= min_load and len(loads) > 1:
        hot = max(loads, key=lambda k: loads[k])
        fair = total / len(loads)
        if loads[hot] > hot_skew * fair:
            out.append({
                "kind": "split",
                "shard": hot,
                "reason": (
                    f"shard {hot} at {loads[hot]}/{total} writes "
                    f"(fair share {fair:.0f})"
                ),
            })
    return out


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        description="topology autopilot watcher (advisory, over /fleet)"
    )
    ap.add_argument("--fleet-url", required=True,
                    help="the collector's /fleet JSON endpoint")
    ap.add_argument("--interval", type=float, default=5.0)
    ap.add_argument("--hot-skew", type=float, default=HOT_SKEW,
                    help="split when the hottest shard exceeds this "
                         "multiple of the fair load share")
    ap.add_argument("--once", action="store_true",
                    help="one scrape, print advice, exit 0/3 "
                         "(3 = advice pending)")
    args = ap.parse_args(argv)

    if not autopilot_enabled():
        print(json.dumps({"autopilot": "disabled (BFTKV_AUTOPILOT=off)"}))
        return 0

    def fetch() -> dict:
        with urllib.request.urlopen(args.fleet_url, timeout=10) as r:
            return json.loads(r.read())

    if args.once:
        advice = advise(fetch(), args.hot_skew)
        print(json.dumps({"ts": time.time(), "advice": advice}))
        return 3 if advice else 0

    try:
        while True:
            try:
                advice = advise(fetch(), args.hot_skew)
                print(
                    json.dumps({"ts": time.time(), "advice": advice}),
                    flush=True,
                )
            except Exception as e:
                print(
                    json.dumps({"ts": time.time(), "error": str(e)}),
                    flush=True,
                )
            time.sleep(args.interval)
    except KeyboardInterrupt:
        # Ctrl-C mostly lands in the sleep — exit clean either way.
        return 0


if __name__ == "__main__":
    sys.exit(main())
