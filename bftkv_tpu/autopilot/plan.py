"""Autopilot decisions: pure functions from health + load to a Plan.

The detect half already exists — per-shard f-budgets and SLO
histograms (PR 7's fleet collector) and per-bucket route load
(``WotQS.bucket_load``).  This module is the *decide* half: given
those inputs, emit at most one :class:`Plan` — split a hot shard's
buckets across cliques, or drain-and-retire a clique whose f-budget is
spent.  Everything here is deterministic and side-effect free so the
same inputs always yield the same plan (the chaos soak replays them).

The *execute* half (``daemon.py``) turns a Plan into three phases —
pre-copy, flip, drain — riding the background anti-entropy/repair
planes, never the write's one-round critical path ("The Latency Price
of Threshold Cryptosystems": keep expensive coordination off the
latency-critical path).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from bftkv_tpu.quorum.wotqs import ROUTE_BUCKETS, RouteTable

__all__ = [
    "Plan",
    "decide",
    "next_table",
    "HOT_SKEW",
    "MIN_LOAD",
]

#: A shard is "hot" when its routed load reaches this multiple of the
#: fair share (total / shards).  1.6 = carrying 60% more than its
#: share.  (At 2 shards the worst case is exactly 2× fair, so a bound
#: of 2.0 could never trigger there.)
HOT_SKEW = 1.6

#: Ignore load signals below this many routed operations — deciding
#: off a handful of requests would make the autopilot twitchy.
MIN_LOAD = 32


@dataclass
class Plan:
    """One topology decision.  ``kind``: ``split`` | ``retire``.
    ``shard``: the source shard index; ``assign``: bucket → destination
    shard index for every moving bucket; ``reason``: human-readable
    trail for the fleet document."""

    kind: str
    shard: int
    assign: dict[int, int] = field(default_factory=dict)
    reason: str = ""


def _shard_loads(
    bucket_load: list[int], owner_of: list[int], nshards: int
) -> list[int]:
    loads = [0] * nshards
    for b, n in enumerate(bucket_load[:ROUTE_BUCKETS]):
        if n and 0 <= owner_of[b] < nshards:
            loads[owner_of[b]] += n
    return loads


def decide(
    f_remaining: dict[int, int],
    bucket_load: list[int],
    owner_of: list[int],
    nshards: int,
    *,
    hot_skew: float = HOT_SKEW,
    min_load: int = MIN_LOAD,
    retiring: set[int] | None = None,
) -> Plan | None:
    """At most one decision, priority ordered:

    1. **retire** — a shard whose f-budget is exhausted
       (``remaining <= 0``: as many clique members dark as the
       b-masking bound tolerates; the next fault stalls its write
       quorum or breaks masking).  All its buckets move to the
       surviving shards, spread by current load (least-loaded first).
    2. **split** — the hottest shard carries more than ``hot_skew``
       times the fair share of routed load: its hottest buckets move
       to the least-loaded shard until roughly half its load is gone.

    Returns None when the topology needs nothing (the steady state).
    """
    retiring = retiring or set()
    if nshards < 2:
        return None  # nowhere to move anything

    # -- retire: tolerance exhausted beats load every time -----------------
    for sh in sorted(f_remaining):
        if f_remaining[sh] > 0 or sh in retiring or sh >= nshards:
            continue
        survivors = [
            i
            for i in range(nshards)
            if i != sh
            and i not in retiring
            and f_remaining.get(i, 1) > 0
        ]
        if not survivors:
            return None  # no healthy destination: a human's problem
        loads = _shard_loads(bucket_load, owner_of, nshards)
        # Spread the dying clique's buckets over survivors, filling the
        # least-loaded first (simple greedy; buckets are fungible).
        assign: dict[int, int] = {}
        weights = {i: loads[i] for i in survivors}
        for b in range(ROUTE_BUCKETS):
            if owner_of[b] != sh:
                continue
            dest = min(weights, key=lambda i: (weights[i], i))
            assign[b] = dest
            weights[dest] += max(bucket_load[b], 1)
        if not assign:
            return None
        return Plan(
            kind="retire",
            shard=sh,
            assign=assign,
            reason=(
                f"shard {sh} f-budget exhausted "
                f"(remaining={f_remaining[sh]}); draining "
                f"{len(assign)} buckets to {sorted(set(assign.values()))}"
            ),
        )

    # -- split: hot-shard load rebalance -----------------------------------
    total = sum(bucket_load[:ROUTE_BUCKETS])
    if total < min_load:
        return None
    loads = _shard_loads(bucket_load, owner_of, nshards)
    hot = max(range(nshards), key=lambda i: loads[i])
    fair = total / nshards
    if loads[hot] < hot_skew * fair:
        return None
    candidates = [
        i for i in range(nshards) if i != hot and i not in retiring
    ]
    if not candidates:
        return None
    target = min(candidates, key=lambda i: (loads[i], i))
    # Move the hot shard's hottest buckets until ~half its load moved.
    hot_buckets = sorted(
        (b for b in range(ROUTE_BUCKETS) if owner_of[b] == hot),
        key=lambda b: -bucket_load[b],
    )
    moved, goal = 0, loads[hot] / 2.0
    assign = {}
    for b in hot_buckets:
        if moved >= goal or len(assign) >= len(hot_buckets) - 1:
            break
        if bucket_load[b] <= 0:
            break  # only observed-hot buckets move; cold ones stay
        assign[b] = target
        moved += bucket_load[b]
    if not assign:
        return None
    return Plan(
        kind="split",
        shard=hot,
        assign=assign,
        reason=(
            f"shard {hot} at {loads[hot]}/{total} routed ops "
            f"(fair share {fair:.0f}); moving {len(assign)} hot "
            f"buckets ({moved} ops) to shard {target}"
        ),
    )


def next_table(
    qs,
    assign: dict[int, int],
    *,
    dual: bool = True,
    retiring: set[int] | None = None,
    epoch: int | None = None,
) -> RouteTable:
    """The epoch-N+1 route table realizing ``assign`` (bucket → new
    owner shard index) on top of ``qs``'s current effective route.
    ``dual=True`` opens the dual-epoch admission window for every
    moving bucket (the flip table); ``dual=False`` closes it (the
    finalize table — and the abrupt form the route_flap fault ships).
    """
    owner = qs.effective_route()
    if not owner:
        raise ValueError("unsharded topology has no route table")
    cliques = qs.route_cliques()
    table = list(owner)
    dual_map: dict[int, int] = {}
    for b, dest in assign.items():
        if table[b] != dest:
            if dual:
                dual_map[b] = table[b]
            table[b] = dest
    return RouteTable(
        epoch=(qs.route_epoch() + 1) if epoch is None else epoch,
        cliques=cliques,
        table=table,
        dual=dual_map,
        retiring=retiring or set(),
    )
