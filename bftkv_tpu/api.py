"""High-level client API — the external-program facade.

Capability parity with the reference (api/api.go:32-239):
- :func:`open_client` — load the home keyrings, build
  graph/quorum/transport/client, join the network (api.go:32-54);
- :meth:`API.register` — decentralized enrollment: sign peer certs,
  authenticate, collect quorum signatures on our own certificate,
  merge and persist (api.go:74-147);
- password-protected :meth:`API.write`/:meth:`API.read` — wrap values
  with the TPA-derived symmetric key (api.go:149-185);
- :meth:`API.update_cert` — atomically rewrite the pubring
  (api.go:187-203);
- :meth:`API.distribute`/:meth:`API.sign` — threshold-CA passthroughs.
"""

from __future__ import annotations

import os

from bftkv_tpu import packet as pkt
from bftkv_tpu import quorum as qm
from bftkv_tpu import topology
from bftkv_tpu import transport as tp
from bftkv_tpu.crypto import cert as certmod
from bftkv_tpu.crypto import dataenc
from bftkv_tpu.errors import ERR_AUTHENTICATION_FAILURE
from bftkv_tpu.protocol.client import Client

__all__ = ["API", "open_client"]


class API:
    def __init__(self, path: str, client: Client, graph, crypt, qs, tr):
        self.path = path
        self.client = client
        self.graph = graph
        self.crypt = crypt
        self.qs = qs
        self.tr = tr

    # -- lifecycle --------------------------------------------------------

    def close(self) -> None:
        self.client.leaving()

    # -- enrollment (reference: api.go:58-147) ----------------------------

    def _sign_peers(self, cert_paths: list[str]) -> None:
        """Trust edges self→peer for each peer home dir
        (reference: api.go:58-72)."""
        for p in cert_paths:
            try:
                with open(os.path.join(p, "pubring"), "rb") as f:
                    peers = certmod.parse(f.read())
            except OSError:
                continue
            if not peers:
                continue
            peer = peers[0]
            certmod.sign_certificate(peer, self.crypt.signer.key)
            self.graph.add_nodes([peer])
            self.crypt.keyring.register([peer])

    def register(self, cert_paths: list[str], password: str) -> None:
        """Join the network and get our certificate counter-signed by a
        quorum (reference: api.go:74-147).

        Sharded namespaces: enrollment is scoped to the clique owning
        ``sha256(uid)`` — the TPA auth record for the uid lives only at
        its owner shard (every other shard's admission rejects it), so
        only that clique can verify the proof and counter-sign, and the
        resulting quorum certificate is valid for variables that clique
        owns.  Fleet-wide write access needs counter-signatures from
        every clique, which is an operator action (``genkeys`` signs
        generated users at every shard); per-shard runtime enrollment
        for one uid is an open item."""
        self._sign_peers(cert_paths)
        self.client.joining()  # construct the full graph
        self._sign_peers(cert_paths)  # re-sign: joining may overwrite

        variable = self.graph.uid.encode()
        proof, _key = self.client.authenticate(variable, password.encode())

        t = 1  # no longer temporary
        cert_blob = self.graph.serialize_self()
        tbs = pkt.serialize(variable, cert_blob, t, nfields=3)
        sig = self.crypt.signer.issue(tbs)
        req = pkt.serialize(variable, cert_blob, t, sig, proof)
        q = qm.choose_quorum_for(self.qs, variable, qm.AUTH | qm.PEER)
        signed: list[certmod.Certificate] = []
        succ: list = []

        def cb(res: tp.MulticastResponse) -> bool:
            if res.err is None and res.data:
                try:
                    certs = certmod.parse(res.data)
                except Exception:
                    return False
                signed.extend(certs)
                succ.append(res.peer)
            return False  # collect as many signatures as possible

        self.tr.multicast(tp.REGISTER, q.nodes(), req, cb)
        if not q.is_sufficient(succ):
            raise ERR_AUTHENTICATION_FAILURE

        # Fold every returned signature into our own certificate.
        self_cert = self.crypt.keyring.lookup(self.graph.id)
        for c in signed:
            if c.id == self_cert.id:
                self_cert.merge(c)
        self.graph.add_nodes([self_cert])
        # Gossip the updated certificate so servers can resolve our
        # quorum certificate on future writes (the reference defers
        # this to the next OpenClient's Joining, api_test.go:114-121),
        # and persist it so registration survives a restart.
        self.client.joining()
        self.update_cert()

    # -- data plane (reference: api.go:149-185) ---------------------------

    def write(self, variable: bytes, value: bytes, password: str = "") -> None:
        proof = None
        if password:
            proof, key = self.client.authenticate(variable, password.encode())
            value = dataenc.encrypt(value, key)
        self.client.write(variable, value, proof)

    def write_once(
        self, variable: bytes, value: bytes, password: str = ""
    ) -> None:
        """Immutable write (t = 2^64-1), with the same password
        protection as :meth:`write`."""
        proof = None
        if password:
            proof, key = self.client.authenticate(variable, password.encode())
            value = dataenc.encrypt(value, key)
        self.client.write_once(variable, value, proof)

    def write_many(
        self, items: list[tuple[bytes, bytes]]
    ) -> list[Exception | None]:
        """Batched write of distinct, password-free variables — one
        protocol round trip per phase for the whole batch
        (:meth:`bftkv_tpu.protocol.client.Client.write_many`).
        Password-protected variables need per-variable TPA proofs; use
        :meth:`write` for those."""
        return self.client.write_many(items)

    def read_many(self, variables: list[bytes]) -> list:
        """Batched read of password-free variables; one entry per
        variable — value bytes, ``None``, or the per-item error."""
        return self.client.read_many(variables)

    def read(self, variable: bytes, password: str = "") -> bytes | None:
        proof = None
        key = None
        if password:
            proof, key = self.client.authenticate(variable, password.encode())
        value = self.client.read(variable, proof)
        if key is not None and value:
            value = dataenc.decrypt(value, key)
        return value

    # -- maintenance ------------------------------------------------------

    def update_cert(self) -> None:
        """Atomically rewrite the pubring with the current graph view
        (reference: api.go:187-203)."""
        path = os.path.join(self.path, "pubring")
        tmp = path + "~"
        with open(tmp, "wb") as f:
            f.write(self.graph.serialize_nodes())
        os.replace(tmp, path)

    # -- threshold CA (reference: api.go:225-233) -------------------------

    def distribute(self, caname: str, key) -> None:
        self.client.distribute(caname, key)

    def sign(self, caname: str, tbs: bytes, algo, hash_name: str) -> bytes:
        return self.client.dist_sign(caname, tbs, algo, hash_name)

    @property
    def uid(self) -> str:
        return self.graph.uid


def open_client(path: str, transport_factory=None, *, join: bool = True) -> API:
    """Open a home directory and join the network
    (reference: api.go:32-54)."""
    graph, crypt, qs = topology.load_home(path)
    if transport_factory is None:
        from bftkv_tpu.transport.http import TrHTTP

        tr = TrHTTP(crypt)
    else:
        tr = transport_factory(crypt)
    client = Client(graph, qs, tr, crypt)
    if join:
        client.joining()
    return API(path, client, graph, crypt, qs, tr)
