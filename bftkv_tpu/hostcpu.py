"""Force JAX onto a virtual multi-device CPU mesh, robustly.

Test and dry-run lanes need N virtual CPU devices
(``--xla_force_host_platform_device_count``) regardless of what
accelerator plugins the ambient environment pre-registered.  Some
environments import jax at interpreter start (via ``sitecustomize``)
with an accelerator platform pre-selected, so merely setting
``JAX_PLATFORMS=cpu`` in the environment is too late: the config was
captured at import.  :func:`force_cpu` repairs this in-process:

- ensures ``XLA_FLAGS`` requests the virtual device count (honored as
  long as the CPU client has not been instantiated yet);
- drops any non-CPU PJRT backend factories so lazy backend discovery
  cannot block on accelerator initialization;
- updates ``jax.config`` (which wins over the captured env var).

Call it before the first ``jax.devices()`` / trace.  Safe to call when
jax has not been imported at all, and idempotent.
"""

from __future__ import annotations

import os

__all__ = ["force_cpu"]


def force_cpu(n_devices: int = 8) -> None:
    flags = [
        f
        for f in os.environ.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    flags.append(f"--xla_force_host_platform_device_count={n_devices}")
    os.environ["XLA_FLAGS"] = " ".join(flags)
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax  # deferred: may or may not already be imported
    import jax._src.xla_bridge as xb

    # Pallas registers TPU lowering rules at import time and refuses if
    # "tpu" is no longer a known platform — import it before the
    # factories are trimmed so interpret-mode kernels keep working on
    # the CPU lane.
    try:
        import jax.experimental.pallas  # noqa: F401
        import jax.experimental.pallas.tpu  # noqa: F401
    except Exception:
        pass

    factories = getattr(xb, "_backend_factories", None)
    if isinstance(factories, dict):
        for name in [k for k in factories if k != "cpu"]:
            factories.pop(name, None)
    jax.config.update("jax_platforms", "cpu")
