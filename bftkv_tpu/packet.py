"""Binary wire format for the versioned triple ``<x, v, t, sig, ss, auth>``.

Byte-compatible with the reference codec (reference: packet/packet.go:35-115)
so captured traffic and fixtures are portable:

- chunks are length-prefixed with a big-endian uint64; a zero-length chunk
  parses back as ``None``;
- the timestamp ``t`` is a big-endian uint64; ``t == 2**64 - 1`` marks a
  write-once value (reference: protocol/client.go:90-92);
- a signature packet is ``type(1) | version(4, BE) | completed(1) |
  chunk(data) | chunk(cert)``; type 0 parses back as ``None``
  (reference: packet/packet.go:192-235);
- ``tbs(pkt)`` is the prefix up to and including ``t`` (what the writer
  signs); ``tbss(pkt)`` additionally covers ``sig`` (what quorum members
  collectively sign) (reference: packet/packet.go:142-190).

Trailing fields may be omitted: a packet may stop after ``x``, after
``v``, after ``t``, etc., and the parser returns ``None``/``0`` defaults
for the rest — the protocol layer relies on this for short packets such
as Time requests.
"""

from __future__ import annotations

import io
import struct
from dataclasses import dataclass

from bftkv_tpu.errors import ERR_MALFORMED_REQUEST
from bftkv_tpu import flags

SIGNATURE_TYPE_NIL = 0
SIGNATURE_TYPE_NATIVE = 1  # our compact cert/signature format
# One byte on the wire (the reference's 256 constant never fits its own
# byte-typed field; we assign a real byte value instead).
SIGNATURE_TYPE_PASSWORD_AUTH_PROOF = 2

WRITE_ONCE_T = 2**64 - 1


@dataclass
class SignaturePacket:
    """A writer or collective signature (reference: packet/packet.go:25-31)."""

    type: int = SIGNATURE_TYPE_NATIVE
    version: int = 0
    completed: bool = True
    data: bytes | None = None
    cert: bytes | None = None


def write_chunk(buf: io.BytesIO, chunk: bytes | None) -> None:
    chunk = chunk or b""
    buf.write(struct.pack(">Q", len(chunk)))
    buf.write(chunk)


def _read_u64(r: io.BytesIO) -> int:
    """Read a big-endian uint64; EOFError at a clean boundary, protocol
    error on a torn header."""
    hdr = r.read(8)
    if len(hdr) == 0:
        raise EOFError
    if len(hdr) < 8:
        raise ERR_MALFORMED_REQUEST
    return struct.unpack(">Q", hdr)[0]


def read_chunk(r: io.BytesIO) -> bytes | None:
    length = _read_u64(r)
    if length == 0:
        return None
    # Bound-check before read: a hostile 2^63-scale prefix must be a clean
    # protocol error, not an OverflowError out of BytesIO.
    if length > len(r.getbuffer()) - r.tell():
        raise ERR_MALFORMED_REQUEST
    return r.read(length)


def _write_signature(buf: io.BytesIO, sig: SignaturePacket | None) -> None:
    if sig is None:
        sig = SignaturePacket(type=SIGNATURE_TYPE_NIL, completed=False)
    if not 0 <= sig.type <= 0xFF:
        raise ValueError(f"signature type {sig.type} does not fit one byte")
    buf.write(struct.pack(">BI?", sig.type, sig.version, sig.completed))
    write_chunk(buf, sig.data)
    write_chunk(buf, sig.cert)


def _read_signature(r: io.BytesIO) -> SignaturePacket | None:
    hdr = r.read(6)
    if len(hdr) == 0:
        raise EOFError
    if len(hdr) < 6:
        raise ERR_MALFORMED_REQUEST
    typ, version, completed = struct.unpack(">BI?", hdr)
    data = read_chunk(r)
    cert = read_chunk(r)
    if typ == SIGNATURE_TYPE_NIL:
        return None
    return SignaturePacket(
        type=typ, version=version, completed=completed, data=data, cert=cert
    )


# -- offset-based readers (hot path) ---------------------------------------
# The stream readers above stay for callers that genuinely consume a
# stream (keyring files, multi-record buffers).  The per-packet parsers
# below run thousands of times per batch handler call; a BytesIO per
# chunk was the top Python cost in the handler profile, so they walk
# (bytes, offset) instead.  Semantics are pinned equal to the stream
# readers by tests/test_packet_fuzz.py.


def _chunk_at(b: bytes, off: int) -> tuple[bytes | None, int]:
    n = len(b)
    if off == n:
        raise EOFError
    if off + 8 > n:
        raise ERR_MALFORMED_REQUEST
    length = int.from_bytes(b[off : off + 8], "big")
    off += 8
    if length == 0:
        return None, off
    if length > n - off:
        raise ERR_MALFORMED_REQUEST
    return b[off : off + length], off + length


def _u64_at(b: bytes, off: int) -> tuple[int, int]:
    n = len(b)
    if off == n:
        raise EOFError
    if off + 8 > n:
        raise ERR_MALFORMED_REQUEST
    return int.from_bytes(b[off : off + 8], "big"), off + 8


def _signature_at(b: bytes, off: int) -> tuple[SignaturePacket | None, int]:
    n = len(b)
    if off == n:
        raise EOFError
    if off + 6 > n:
        raise ERR_MALFORMED_REQUEST
    typ, version, completed = struct.unpack_from(">BI?", b, off)
    data, off = _chunk_at(b, off + 6)
    cert, off = _chunk_at(b, off)
    if typ == SIGNATURE_TYPE_NIL:
        return None, off
    return (
        SignaturePacket(
            type=typ, version=version, completed=completed,
            data=data, cert=cert,
        ),
        off,
    )


def serialize(
    variable: bytes,
    value: bytes | None = None,
    t: int | None = None,
    sig: SignaturePacket | None = None,
    ss: SignaturePacket | None = None,
    auth: bytes | None = None,
    *,
    nfields: int | None = None,
) -> bytes:
    """Serialize ``<x, v, t, sig, ss, auth>`` (reference: packet/packet.go:35-60).

    ``nfields`` limits how many leading fields are emitted (default: all six),
    mirroring the reference's variadic ``Serialize(x)``, ``Serialize(x, v)``,
    ... call shapes.
    """
    if nfields is None:
        nfields = 6
    buf = io.BytesIO()
    if nfields >= 1:
        write_chunk(buf, variable)
    if nfields >= 2:
        write_chunk(buf, value)
    if nfields >= 3:
        buf.write(struct.pack(">Q", t or 0))
    if nfields >= 4:
        _write_signature(buf, sig)
    if nfields >= 5:
        _write_signature(buf, ss)
    if nfields >= 6:
        write_chunk(buf, auth)
    return buf.getvalue()


@dataclass
class Packet:
    """Parsed ``<x, v, t, sig, ss, auth>`` with defaults for omitted tails."""

    variable: bytes | None = None
    value: bytes | None = None
    t: int = 0
    sig: SignaturePacket | None = None
    ss: SignaturePacket | None = None
    auth: bytes | None = None

    def serialize(self, nfields: int | None = None) -> bytes:
        return serialize(
            self.variable or b"",
            self.value,
            self.t,
            self.sig,
            self.ss,
            self.auth,
            nfields=nfields,
        )


def parse(pkt: bytes) -> Packet:
    """Parse a packet, tolerating omitted *trailing* fields. EOF before the
    first field is a malformed request — the reference only forgives EOF
    after ``variable`` (reference: packet/packet.go:62-115)."""
    out = Packet()
    try:
        out.variable, off = _chunk_at(pkt, 0)
    except EOFError:
        raise ERR_MALFORMED_REQUEST from None
    try:
        out.value, off = _chunk_at(pkt, off)
        out.t, off = _u64_at(pkt, off)
        out.sig, off = _signature_at(pkt, off)
        out.ss, off = _signature_at(pkt, off)
        out.auth, off = _chunk_at(pkt, off)
    except EOFError:
        pass
    return out


def _tbs_offset(pkt: bytes) -> int:
    """Offset just past ``t`` (reference: packet/packet.go:142-154)."""
    try:
        off = 0
        for _ in range(2):  # variable, value
            _, off = _chunk_at(pkt, off)
        off += 8  # timestamp
        if off > len(pkt):
            raise EOFError
    except EOFError:
        raise ERR_MALFORMED_REQUEST from None
    return off


def tbs(pkt: bytes) -> bytes:
    """Bytes covered by the writer signature (reference: packet/packet.go:156-168)."""
    return pkt[: _tbs_offset(pkt)]


def tbss(pkt: bytes) -> bytes:
    """Bytes covered by the collective signature
    (reference: packet/packet.go:170-190)."""
    off = _tbs_offset(pkt)
    try:
        _sig, end = _signature_at(pkt, off)
    except EOFError:
        raise ERR_MALFORMED_REQUEST from None
    return pkt[:end]


def parse_signature(pkt: bytes) -> SignaturePacket | None:
    try:
        return _signature_at(pkt, 0)[0]
    except EOFError:
        raise ERR_MALFORMED_REQUEST from None


def serialize_signature(sig: SignaturePacket | None) -> bytes:
    buf = io.BytesIO()
    _write_signature(buf, sig)
    return buf.getvalue()


def serialize_auth_request(phase: int, variable: bytes, adata: bytes) -> bytes:
    """(reference: packet/packet.go:266-278)"""
    buf = io.BytesIO()
    buf.write(bytes([phase & 0xFF]))
    write_chunk(buf, variable)
    write_chunk(buf, adata)
    return buf.getvalue()


def parse_auth_request(pkt: bytes) -> tuple[int, bytes | None, bytes | None]:
    """(reference: packet/packet.go:250-264)"""
    r = io.BytesIO(pkt)
    b = r.read(1)
    if len(b) < 1:
        raise ERR_MALFORMED_REQUEST
    phase = b[0]
    try:
        variable = read_chunk(r)
        adata = read_chunk(r)
    except EOFError:
        raise ERR_MALFORMED_REQUEST from None
    return phase, variable, adata


def serialize_list(items: list[bytes]) -> bytes:
    """Count-prefixed list of chunks — the payload of the batch commands
    (no reference analog; the reference carries one request per round)."""
    buf = io.BytesIO()
    buf.write(struct.pack(">I", len(items)))
    for it in items:
        write_chunk(buf, it)
    return buf.getvalue()


def parse_list(data: bytes) -> list[bytes]:
    if len(data) < 4:
        raise ERR_MALFORMED_REQUEST
    count = int.from_bytes(data[:4], "big")
    # Each item needs at least an 8-byte length header after the count.
    if count > (len(data) - 4) // 8:
        raise ERR_MALFORMED_REQUEST
    out: list[bytes] = []
    off = 4
    for _ in range(count):
        try:
            c, off = _chunk_at(data, off)
        except EOFError:
            raise ERR_MALFORMED_REQUEST from None
        out.append(c or b"")
    return out


def serialize_results(results: list[tuple[str | None, bytes]]) -> bytes:
    """Per-item outcomes of a batch command: ``(error_message | None,
    payload)`` per item.  Error strings round-trip through the interned
    error registry exactly like the x-error header does."""
    items = []
    for err, payload in results:
        if err is None:
            items.append(b"\x00" + payload)
        else:
            items.append(b"\x01" + err.encode())
    return serialize_list(items)


def parse_results(data: bytes) -> list[tuple[str | None, bytes]]:
    out: list[tuple[str | None, bytes]] = []
    for it in parse_list(data):
        if not it:
            raise ERR_MALFORMED_REQUEST
        if it[0] == 0:
            out.append((None, it[1:]))
        else:
            out.append((it[1:].decode(errors="replace"), b""))
    return out


# -- anti-entropy codecs (bftkv_tpu/sync; no reference analog) -------------
# A digest is the non-empty buckets of a replica's keyspace digest tree:
# count-prefixed entries of ``bucket_id(1) | bucket_hash(32)``.  A pull
# request names bucket ids (one byte each); a pull response is a
# count-prefixed list of raw stored records (full packets).  All three
# ride the existing list codec so the C fast path applies.

DIGEST_HASH_LEN = 32


def serialize_digest(buckets: dict[int, bytes]) -> bytes:
    items = [
        bytes([b]) + h for b, h in sorted(buckets.items()) if h is not None
    ]
    return serialize_list(items)


def parse_digest(data: bytes) -> dict[int, bytes]:
    """Inverse of :func:`serialize_digest`.  Entries of the wrong shape
    are a protocol error — digests come from untrusted peers, and a
    torn entry must not silently alias an empty bucket."""
    out: dict[int, bytes] = {}
    items = parse_list(data)
    if len(items) > 256:
        raise ERR_MALFORMED_REQUEST
    for it in items:
        if len(it) != 1 + DIGEST_HASH_LEN:
            raise ERR_MALFORMED_REQUEST
        out[it[0]] = it[1:]
    return out


def serialize_bucket_ids(ids: list[int]) -> bytes:
    return serialize_list([bytes([b]) for b in ids])


def parse_bucket_ids(data: bytes) -> list[int]:
    out = []
    items = parse_list(data)
    if len(items) > 256:
        raise ERR_MALFORMED_REQUEST
    for it in items:
        if len(it) != 1:
            raise ERR_MALFORMED_REQUEST
        out.append(it[0])
    return out


# -- round-collapsed write ack (piggyback plane; no reference analog) ------
# A WRITE_SIGN responder answers with ONE of:
#   accept:  0x00 | serialized SignaturePacket share (empty for a
#            storage-plane node that holds no seat in the sign quorum —
#            its ack counts toward the write threshold only);
#   decline: 0x01 | u64 stored_t — the responder's current timestamp
#            for the variable.  The client's optimistic timestamp was
#            stale; it retries the SAME round at max(stored_t)+1, which
#            is what lets the separate TIME round disappear from the
#            steady-state write.  A decline is NOT an error reply: the
#            legacy error tunnel (x-error header) carries no payload,
#            and the hint is the whole point.

WS_ACCEPT = 0
WS_DECLINE_T = 1


def serialize_ws_ack(
    share: bytes | None = None, decline_t: int | None = None
) -> bytes:
    if decline_t is not None:
        return bytes([WS_DECLINE_T]) + struct.pack(">Q", decline_t)
    return bytes([WS_ACCEPT]) + (share or b"")


def parse_ws_ack(data: bytes) -> tuple[int, bytes, int]:
    """``(status, share_bytes, stored_t)``; the irrelevant half of the
    pair is ``b""`` / ``0``.  Anything malformed is a protocol error —
    acks come from untrusted peers."""
    if not data:
        raise ERR_MALFORMED_REQUEST
    if data[0] == WS_ACCEPT:
        return WS_ACCEPT, data[1:], 0
    if data[0] == WS_DECLINE_T:
        if len(data) != 9:
            raise ERR_MALFORMED_REQUEST
        return WS_DECLINE_T, b"", struct.unpack(">Q", data[1:])[0]
    raise ERR_MALFORMED_REQUEST


# -- trace-context envelope (observability plane; no reference analog) -----
# The transport fan-out prepends this to the PLAINTEXT payload before
# session encryption, so a request's trace context crosses nodes (and
# processes) without touching the HTTP surface or the session layer;
# Server.handler strips it right after decrypt.  Unambiguous against
# every legitimate payload: a packet starts with the 8-byte big-endian
# length of ``variable``, bound-checked against the buffer, so its
# first byte is 0x00 for any packet under 2^56 bytes — 0xff can never
# begin a valid packet — and an auth request starts with a phase byte
# that conforming clients keep tiny.

TRACE_MAGIC = b"\xffTRC"
_TRACE_HDR = len(TRACE_MAGIC) + 16


def wrap_trace(trace_id: int, span_id: int, payload: bytes) -> bytes:
    return (
        TRACE_MAGIC
        + trace_id.to_bytes(8, "big")
        + span_id.to_bytes(8, "big")
        + payload
    )


def unwrap_trace(data: bytes) -> tuple[tuple[int, int] | None, bytes]:
    """``(context, payload)``: context is ``(trace_id, span_id)`` when
    the envelope is present, else None with the data untouched."""
    if len(data) >= _TRACE_HDR and data[: len(TRACE_MAGIC)] == TRACE_MAGIC:
        return (
            (
                int.from_bytes(data[4:12], "big"),
                int.from_bytes(data[12:20], "big"),
            ),
            data[_TRACE_HDR:],
        )
    return None, data


def write_bigint(buf: io.BytesIO, n: int | None) -> None:
    """(reference: packet/packet.go:288-294)"""
    if n is None:
        write_chunk(buf, b"")
        return
    if n < 0:
        raise ValueError("write_bigint: negative")
    length = (n.bit_length() + 7) // 8
    write_chunk(buf, n.to_bytes(length, "big"))


def read_bigint(r: io.BytesIO) -> int:
    """(reference: packet/packet.go:280-286)"""
    try:
        c = read_chunk(r)
    except EOFError:
        raise ERR_MALFORMED_REQUEST from None
    return int.from_bytes(c or b"", "big")


# -- optional C codec -------------------------------------------------------
# The per-packet codec is the top Python cost in the batch handlers
# (docs/PERFORMANCE.md "Handler Python ceiling").  native/packetcodec.c
# implements the identical grammar; the pure-Python functions above
# stay as the fallback AND as the semantics oracle the fuzz tests
# compare against (tests/test_packet_fuzz.py).  Disable with
# BFTKV_NATIVE_CODEC=off.

_py_parse = parse
_py_tbs = tbs
_py_tbss = tbss
_py_parse_signature = parse_signature
_py_parse_list = parse_list
_py_serialize = serialize
_py_serialize_signature = serialize_signature


def _load_native_codec():
    import importlib.util
    import os
    import subprocess
    import sysconfig

    if flags.raw("BFTKV_NATIVE_CODEC", "auto") == "off":
        return None
    nd = os.path.abspath(
        os.path.join(os.path.dirname(__file__), "..", "native")
    )
    try:
        # Resolve headers and the ABI tag from the RUNNING interpreter
        # (a PATH python3 may be a different version — its .so would be
        # ABI-incompatible), and serialize concurrent builders (N
        # daemons starting at once must not write the same .so).
        inc = sysconfig.get_paths()["include"]
        suffix = sysconfig.get_config_var("EXT_SUFFIX")
        so_path = os.path.join(nd, f"_packetcodec{suffix}")
        src = os.path.join(nd, "packetcodec.c")
        if not os.path.exists(so_path) or (
            os.path.getmtime(so_path) < os.path.getmtime(src)
        ):
            import fcntl

            with open(os.path.join(nd, ".codec.lock"), "w") as lk:
                fcntl.flock(lk, fcntl.LOCK_EX)
                subprocess.run(
                    [
                        "make", "-s", "codec",
                        f"PY_INC={inc}", f"EXT_SUFFIX={suffix}",
                    ],
                    cwd=nd, check=True, capture_output=True,
                )
        spec = importlib.util.spec_from_file_location(
            "bftkv_tpu._packetcodec", so_path
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        mod.set_malformed(ERR_MALFORMED_REQUEST)
        return mod
    except Exception:
        return None


_C = _load_native_codec()

if _C is not None:

    def _sig_from_tuple(t):
        if t is None:
            return None
        return SignaturePacket(
            type=t[0], version=t[1], completed=t[2], data=t[3], cert=t[4]
        )

    def _sig_to_tuple(s):
        if s is None:
            return None
        if not 0 <= s.type <= 0xFF:
            raise ValueError(
                f"signature type {s.type} does not fit one byte"
            )
        return (s.type, s.version, s.completed, s.data, s.cert)

    def parse(pkt: bytes) -> Packet:  # noqa: F811
        variable, value, t, sig, ss, auth = _C.parse(pkt)
        return Packet(
            variable=variable,
            value=value,
            t=t,
            sig=_sig_from_tuple(sig),
            ss=_sig_from_tuple(ss),
            auth=auth,
        )

    def tbs(pkt: bytes) -> bytes:  # noqa: F811
        return pkt[: _C.tbs_offset(pkt)]

    def tbss(pkt: bytes) -> bytes:  # noqa: F811
        return pkt[: _C.tbss_end(pkt)]

    def parse_signature(pkt: bytes) -> SignaturePacket | None:  # noqa: F811
        return _sig_from_tuple(_C.parse_signature(pkt))

    def parse_list(data: bytes) -> list[bytes]:  # noqa: F811
        return _C.parse_list(data)

    def serialize(  # noqa: F811
        variable: bytes,
        value: bytes | None = None,
        t: int | None = None,
        sig: SignaturePacket | None = None,
        ss: SignaturePacket | None = None,
        auth: bytes | None = None,
        *,
        nfields: int | None = None,
    ) -> bytes:
        return _C.serialize(
            variable,
            value,
            t or 0,
            _sig_to_tuple(sig) if nfields is None or nfields >= 4 else None,
            _sig_to_tuple(ss) if nfields is None or nfields >= 5 else None,
            auth,
            6 if nfields is None else nfields,
        )

    def serialize_signature(sig: SignaturePacket | None) -> bytes:  # noqa: F811
        return _C.serialize_signature(_sig_to_tuple(sig))
