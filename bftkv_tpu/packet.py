"""Binary wire format for the versioned triple ``<x, v, t, sig, ss, auth>``.

Byte-compatible with the reference codec (reference: packet/packet.go:35-115)
so captured traffic and fixtures are portable:

- chunks are length-prefixed with a big-endian uint64; a zero-length chunk
  parses back as ``None``;
- the timestamp ``t`` is a big-endian uint64; ``t == 2**64 - 1`` marks a
  write-once value (reference: protocol/client.go:90-92);
- a signature packet is ``type(1) | version(4, BE) | completed(1) |
  chunk(data) | chunk(cert)``; type 0 parses back as ``None``
  (reference: packet/packet.go:192-235);
- ``tbs(pkt)`` is the prefix up to and including ``t`` (what the writer
  signs); ``tbss(pkt)`` additionally covers ``sig`` (what quorum members
  collectively sign) (reference: packet/packet.go:142-190).

Trailing fields may be omitted: a packet may stop after ``x``, after
``v``, after ``t``, etc., and the parser returns ``None``/``0`` defaults
for the rest — the protocol layer relies on this for short packets such
as Time requests.
"""

from __future__ import annotations

import io
import struct
from dataclasses import dataclass

from bftkv_tpu.errors import ERR_MALFORMED_REQUEST

SIGNATURE_TYPE_NIL = 0
SIGNATURE_TYPE_NATIVE = 1  # our compact cert/signature format
# One byte on the wire (the reference's 256 constant never fits its own
# byte-typed field; we assign a real byte value instead).
SIGNATURE_TYPE_PASSWORD_AUTH_PROOF = 2

WRITE_ONCE_T = 2**64 - 1


@dataclass
class SignaturePacket:
    """A writer or collective signature (reference: packet/packet.go:25-31)."""

    type: int = SIGNATURE_TYPE_NATIVE
    version: int = 0
    completed: bool = True
    data: bytes | None = None
    cert: bytes | None = None


def write_chunk(buf: io.BytesIO, chunk: bytes | None) -> None:
    chunk = chunk or b""
    buf.write(struct.pack(">Q", len(chunk)))
    buf.write(chunk)


def _read_u64(r: io.BytesIO) -> int:
    """Read a big-endian uint64; EOFError at a clean boundary, protocol
    error on a torn header."""
    hdr = r.read(8)
    if len(hdr) == 0:
        raise EOFError
    if len(hdr) < 8:
        raise ERR_MALFORMED_REQUEST
    return struct.unpack(">Q", hdr)[0]


def read_chunk(r: io.BytesIO) -> bytes | None:
    length = _read_u64(r)
    if length == 0:
        return None
    # Bound-check before read: a hostile 2^63-scale prefix must be a clean
    # protocol error, not an OverflowError out of BytesIO.
    if length > len(r.getbuffer()) - r.tell():
        raise ERR_MALFORMED_REQUEST
    return r.read(length)


def _write_signature(buf: io.BytesIO, sig: SignaturePacket | None) -> None:
    if sig is None:
        sig = SignaturePacket(type=SIGNATURE_TYPE_NIL, completed=False)
    if not 0 <= sig.type <= 0xFF:
        raise ValueError(f"signature type {sig.type} does not fit one byte")
    buf.write(struct.pack(">BI?", sig.type, sig.version, sig.completed))
    write_chunk(buf, sig.data)
    write_chunk(buf, sig.cert)


def _read_signature(r: io.BytesIO) -> SignaturePacket | None:
    hdr = r.read(6)
    if len(hdr) == 0:
        raise EOFError
    if len(hdr) < 6:
        raise ERR_MALFORMED_REQUEST
    typ, version, completed = struct.unpack(">BI?", hdr)
    data = read_chunk(r)
    cert = read_chunk(r)
    if typ == SIGNATURE_TYPE_NIL:
        return None
    return SignaturePacket(
        type=typ, version=version, completed=completed, data=data, cert=cert
    )


def serialize(
    variable: bytes,
    value: bytes | None = None,
    t: int | None = None,
    sig: SignaturePacket | None = None,
    ss: SignaturePacket | None = None,
    auth: bytes | None = None,
    *,
    nfields: int | None = None,
) -> bytes:
    """Serialize ``<x, v, t, sig, ss, auth>`` (reference: packet/packet.go:35-60).

    ``nfields`` limits how many leading fields are emitted (default: all six),
    mirroring the reference's variadic ``Serialize(x)``, ``Serialize(x, v)``,
    ... call shapes.
    """
    if nfields is None:
        nfields = 6
    buf = io.BytesIO()
    if nfields >= 1:
        write_chunk(buf, variable)
    if nfields >= 2:
        write_chunk(buf, value)
    if nfields >= 3:
        buf.write(struct.pack(">Q", t or 0))
    if nfields >= 4:
        _write_signature(buf, sig)
    if nfields >= 5:
        _write_signature(buf, ss)
    if nfields >= 6:
        write_chunk(buf, auth)
    return buf.getvalue()


@dataclass
class Packet:
    """Parsed ``<x, v, t, sig, ss, auth>`` with defaults for omitted tails."""

    variable: bytes | None = None
    value: bytes | None = None
    t: int = 0
    sig: SignaturePacket | None = None
    ss: SignaturePacket | None = None
    auth: bytes | None = None

    def serialize(self, nfields: int | None = None) -> bytes:
        return serialize(
            self.variable or b"",
            self.value,
            self.t,
            self.sig,
            self.ss,
            self.auth,
            nfields=nfields,
        )


def parse(pkt: bytes) -> Packet:
    """Parse a packet, tolerating omitted *trailing* fields. EOF before the
    first field is a malformed request — the reference only forgives EOF
    after ``variable`` (reference: packet/packet.go:62-115)."""
    r = io.BytesIO(pkt)
    out = Packet()
    try:
        out.variable = read_chunk(r)
    except EOFError:
        raise ERR_MALFORMED_REQUEST from None
    try:
        out.value = read_chunk(r)
        out.t = _read_u64(r)
        out.sig = _read_signature(r)
        out.ss = _read_signature(r)
        out.auth = read_chunk(r)
    except EOFError:
        pass
    return out


def _tbs_offset(pkt: bytes) -> int:
    """Offset just past ``t`` (reference: packet/packet.go:142-154)."""
    r = io.BytesIO(pkt)
    try:
        for _ in range(2):  # variable, value
            length = _read_u64(r)
            if length > len(pkt) - r.tell():
                raise ERR_MALFORMED_REQUEST
            r.seek(length, io.SEEK_CUR)
    except EOFError:
        raise ERR_MALFORMED_REQUEST from None
    r.seek(8, io.SEEK_CUR)  # timestamp
    off = r.tell()
    if off > len(pkt):
        raise ERR_MALFORMED_REQUEST
    return off


def tbs(pkt: bytes) -> bytes:
    """Bytes covered by the writer signature (reference: packet/packet.go:156-168)."""
    return pkt[: _tbs_offset(pkt)]


def tbss(pkt: bytes) -> bytes:
    """Bytes covered by the collective signature
    (reference: packet/packet.go:170-190)."""
    off = _tbs_offset(pkt)
    r = io.BytesIO(pkt)
    r.seek(off)
    try:
        _read_signature(r)
    except EOFError:
        raise ERR_MALFORMED_REQUEST from None
    end = r.tell()
    if end > len(pkt):
        raise ERR_MALFORMED_REQUEST
    return pkt[:end]


def parse_signature(pkt: bytes) -> SignaturePacket | None:
    try:
        return _read_signature(io.BytesIO(pkt))
    except EOFError:
        raise ERR_MALFORMED_REQUEST from None


def serialize_signature(sig: SignaturePacket | None) -> bytes:
    buf = io.BytesIO()
    _write_signature(buf, sig)
    return buf.getvalue()


def serialize_auth_request(phase: int, variable: bytes, adata: bytes) -> bytes:
    """(reference: packet/packet.go:266-278)"""
    buf = io.BytesIO()
    buf.write(bytes([phase & 0xFF]))
    write_chunk(buf, variable)
    write_chunk(buf, adata)
    return buf.getvalue()


def parse_auth_request(pkt: bytes) -> tuple[int, bytes | None, bytes | None]:
    """(reference: packet/packet.go:250-264)"""
    r = io.BytesIO(pkt)
    b = r.read(1)
    if len(b) < 1:
        raise ERR_MALFORMED_REQUEST
    phase = b[0]
    try:
        variable = read_chunk(r)
        adata = read_chunk(r)
    except EOFError:
        raise ERR_MALFORMED_REQUEST from None
    return phase, variable, adata


def serialize_list(items: list[bytes]) -> bytes:
    """Count-prefixed list of chunks — the payload of the batch commands
    (no reference analog; the reference carries one request per round)."""
    buf = io.BytesIO()
    buf.write(struct.pack(">I", len(items)))
    for it in items:
        write_chunk(buf, it)
    return buf.getvalue()


def parse_list(data: bytes) -> list[bytes]:
    r = io.BytesIO(data)
    hdr = r.read(4)
    if len(hdr) < 4:
        raise ERR_MALFORMED_REQUEST
    (count,) = struct.unpack(">I", hdr)
    # Each item needs at least an 8-byte length header after the count.
    if count > (len(data) - 4) // 8:
        raise ERR_MALFORMED_REQUEST
    out: list[bytes] = []
    for _ in range(count):
        try:
            out.append(read_chunk(r) or b"")
        except EOFError:
            raise ERR_MALFORMED_REQUEST from None
    return out


def serialize_results(results: list[tuple[str | None, bytes]]) -> bytes:
    """Per-item outcomes of a batch command: ``(error_message | None,
    payload)`` per item.  Error strings round-trip through the interned
    error registry exactly like the x-error header does."""
    items = []
    for err, payload in results:
        if err is None:
            items.append(b"\x00" + payload)
        else:
            items.append(b"\x01" + err.encode())
    return serialize_list(items)


def parse_results(data: bytes) -> list[tuple[str | None, bytes]]:
    out: list[tuple[str | None, bytes]] = []
    for it in parse_list(data):
        if not it:
            raise ERR_MALFORMED_REQUEST
        if it[0] == 0:
            out.append((None, it[1:]))
        else:
            out.append((it[1:].decode(errors="replace"), b""))
    return out


def write_bigint(buf: io.BytesIO, n: int | None) -> None:
    """(reference: packet/packet.go:288-294)"""
    if n is None:
        write_chunk(buf, b"")
        return
    if n < 0:
        raise ValueError("write_bigint: negative")
    length = (n.bit_length() + 7) // 8
    write_chunk(buf, n.to_bytes(length, "big"))


def read_bigint(r: io.BytesIO) -> int:
    """(reference: packet/packet.go:280-286)"""
    try:
        c = read_chunk(r)
    except EOFError:
        raise ERR_MALFORMED_REQUEST from None
    return int.from_bytes(c or b"", "big")
