"""Process-wide interned error registry.

Errors travel between client and server as strings (the transport tunnels
them in an ``x-error`` header) and must map back to the *identical* error
value on the far side so protocol code can compare and count them.
Capability parity with the reference's interned error map
(reference: bftkv.go:12-48), done the Python way: each error is a distinct
``Error`` *subclass* interned by message. That makes all of these work:

- ``raise ERR_BAD_TIMESTAMP`` — raises a fresh instance (no shared
  traceback state between concurrent raises);
- ``except ERR_BAD_TIMESTAMP:`` — catch a specific error;
- ``except Error as e:`` — catch any protocol error; ``e`` compares equal
  to the interned value and to any error with the same message, so errors
  can key dicts for majority-vote counting
  (reference: protocol/client.go:28-50).
"""

from __future__ import annotations

from bftkv_tpu.devtools.lockwatch import named_lock


def _message_of(obj: object) -> str | None:
    m = getattr(obj, "message", None)
    return m if isinstance(m, str) else None


class _ErrorMeta(type):
    """Make error *classes* compare/hash by message, so a class, an
    instance of it, and a re-parsed wire error are all interchangeable."""

    def __eq__(cls, other: object) -> bool:
        other_m = _message_of(other)
        return other_m is not None and other_m == cls.message

    def __ne__(cls, other: object) -> bool:
        return not cls.__eq__(other)

    def __hash__(cls) -> int:
        return hash(cls.message)

    def __repr__(cls) -> str:  # pragma: no cover
        return f"Error({cls.message!r})"


class Error(Exception, metaclass=_ErrorMeta):
    """Base class for all bftkv_tpu errors."""

    message: str = "error"

    def __init__(self, message: str | None = None):
        if message is not None:
            self.message = message
        super().__init__(self.message)

    def __eq__(self, other: object) -> bool:
        other_m = _message_of(other)
        return other_m is not None and other_m == self.message

    def __ne__(self, other: object) -> bool:
        return not self.__eq__(other)

    def __hash__(self) -> int:
        return hash(self.message)

    def __repr__(self) -> str:  # pragma: no cover
        return f"Error({self.message!r})"


_registry: dict[str, type[Error]] = {}
_lock = named_lock("errors.intern")


def new_error(message: str) -> type[Error]:
    """Create (or fetch) the interned error class for ``message``."""
    with _lock:
        err = _registry.get(message)
        if err is None:
            name = "Err_" + "".join(
                c if c.isalnum() else "_" for c in message
            )
            err = _ErrorMeta(name, (Error,), {"message": message})
            _registry[message] = err
        return err


def error_from_string(message: str) -> type[Error]:
    """Map a wire string back to the interned error value
    (reference: bftkv.go:40-48)."""
    return new_error(message)


# The shared error vocabulary (reference: bftkv.go:12-29).
ERR_INSUFFICIENT_NUMBER_OF_QUORUM = new_error("insufficient number of quorum")
ERR_INSUFFICIENT_NUMBER_OF_RESPONSES = new_error("insufficient number of responses")
ERR_INSUFFICIENT_NUMBER_OF_VALID_RESPONSES = new_error(
    "insufficient number of valid responses"
)
ERR_INVALID_QUORUM_CERTIFICATE = new_error("invalid quorum certificate")
ERR_INVALID_TIMESTAMP = new_error("invalid timestamp")
ERR_INVALID_SIGN_REQUEST = new_error("invalid signature request")
ERR_PERMISSION_DENIED = new_error("permission denied")
ERR_BAD_TIMESTAMP = new_error("bad timestamp")
ERR_EQUIVOCATION = new_error("equivocation error")
ERR_INVALID_VARIABLE = new_error("invalid variable")
ERR_UNKNOWN_COMMAND = new_error("unknown command")
ERR_MALFORMED_REQUEST = new_error("malformed request")
ERR_NO_MORE_WRITE = new_error("no more write")
ERR_AUTHENTICATION_FAILURE = new_error("authentication failure")
ERR_EXIST = new_error("already exist")
ERR_INVALID_USER_ID = new_error("invalid user ID")
ERR_INVALID_RESPONSE = new_error("invalid response")

# Crypto-layer errors (reference: crypto/crypto.go:16-33).
ERR_CERTIFICATE_NOT_FOUND = new_error("certificate not found")
ERR_KEY_NOT_FOUND = new_error("key not found")
ERR_INVALID_SIGNATURE = new_error("invalid signature")
ERR_INSUFFICIENT_NUMBER_OF_SIGNATURES = new_error(
    "insufficient number of signatures"
)
ERR_INVALID_TRANSPORT_SECURITY_DATA = new_error(
    "invalid transport security data"
)
ERR_NO_AUTHENTICATION_DATA = new_error("no authentication data")
ERR_INVALID_AUTHENTICATION_DATA = new_error("invalid authentication data")
ERR_TOO_MANY_ATTEMPTS = new_error("too many authentication attempts")
ERR_UNSUPPORTED_ALGORITHM = new_error("unsupported algorithm")
ERR_SHARE_NOT_FOUND = new_error("share not found")
ERR_INSUFFICIENT_NUMBER_OF_SECRETS = new_error("insufficient number of secrets")
ERR_CONTINUE = new_error("continue")  # threshold phase loop sentinel
ERR_DECRYPTION_FAILURE = new_error("decryption failure")
# Session-keyed transport (this framework's addition, no reference
# analog): the receiver no longer holds the pairwise session the sender
# used; the sender re-bootstraps on seeing this.
ERR_UNKNOWN_SESSION = new_error("unknown transport session")

# Keyspace sharding (this framework's addition, no reference analog):
# the variable hash-routes to a quorum clique this replica is not a
# member of — an honest client never sees this, a misrouted or
# Byzantine request dies in admission.
ERR_WRONG_SHARD = new_error("wrong shard")


def wrong_shard_error(
    epoch: int | None = None, owner: int | None = None
) -> type[Error]:
    """The wrong-shard decline, optionally carrying a routing hint:
    the responder's route-table epoch and the owning shard index, so a
    stale-route client re-routes in-round instead of failing.  The
    bare form is kept for legacy servers (and for epoch-0 fleets,
    where there is nothing to hint) — both intern and tunnel through
    the x-error header like any other protocol error."""
    if epoch is None or owner is None:
        return ERR_WRONG_SHARD
    return new_error(f"wrong shard epoch={int(epoch)} owner={int(owner)}")


def parse_wrong_shard(err: object) -> tuple[int | None, int | None] | None:
    """``None`` if ``err`` is not a wrong-shard decline; else the
    ``(epoch, owner)`` hint — ``(None, None)`` for the bare legacy
    form.  Accepts error classes, instances, and wire strings."""
    m = _message_of(err)
    if m is None and isinstance(err, str):
        m = err
    if m is None or not m.startswith("wrong shard"):
        return None
    rest = m[len("wrong shard"):].strip()
    if not rest:
        return (None, None)
    out: dict[str, int] = {}
    for part in rest.split():
        k, sep, v = part.partition("=")
        if sep and v.isdigit():
            out[k] = int(v)
    if "epoch" in out and "owner" in out:
        return (out["epoch"], out["owner"])
    return (None, None)

# Edge gateway tier (this framework's addition, no reference analog):
# the gateway's bounded admission queue is full — the caller should
# back off or try another gateway; quorum state is untouched.
ERR_GATEWAY_OVERLOADED = new_error("gateway overloaded")
# A gateway fill whose collective signature failed verification against
# the owner quorum: the record is never cached and never served.
ERR_UNCERTIFIED_RECORD = new_error("uncertified record")

# Storage errors (reference: storage/storage.go).
ERR_NOT_FOUND = new_error("not found")

# Transport errors.
ERR_TRANSPORT = new_error("transport failure")
ERR_NONCE_MISMATCH = new_error("nonce mismatch")
