"""RSA verification in a residue number system — MXU/f32-native bignum.

The limb kernels (:mod:`bftkv_tpu.ops.bigint`, the Pallas variant) are
bound by *emulated* 32-bit integer multiplies on the VPU — a 128-limb
Montgomery product is a 128-step convolution of digit products, and
every digit product pays the int32-mul emulation tax. This module
removes both the convolution and the integer arithmetic:

- numbers live as residues modulo ~2k primes of 11-12 bits (two RNS
  bases B, B' plus a 2^12 redundant channel), so multiplication is
  *channelwise*: one native f32 multiply per lane (products < 2^24 are
  exactly representable) plus a Barrett reduction — f32 reciprocal,
  floor, and ≤2 conditional fixups, all native VPU ops;
- Montgomery reduction (Bajard et al.) needs two base extensions per
  product; each is Σ_i σ_i·(M/p_i mod target) — a matrix product whose
  matrix depends only on the prime bases, NOT the data → it runs on
  the MXU as four *exact* f32 matmuls (operands split into 6-bit
  halves, so every partial sum stays < 2^24);
- the B→B' extension is approximate (off by α·M, α < k — harmless:
  the bases carry ~200 bits of slack over 2048-bit moduli), while the
  B'→B return extension is made *exact* with the Shenoy–Kumaresan
  correction through the 2^12 redundant channel, keeping the bases
  consistent;
- the final check needs no RNS→positional conversion: with
  v ≡ s^e (mod N) and v < (k+1)·N, Δ_j = (v_j − em_j)·N⁻¹ mod p_j is
  the same small integer α = (v − em)/N in *every* channel iff the
  signature is valid; ~2k independent channels cannot agree otherwise.
"""

from __future__ import annotations

import functools
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np

from bftkv_tpu.ops import devbuf
from bftkv_tpu.ops import limb
from bftkv_tpu import flags

__all__ = [
    "RNSContext",
    "context",
    "verify_e65537_rns",
    "flat_verify_fn",
    "stack_key_rows",
    "assemble_key_rows",
    "digits_to_halves",
]

PR_BITS = 12
PR = 1 << PR_BITS  # redundant modulus (power of two)
DIGITS = 128  # 16-bit digits per 2048-bit number
SPLIT = 6  # matmul operand split (values < 64: f32 partials stay exact)


def _gen_primes(lo: int, hi: int) -> list[int]:
    sieve = np.ones(hi - lo, dtype=bool)
    for p in range(2, int(hi**0.5) + 1):
        start = max(p * p, ((lo + p - 1) // p) * p)
        sieve[start - lo :: p] = False
    return [int(lo + i) for i in np.nonzero(sieve)[0]]


class RNSContext:
    """Shared (key-independent) precomputation for one digit width."""

    def __init__(self, digits: int = DIGITS, n_bits: int = 2048):
        # All primes below 2^12, largest first; two interleaved bases
        # so both get ~equal bit mass. Each base must clear n_bits by a
        # healthy margin (the AMM slack analysis needs M > (k+2)^2 N).
        primes = [p for p in _gen_primes(1 << 10, 1 << PR_BITS)][::-1]
        need = n_bits + 64
        self.pb: list[int] = []
        self.pq: list[int] = []
        bits_b = bits_q = 0.0
        for p in primes:
            if bits_b <= bits_q:
                self.pb.append(p)
                bits_b += np.log2(p)
            else:
                self.pq.append(p)
                bits_q += np.log2(p)
            if bits_b > need and bits_q > need:
                break
        else:
            raise ValueError("not enough sub-2^12 primes for the bases")
        # Equal channel counts keep the matmul shapes square-ish.
        k = min(len(self.pb), len(self.pq))
        self.pb, self.pq = self.pb[:k], self.pq[:k]
        self.k = k
        self.digits = digits
        self.M = 1
        for p in self.pb:
            self.M *= p
        self.Mq = 1
        for q in self.pq:
            self.Mq *= q
        if self.M <= (1 << need) or self.Mq <= (1 << need):
            raise ValueError("base bit mass too small")

        f = lambda xs: np.asarray(xs, dtype=np.float32)
        self.p_all = f(self.pb + self.pq)
        self.inv_all = np.float32(1.0) / self.p_all  # Barrett reciprocals

        # --- extension B -> B' (+ redundant channel) ------------------
        Mi = [self.M // p for p in self.pb]
        self.invMi_b = f([pow(Mi[i] % p, -1, p) for i, p in enumerate(self.pb)])
        E1 = np.zeros((k, k + 1), dtype=np.int64)
        for i in range(k):
            for j, q in enumerate(self.pq):
                E1[i, j] = Mi[i] % q
            E1[i, k] = Mi[i] % PR
        self._E1 = self._split6(E1)

        # --- extension B' -> B (+ redundant channel, Shenoy) ----------
        Mqj = [self.Mq // q for q in self.pq]
        self.invMi_q = f([pow(Mqj[j] % q, -1, q) for j, q in enumerate(self.pq)])
        E2 = np.zeros((k, k + 1), dtype=np.int64)
        for j in range(k):
            for i, p in enumerate(self.pb):
                E2[j, i] = Mqj[j] % p
            E2[j, k] = Mqj[j] % PR
        self._E2 = self._split6(E2)
        self.Mq_mod_b = f([self.Mq % p for p in self.pb])
        self.invMq_pr = np.float32(pow(self.Mq % PR, -1, PR))
        self.invM_q = f([pow(self.M % q, -1, q) for q in self.pq])
        self.invM_pr = np.float32(pow(self.M % PR, -1, PR))

        # --- digit -> residue conversion ------------------------------
        # Digits are 16-bit; split each into two 8-bit halves so the
        # conversion matmul operands stay < 2^8 (f32 partial sums over
        # 256 half-digits < 256·255·2^12 ≈ 2^26 — too big; split the
        # *matrix* to 6 bits instead and the data to 8: partials
        # < 256·255·63 ≈ 2^22 — exact).
        D = np.zeros((2 * digits, 2 * k + 1), dtype=np.int64)
        for d in range(digits):
            w_lo = pow(1 << 16, d)
            w_hi = (w_lo << 8)
            for ch, p in enumerate(self.pb + self.pq):
                D[2 * d, ch] = w_lo % p
                D[2 * d + 1, ch] = w_hi % p
            D[2 * d, 2 * k] = w_lo % PR
            D[2 * d + 1, 2 * k] = w_hi % PR
        self._D = self._split6(D)

    @staticmethod
    def _split6(m: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """12-bit entries → two 6-bit f32 planes."""
        return (
            (m & 63).astype(np.float32),
            (m >> 6).astype(np.float32),
        )

    # -- per-key (per modulus N) data, host side ------------------------

    @functools.lru_cache(maxsize=4096)
    def key_rows(self, n: int):
        """Channel constants for one public modulus ``n`` (cached).

        Returns None for modulo that cannot ride the RNS path: even,
        too wide for the digit budget, or sharing a factor with a
        channel prime — real RSA moduli never do, but certificates are
        attacker-supplied, so such keys must fall back, not raise.
        """
        if n <= 0 or n % 2 == 0 or n.bit_length() > 16 * self.digits:
            return None
        chans = self.pb + self.pq
        for p in chans:
            if n % p == 0:
                return None
        f = lambda xs: np.asarray(xs, dtype=np.float32)
        n_all = f([n % p for p in chans])
        n_r = np.float32(n % PR)
        neg_ninv_b = f([(-pow(n, -1, p)) % p for p in self.pb])
        ninv_all = f([pow(n % p, -1, p) for p in chans])
        m2 = (self.M * self.M) % n
        m2_all = f([m2 % p for p in chans])
        m2_r = np.float32(m2 % PR)
        return n_all, n_r, neg_ninv_b, ninv_all, m2_all, m2_r


@functools.lru_cache(maxsize=4)
def context(digits: int = DIGITS, n_bits: int = 2048) -> RNSContext:
    return RNSContext(digits, n_bits)


# ---------------------------------------------------------------------------
# Device side. All tensors are f32 holding exact integers < 2^24;
# channels ride the last axis. One number = (xb (T,k), xq (T,k), xr (T,1)).
# ---------------------------------------------------------------------------

_PRF = np.float32(PR)
_INV_PRF = np.float32(1.0 / PR)


def _barrett(x, inv_p, p):
    """x mod p for integral f32 x < 2^24; exact via reciprocal + fixups."""
    q = jnp.floor(x * inv_p)
    r = x - q * p
    r = jnp.where(r < 0, r + p, r)
    r = jnp.where(r < 0, r + p, r)
    r = jnp.where(r >= p, r - p, r)
    r = jnp.where(r >= p, r - p, r)
    return r


def _mulmod(a, b, inv_p, p):
    return _barrett(a * b, inv_p, p)


def _addmod(a, b, p):
    s = a + b
    return jnp.where(s >= p, s - p, s)


def _submod(a, b, p):
    d = a - b
    return jnp.where(d < 0, d + p, d)


def _mod_r(x):
    """x mod 2^12 for integral f32 x < 2^24 (exact)."""
    return x - jnp.floor(x * _INV_PRF) * _PRF


def _mulmod_r(a, b):
    return _mod_r(a * b)


def _matmul_f32(x, m_split):
    """Exact Σ_i x[i]·M[i,j] via bf16 MXU matmuls with f32 accumulate.

    ``x`` (T,rows) f32 integral < 2^12, split into 6-bit halves; the
    matrix is pre-split.  Every operand is < 64, which bf16 represents
    exactly (8 significant bits), and the MXU multiplies bf16 natively
    into an f32 accumulator — one systolic pass per dot instead of
    XLA's multi-pass f32 emulation.  Partial products < 2^12, summed
    over ≤ 400 rows < 2^21 — exact.  Returns (s_ll, s_mid, s_hh).
    """
    mlo, mhi = m_split
    xlo = x - jnp.floor(x * np.float32(1 / 64)) * 64  # x & 63, f32-exact
    xhi = jnp.floor(x * np.float32(1 / 64))
    dot = lambda a, b: jax.lax.dot_general(
        a.astype(jnp.bfloat16),
        b.astype(jnp.bfloat16),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    s_ll = dot(xlo, mlo)
    s_mid = dot(xlo, mhi) + dot(xhi, mlo)
    s_hh = dot(xhi, mhi)
    return s_ll, s_mid, s_hh


def _combine_mod(s_ll, s_mid, s_hh, inv_p, p):
    """(s_ll + 2^6·s_mid + 2^12·s_hh) mod p, channelwise, f32-exact.

    Partials < 2^22; reduce each below p (< 2^12) before shifting so
    every intermediate stays < 2^24."""
    a = _barrett(s_ll, inv_p, p)
    b = _barrett(s_mid, inv_p, p)
    d = _barrett(s_hh, inv_p, p)
    b6 = _barrett(b * 64, inv_p, p)
    d12 = _barrett(_barrett(d * 64, inv_p, p) * 64, inv_p, p)
    return _addmod(_addmod(a, b6, p), d12, p)


def _combine_mod_r(s_ll, s_mid, s_hh):
    return _mod_r(_mod_r(s_ll) + _mod_r(s_mid * 64) + _mod_r(_mod_r(s_hh * 64) * 64))


class _Consts:
    """Device-resident context constants bundled for one jit call."""

    def __init__(self, ctx: RNSContext):
        self.k = ctx.k
        j = jnp.asarray
        self.pb = j(ctx.p_all[: ctx.k])
        self.pq = j(ctx.p_all[ctx.k :])
        self.ib = j(ctx.inv_all[: ctx.k])
        self.iq = j(ctx.inv_all[ctx.k :])
        self.invMi_b = j(ctx.invMi_b)
        self.invMi_q = j(ctx.invMi_q)
        self.E1 = (j(ctx._E1[0]), j(ctx._E1[1]))
        self.E2 = (j(ctx._E2[0]), j(ctx._E2[1]))
        self.D = (j(ctx._D[0]), j(ctx._D[1]))
        self.Mq_mod_b = j(ctx.Mq_mod_b)
        self.invMq_pr = jnp.float32(ctx.invMq_pr)
        self.invM_q = j(ctx.invM_q)
        self.invM_pr = jnp.float32(ctx.invM_pr)


def _mont_mul(cn, a, b, key):
    """RNS Montgomery product (Bajard AMM + Shenoy return extension)."""
    ab, aq, ar = a
    bb, bq, br = b
    n_all, n_r, neg_ninv_b, _ninv, _m2, _m2r = key
    k = cn.k
    nq = n_all[:, k:]

    db = _mulmod(ab, bb, cn.ib, cn.pb)
    dq = _mulmod(aq, bq, cn.iq, cn.pq)
    dr = _mulmod_r(ar, br)

    # q = d·(−N⁻¹) mod M, channelwise in B.
    qb = _mulmod(db, neg_ninv_b, cn.ib, cn.pb)
    # Approximate extension of q̂ = Σ σ_i·M_i (= q + α₁M) to B' ∪ {2^12}.
    sigma = _mulmod(qb, cn.invMi_b, cn.ib, cn.pb)
    s_ll, s_mid, s_hh = _matmul_f32(sigma, cn.E1)
    qhat_q = _combine_mod(s_ll[:, :k], s_mid[:, :k], s_hh[:, :k], cn.iq, cn.pq)
    qhat_r = _combine_mod_r(s_ll[:, k:], s_mid[:, k:], s_hh[:, k:])

    # r = (d + q̂·N)/M in B' and the redundant channel.
    t = _mulmod(qhat_q, nq, cn.iq, cn.pq)
    rq = _mulmod(_addmod(dq, t, cn.pq), cn.invM_q, cn.iq, cn.pq)
    tr = _mulmod_r(qhat_r, n_r)
    rr = _mulmod_r(_mod_r(dr + tr), cn.invM_pr)

    # Exact extension of r from B' back to B (Shenoy via 2^12 channel).
    sigma2 = _mulmod(rq, cn.invMi_q, cn.iq, cn.pq)
    z_ll, z_mid, z_hh = _matmul_f32(sigma2, cn.E2)
    ext_b = _combine_mod(z_ll[:, :k], z_mid[:, :k], z_hh[:, :k], cn.ib, cn.pb)
    ext_r = _combine_mod_r(z_ll[:, k:], z_mid[:, k:], z_hh[:, k:])
    alpha = _mulmod_r(_mod_r(ext_r - rr + _PRF), cn.invMq_pr)
    corr = _mulmod(
        jnp.broadcast_to(alpha, ext_b.shape),
        jnp.broadcast_to(cn.Mq_mod_b, ext_b.shape),
        cn.ib,
        cn.pb,
    )
    rb = _submod(ext_b, corr, cn.pb)
    return rb, rq, rr


def _to_residues(cn, digit_halves):
    """(T, 256) 8-bit digit halves → residues over [B | B' | 2^12]."""
    s_ll, s_mid, s_hh = _matmul_f32(digit_halves, cn.D)
    k = cn.k
    xb = _combine_mod(s_ll[:, :k], s_mid[:, :k], s_hh[:, :k], cn.ib, cn.pb)
    xq = _combine_mod(
        s_ll[:, k : 2 * k], s_mid[:, k : 2 * k], s_hh[:, k : 2 * k],
        cn.iq, cn.pq,
    )
    xr = _combine_mod_r(s_ll[:, 2 * k :], s_mid[:, 2 * k :], s_hh[:, 2 * k :])
    return xb, xq, xr


def _verify_kernel(cn: _Consts, sig_halves, em_halves, key):
    n_all, n_r, neg_ninv_b, ninv_all, m2_all, m2_r = key
    k = cn.k
    s = _to_residues(cn, sig_halves)
    em_b, em_q, _em_r = _to_residues(cn, em_halves)

    m2 = (m2_all[:, :k], m2_all[:, k:], m2_r)
    sm = _mont_mul(cn, s, m2, key)  # to Montgomery form

    acc = sm
    for _ in range(16):
        acc = _mont_mul(cn, acc, acc, key)
    acc = _mont_mul(cn, acc, sm, key)

    one = (jnp.ones_like(sm[0]), jnp.ones_like(sm[1]), jnp.ones_like(sm[2]))
    vb, vq, _vr = _mont_mul(cn, acc, one, key)  # v ≡ s^e (mod N), v < (k+1)N

    # Δ_j = (v_j − em_j)·N⁻¹ mod p_j: the same small α in every channel
    # iff v ≡ em (mod N).
    delta_b = _mulmod(_submod(vb, em_b, cn.pb), ninv_all[:, :k], cn.ib, cn.pb)
    delta_q = _mulmod(_submod(vq, em_q, cn.pq), ninv_all[:, k:], cn.iq, cn.pq)
    alpha = delta_b[:, :1]
    ok = jnp.all(delta_b == alpha, axis=1) & jnp.all(delta_q == alpha, axis=1)
    return ok & (alpha[:, 0] <= cn.k + 1)


def flat_verify_fn():
    """The verify step with a flat signature — the public jittable for
    drivers and benchmarks (the graft entry / shard_map wrap it):
    ``f(sig_h, em_h, n_all, n_r, neg_ninv_b, ninv_all, m2_all, m2_r)``.
    """
    cn = _Consts(context())

    def f(sig_h, em_h, n_all, n_r, neg_ninv_b, ninv_all, m2_all, m2_r):
        return _verify_kernel(
            cn, sig_h, em_h, (n_all, n_r, neg_ninv_b, ninv_all, m2_all, m2_r)
        )

    return f


@functools.lru_cache(maxsize=1)
def _jitted_verify():
    f = flat_verify_fn()

    @jax.jit
    def g(sig_halves, em_halves, key):
        return f(sig_halves, em_halves, *key)

    return g


@functools.lru_cache(maxsize=1)
def _jitted_verify_gather():
    """Verify with device-side key gather and uint8 operands.

    The per-row key tensors are ~12 KB each; a cluster flush repeats a
    handful of distinct keys thousands of times, and on a tunneled TPU
    the host→device transfer dwarfs the kernel (~440 ms vs ~64 ms at
    batch 4096).  Shipping (K, ·) unique-key tensors plus a (T,) index
    and casting u8→f32 on device cuts the transfer ~12x.
    """
    cn = _Consts(context())

    @jax.jit
    def g(sig_halves_u8, em_halves_u8, idx, ukey):
        key = tuple(u[idx] for u in ukey)
        return _verify_kernel(
            cn,
            sig_halves_u8.astype(jnp.float32),
            em_halves_u8.astype(jnp.float32),
            key,
        )

    return g


# ---------------------------------------------------------------------------
# General modexp in RNS — the signing hot path (CRT halves of RSA keys).
#
# Unlike verify (fixed e = 65537), exponents here are per-row secrets up
# to the modulus width.  Fixed 4-bit windows keep the schedule uniform
# across the batch: every step is 4 squarings plus one multiply by a
# table entry selected with a one-hot matvec (no data-dependent control
# flow, no gather) — constant-time by construction, SURVEY §7 hard
# part 3 applied to modexp.  The AMM invariant (inputs < (k+2)N keep
# outputs < (k+2)N when M > (k+2)²N) is iteration-stable, so a
# 256-step chain needs no extra slack over verify's 18-step chain.
# ---------------------------------------------------------------------------


def _pow_kernel(cn: _Consts, base_halves, exp_nibbles_t, key):
    """acc = base^exp mod N per row; returns CRT coefficients σ over B.

    ``exp_nibbles_t``: (W, T) f32 most-significant-nibble first.
    """
    k = cn.k
    m2 = (key[4][:, :k], key[4][:, k:], key[5])

    def one_like(x):
        return (
            jnp.ones_like(x[0]),
            jnp.ones_like(x[1]),
            jnp.ones_like(x[2]),
        )

    base = _to_residues(cn, base_halves)
    ones = one_like(base)
    base_m = _mont_mul(cn, base, m2, key)  # to Montgomery form
    one_m = _mont_mul(cn, m2, ones, key)  # M mod N, the Montgomery one

    # 16-entry window table in Montgomery form: t[w] = base^w.
    tab = [one_m, base_m]
    for _ in range(14):
        tab.append(_mont_mul(cn, tab[-1], base_m, key))
    tb = jnp.stack([t[0] for t in tab], axis=1)  # (T, 16, k)
    tq = jnp.stack([t[1] for t in tab], axis=1)
    tr = jnp.stack([t[2] for t in tab], axis=1)  # (T, 16, 1)

    def body(acc, nib):
        for _ in range(4):
            acc = _mont_mul(cn, acc, acc, key)
        oh = jax.nn.one_hot(nib.astype(jnp.int32), 16, dtype=jnp.float32)
        sel = (
            jnp.einsum("tw,twc->tc", oh, tb),
            jnp.einsum("tw,twc->tc", oh, tq),
            jnp.einsum("tw,twc->tc", oh, tr),
        )
        return _mont_mul(cn, acc, sel, key), None

    acc, _ = jax.lax.scan(body, one_m, exp_nibbles_t)
    vb, _vq, _vr = _mont_mul(cn, acc, ones, key)  # out of Montgomery form
    # CRT coefficients: σ_i = v_i·(M_i⁻¹ mod p_i); host side rebuilds
    # v = Σ σ_i·M_i (< M, no α ambiguity since v < (k+1)·N ≪ M).
    return _mulmod(vb, cn.invMi_b, cn.ib, cn.pb)


@functools.lru_cache(maxsize=4)
def _jitted_pow(digits: int, n_bits: int, donate: bool = False):
    """uint8 operands + device-side gather of the (few) unique moduli —
    same transfer-lean scheme as the verify path.

    ``donate=True`` (accelerator backends only) donates the per-batch
    operand buffers: XLA may alias the freshly-transferred arrays into
    the kernel instead of defensively copying them — the host-side
    staging slot (:mod:`bftkv_tpu.ops.devbuf`) stays owned by the host
    and is reused for the next flush.  CPU ignores donation with a
    warning, so callers gate it on the backend."""
    cn = _Consts(context(digits, n_bits))

    @functools.partial(
        jax.jit, donate_argnums=(0, 1, 2) if donate else ()
    )
    def g(base_halves_u8, exp_nibbles_t_u8, idx, ukey):
        key = tuple(u[idx] for u in ukey)
        return _pow_kernel(
            cn,
            base_halves_u8.astype(jnp.float32),
            exp_nibbles_t_u8.astype(jnp.float32),
            key,
        )

    return g


def _crt_matrix(ctx: RNSContext) -> np.ndarray:
    """(k, D) float64 16-bit digit planes of M_i = M/p_i, cached on ctx.
    Row sums Σ σ_i·M_i stay < k·2^12·2^16 = 2^35 < 2^53: exact."""
    m = getattr(ctx, "_crt_digits", None)
    if m is None:
        width = (ctx.M.bit_length() + PR_BITS + 15) // 16 + 1
        m = np.zeros((ctx.k, width), dtype=np.float64)
        for i, p in enumerate(ctx.pb):
            m[i] = limb.int_to_limbs(ctx.M // p, width)
        ctx._crt_digits = m
    return m


def _sigma_to_ints(ctx: RNSContext, sigma: np.ndarray) -> list[int]:
    """Batched RNS→integer via a float64 digit matmul + one carry pass."""
    m = _crt_matrix(ctx)
    acc = sigma.astype(np.float64) @ m  # (T, D) digit sums < 2^35
    acc = acc.astype(np.int64)
    carry = np.zeros(acc.shape[0], dtype=np.int64)
    out = np.empty_like(acc, dtype=np.uint16)
    for d in range(acc.shape[1]):
        s = acc[:, d] + carry
        out[:, d] = (s & 0xFFFF).astype(np.uint16)
        carry = s >> 16
    vals = [
        int.from_bytes(row.tobytes(), "little") for row in out
    ]
    return [v % ctx.M for v in vals]


class DeferredModexp:
    """Handle for a non-blocking :func:`power_mod_rns` launch.

    The kernel is already on the device stream when this is returned;
    :meth:`wait` materializes the device result, rebuilds the integers,
    and releases the staging slot.  Exactly one waiter finalizes it
    (the dispatcher's completion-drain thread)."""

    __slots__ = ("_finish", "_value", "_done")

    def __init__(self, finish):
        self._finish = finish
        self._value = None
        self._done = False

    def wait(self) -> list[int]:
        if not self._done:
            self._done = True
            fin, self._finish = self._finish, None
            self._value = fin()
        return self._value


def _pow_staging(digits: int, n_bits: int, padded: int):
    """One launch's operand arrays — a persistent devbuf slot when the
    rings are on (``None`` ring → plain throwaway arrays)."""
    shapes = {
        "base_halves": ((padded, 2 * digits), np.uint8),
        "nib_t": ((4 * digits, padded), np.uint8),
        "idx": ((padded,), np.int32),
    }

    def make():
        return {k: np.empty(s, d) for k, (s, d) in shapes.items()}

    if not devbuf.enabled():
        return None, devbuf.Slot(make())
    ring = devbuf.ring_for(
        f"pow:{digits}:{n_bits}:{padded}", make, width=str(digits)
    )
    slot = ring.acquire()
    if slot is None:
        return None, ring.fresh()  # ring saturated: unpooled fallback
    return ring, slot


def power_mod_rns(
    bases: list[int], exps: list[int], mods: list[int], *,
    n_bits: int = 1024, defer: bool = False,
):
    """Batched x^e mod m with per-row (x, e, m) — the threshold-RSA /
    CRT-signing workhorse.  Returns a list of ints, or None when any
    modulus cannot ride the RNS path (caller falls back).

    ``n_bits`` bounds the modulus/exponent width; 1024 covers the CRT
    halves of RSA-2048 (reference hot loop: crypto_pgp.go:346-371,
    threshold fragments rsa.go:140-178).

    ``defer=True`` returns a :class:`DeferredModexp` instead of a list:
    the launch is dispatched but NOT blocked on, so the caller (the
    async dispatcher) can stage further width groups while the device
    works.  The staging slot stays in flight until ``wait()``.
    """
    if not mods:
        return []
    for e in exps:
        if e < 0 or e.bit_length() > n_bits:
            return None
    digits = max(32, (n_bits + 15) // 16)
    ctx = context(digits, n_bits)
    unique: dict[int, int] = {}
    urows: list = []
    idxs: list[int] = []
    for m in mods:
        u = unique.get(m)
        if u is None:
            r = ctx.key_rows(m)
            if r is None:
                return None
            u = unique[m] = len(urows)
            urows.append(r)
        idxs.append(u)
    t = len(idxs)
    # Pad the batch axis (floor 64) to power-of-two buckets so only a
    # handful of kernel shapes compile.  The unique-modulus axis gets a
    # fixed floor of 64: cross-request flushes mix many signers' p/q,
    # and every fresh (T, K) pair would recompile the 256-step scan
    # (~15-60 s); 64 padded key rows are < 1 MB of extra transfer.
    padded = max(64, 1 << (t - 1).bit_length())
    kpad = max(64, 1 << (len(urows) - 1).bit_length())
    urows += [urows[0]] * (kpad - len(urows))
    ukey = tuple(jnp.asarray(a) for a in stack_key_rows(urows))
    # Stage operands into a persistent slot (devbuf ring) or throwaway
    # arrays: ONLY the t live rows ride the int→limb→half pipeline; the
    # pad region broadcasts row 0 in place, which is bit-identical to
    # the historical pad-the-input-lists-with-item-0 convention (pad
    # base = bases[0] % mods[0] = row 0's conversion; pad unique-index
    # is 0 = row 0's by construction) without its per-pad-row bigint
    # conversions or per-launch allocations.
    ring, slot = _pow_staging(digits, n_bits, padded)
    bh, nt, ix = slot["base_halves"], slot["nib_t"], slot["idx"]
    released = False

    def _release():
        nonlocal released
        if not released:
            released = True
            if ring is not None:
                ring.release(slot)

    try:
        base_digits = np.stack(
            [limb.int_to_limbs(b % m, digits) for b, m in zip(bases, mods)]
        )
        bh[:t, 0::2] = base_digits & 0xFF
        bh[:t, 1::2] = base_digits >> 8
        ed = np.stack(
            [limb.int_to_limbs(e, digits) for e in exps]
        )  # (t, digits)
        nib = np.empty((t, digits * 4), dtype=np.uint8)
        nib[:, 0::4] = ed & 0xF  # little-endian within each 16-bit digit
        nib[:, 1::4] = (ed >> 4) & 0xF
        nib[:, 2::4] = (ed >> 8) & 0xF
        nib[:, 3::4] = (ed >> 12) & 0xF
        nt[:, :t] = nib[:, ::-1].T  # most-significant nibble first
        ix[:t] = np.asarray(idxs, dtype=np.int32)
        if padded > t:
            bh[t:] = bh[0:1]
            nt[:, t:] = nt[:, 0:1]
            ix[t:] = 0
        pow_args = (bh, nt, ix, ukey)
        sigma = None
        if _use_pallas("BFTKV_RNS_POW_BACKEND"):
            try:
                from bftkv_tpu.ops import pallas_rns

                sigma = np.asarray(
                    pallas_rns.pow_pallas(
                        *pow_args, digits=digits, n_bits=n_bits
                    )
                )[:t]
                _pallas_mark_proven("pow")
            except Exception as e:
                # A Mosaic compile/runtime failure must degrade to the
                # XLA kernel, not sink the sign path — but loudly: a
                # silent fallback would misattribute every benchmark
                # number.
                import logging

                _PALLAS_STATUS["pow"] = f"fallback: {type(e).__name__}"
                logging.getLogger("bftkv_tpu.ops.rns").exception(
                    "pallas pow kernel failed; falling back to XLA"
                )
        if sigma is not None:
            _release()
            vals = _sigma_to_ints(ctx, sigma)
            res = [v % m for v, m in zip(vals, mods)]
            return DeferredModexp(lambda: res) if defer else res
        if _shardable(padded):
            fn = _jitted_pow_sharded(digits, n_bits)
        else:
            # Donation only pays (and only works) on real accelerators;
            # see _jitted_pow.
            fn = _jitted_pow(
                digits, n_bits,
                donate=jax.default_backend() in ("tpu", "gpu"),
            )
        dev = fn(*pow_args)  # jax dispatch is async: not a result yet
        mods_live = list(mods)

        def finish() -> list[int]:
            try:
                s = np.asarray(dev)[:t]
            finally:
                # Materialized (or launch failed): the device no longer
                # reads the staging arrays either way.
                _release()
            vals = _sigma_to_ints(ctx, s)
            return [v % m for v, m in zip(vals, mods_live)]

        if defer:
            # Slot ownership moves to the handle: finish() releases it.
            return DeferredModexp(finish)
        return finish()
    except BaseException:
        _release()
        raise


def digits_to_halves(digits_u32: np.ndarray) -> np.ndarray:
    """(T, 128) 16-bit digits → (T, 256) interleaved 8-bit halves (f32)."""
    t = digits_u32.shape[0]
    out = np.empty((t, 2 * digits_u32.shape[1]), dtype=np.float32)
    out[:, 0::2] = (digits_u32 & 0xFF).astype(np.float32)
    out[:, 1::2] = (digits_u32 >> 8).astype(np.float32)
    return out


def digits_to_halves_u8(digits_u32: np.ndarray) -> np.ndarray:
    """Same as :func:`digits_to_halves` but uint8 — 4x less wire for
    host→device transfer; the kernel casts to f32 on device."""
    t = digits_u32.shape[0]
    out = np.empty((t, 2 * digits_u32.shape[1]), dtype=np.uint8)
    out[:, 0::2] = (digits_u32 & 0xFF).astype(np.uint8)
    out[:, 1::2] = (digits_u32 >> 8).astype(np.uint8)
    return out


def verify_e65537_rns(sig_digits, em_digits, key_rows) -> jnp.ndarray:
    """Batched RSA e=65537 verify in RNS.

    ``sig_digits``/``em_digits``: (T, 128) uint32 16-bit digit arrays;
    ``key_rows``: stacked per-row key tensors from
    :meth:`RNSContext.key_rows` — (n_all (T,2k), n_r (T,1),
    neg_ninv_b (T,k), ninv_all (T,2k), m2_all (T,2k), m2_r (T,1)).
    """
    sig_h = digits_to_halves(np.asarray(sig_digits))
    em_h = digits_to_halves(np.asarray(em_digits))
    return _jitted_verify()(sig_h, em_h, key_rows)


#: Last outcome per fused-chain entry point in THIS process:
#: "unused" (never attempted), "ok" (a pallas call completed), or
#: "fallback: <Error>" (the loud XLA fallback fired).  Bench sections
#: export this so a TPU record can never silently misattribute a
#: fallen-back XLA rate to the Pallas kernels (VERDICT r4 item 3).
_PALLAS_STATUS = {"pow": "unused", "verify": "unused"}


def pallas_status() -> dict:
    return dict(_PALLAS_STATUS)


@functools.lru_cache(maxsize=2)
def _pallas_proven_path(which: str) -> str:
    """Marker recording that fused chain ``which`` ("pow"/"verify")
    COMPLETED on real TPU for the current kernel sources + jax version
    (hash of this file and pallas_rns.py) at the current tile size —
    tile is folded in because VMEM pressure scales with it: a proof at
    tile 128 says nothing about tile 512.  Per-chain: a verify-only
    proof must not arm auto mode for a pow chain whose Mosaic compile
    fails on this hardware."""
    import hashlib

    from bftkv_tpu.ops import pallas_rns

    h = hashlib.sha256()
    for mod in (pallas_rns, sys.modules[__name__]):
        try:
            with open(mod.__file__, "rb") as f:
                h.update(f.read())
        except OSError:
            pass
    h.update(jax.__version__.encode())
    tile = (
        pallas_rns.TILE_POW if which == "pow" else pallas_rns.TILE_VERIFY
    )
    cache = os.path.expanduser("~/.cache/jax_bftkv")
    return os.path.join(
        cache, f"pallas_proven_{which}_t{tile}_{h.hexdigest()[:12]}"
    )


def _pallas_mark_proven(which: str) -> None:
    """Record a completed on-TPU pallas call (process + cross-process)."""
    if _PALLAS_STATUS[which] == "ok":
        return  # hot path: no re-hash / file I/O per flush
    _PALLAS_STATUS[which] = "ok"
    if jax.default_backend() != "tpu":
        return
    try:
        path = _pallas_proven_path(which)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "a"):
            pass
        _pallas_proven.cache_clear()  # same-process auto calls see it
    except OSError:
        pass


@functools.lru_cache(maxsize=2)
def _pallas_proven(which: str) -> bool:
    try:
        return os.path.exists(_pallas_proven_path(which))
    except Exception:
        return False


def _use_pallas(env: str) -> bool:
    """Backend choice for the fused VMEM-resident Pallas chains
    (:mod:`bftkv_tpu.ops.pallas_rns`): "auto" (default) uses them on a
    single real TPU chip — but only once a forced run has *proven* they
    complete on this hardware/kernel revision (marker file written by
    :func:`_pallas_mark_proven`; the bench's kernel sections force-prove
    before any cluster section relies on auto).  Interpret mode on CPU
    would be far slower than the XLA kernels, and on a multi-chip pool
    the sharded XLA path spreads the batch over every device (see
    :func:`_mesh`).  "pallas"/"xla" force."""
    mode = flags.raw(env, "auto")
    if mode == "pallas":
        return True
    if mode == "auto":
        which = "pow" if env == "BFTKV_RNS_POW_BACKEND" else "verify"
        return (
            jax.default_backend() == "tpu"
            and len(jax.devices()) == 1
            and _pallas_proven(which)
        )
    return False


@functools.lru_cache(maxsize=1)
def _mesh():
    """1-D device mesh over every local device, or None when sharding
    is pointless (single device) or disabled (``BFTKV_SHARD=off``).

    This is the production counterpart of the driver's
    ``dryrun_multichip`` demo: verify/sign flushes are data-parallel
    over the batch axis, so the dispatcher's launches shard across the
    replica's whole accelerator pool via ``shard_map`` — collectives
    stay strictly inside one replica's trust domain (SURVEY §5)."""
    if flags.raw("BFTKV_SHARD", "auto") == "off":
        return None
    devs = jax.devices()
    if len(devs) < 2:
        return None
    return jax.sharding.Mesh(np.array(devs), ("batch",))


def _shard_map(fn, mesh, in_specs, out_specs):
    try:
        from jax import shard_map as _sm
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map as _sm
    return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs)


@functools.lru_cache(maxsize=1)
def _jitted_verify_gather_sharded():
    """The gather-verify kernel sharded over the batch axis of the
    local device mesh; key rows replicate (they are small and shared)."""
    from jax.sharding import PartitionSpec as P

    cn = _Consts(context())
    mesh = _mesh()

    def body(sig_halves_u8, em_halves_u8, idx, ukey):
        key = tuple(u[idx] for u in ukey)
        return _verify_kernel(
            cn,
            sig_halves_u8.astype(jnp.float32),
            em_halves_u8.astype(jnp.float32),
            key,
        )

    b = P("batch")
    return jax.jit(
        _shard_map(
            body, mesh,
            in_specs=(b, b, b, (P(),) * 6),
            out_specs=b,
        )
    )


@functools.lru_cache(maxsize=4)
def _jitted_pow_sharded(digits: int, n_bits: int):
    from jax.sharding import PartitionSpec as P

    cn = _Consts(context(digits, n_bits))
    mesh = _mesh()

    def body(base_halves_u8, exp_nibbles_t_u8, idx, ukey):
        key = tuple(u[idx] for u in ukey)
        return _pow_kernel(
            cn,
            base_halves_u8.astype(jnp.float32),
            exp_nibbles_t_u8.astype(jnp.float32),
            key,
        )

    b = P("batch")
    return jax.jit(
        _shard_map(
            body, mesh,
            # exponent nibbles ride (W, T): batch is axis 1 there.
            in_specs=(b, P(None, "batch"), b, (P(),) * 6),
            out_specs=b,
        )
    )


def _shardable(batch: int) -> bool:
    mesh = _mesh()
    return mesh is not None and batch % mesh.devices.size == 0


def verify_e65537_rns_indexed(
    sig_digits, em_digits, key_idx, unique_rows
) -> jnp.ndarray:
    """Transfer-lean verify: ``unique_rows`` are stacked rows for the
    *distinct* keys only (from :func:`stack_key_rows`), ``key_idx`` maps
    each item to its key row; the gather happens on device."""
    sig_h = digits_to_halves_u8(np.asarray(sig_digits))
    em_h = digits_to_halves_u8(np.asarray(em_digits))
    idx = np.asarray(key_idx, dtype=np.int32)
    if _use_pallas("BFTKV_RNS_VERIFY_BACKEND"):
        try:
            from bftkv_tpu.ops import pallas_rns

            # Materialize before returning: jit dispatch is async, so a
            # Mosaic failure would otherwise surface at the *caller's*
            # block_until_ready, past this fallback.  Callers convert
            # the verdict to numpy immediately anyway.
            out = jax.block_until_ready(
                pallas_rns.verify_pallas(sig_h, em_h, idx, unique_rows)
            )
            _pallas_mark_proven("verify")
            return out
        except Exception as e:
            import logging

            _PALLAS_STATUS["verify"] = f"fallback: {type(e).__name__}"
            logging.getLogger("bftkv_tpu.ops.rns").exception(
                "pallas verify kernel failed; falling back to XLA"
            )
    if _shardable(sig_h.shape[0]):
        return _jitted_verify_gather_sharded()(sig_h, em_h, idx, unique_rows)
    return _jitted_verify_gather()(sig_h, em_h, idx, unique_rows)


def stack_key_rows(rows: list):
    """Stack per-key row tuples (from :meth:`RNSContext.key_rows`) into
    the batch tensors ``verify_e65537_rns`` takes. The (T, 1) reshape
    of the scalar redundant-channel entries lives here and only here."""
    stack = lambda i: np.stack([np.asarray(r[i]) for r in rows])
    t = len(rows)
    return (
        stack(0),
        stack(1).reshape(t, 1),
        stack(2),
        stack(3),
        stack(4),
        stack(5).reshape(t, 1),
    )


def assemble_key_rows(ns: list[int]):
    """Stack cached per-key rows for a batch of moduli, or None if any
    modulus is RNS-incapable (caller falls back for those)."""
    ctx = context()
    rows = []
    for n in ns:
        r = ctx.key_rows(n)
        if r is None:
            return None
        rows.append(r)
    return stack_key_rows(rows)
