"""P-256 scalar multiplication on the RNS/MXU field core.

The limb-based P-256 kernel (:mod:`bftkv_tpu.ops.ec`) pays the same
tax the limb RSA kernels did: every field multiply is a 16-step digit
convolution of *emulated* integer multiplies on the VPU (556 scalar
mults/s at batch 64 — the weakest kernel in the round-3 record).  This
module applies the RNS playbook that made RSA fast
(:mod:`bftkv_tpu.ops.rns`) to the P-256 field:

- field elements live as residues over ~54 primes of ~12 bits (two
  bases + a 2^12 redundant channel), so a field multiply is one
  channelwise f32 Barrett pass plus two base extensions that run as
  exact bf16 MXU matmuls — no emulated integer arithmetic anywhere;
- the modulus is FIXED (the P-256 prime), so all Montgomery/extension
  constants are compile-time and broadcast — zero per-row key traffic;
- **channel-major layout**: tensors are ``(k, T)`` — batch rides the
  lane (minor) axis, channels ride sublanes.  P-256's k is only 27
  per base; channels-minor would lane-pad 27 → 128 (4.7× VPU waste on
  every Barrett op), while batch-minor keeps all 128 lanes busy and
  pads sublanes just 27 → 32.  (The RSA contexts sit at k = 94/188
  where channels-minor padding is mild; here layout is the difference
  between a VPU-bound and a balanced kernel.)  Base extensions become
  ``Eᵀ @ x`` matmuls — same exact 6-bit-split bf16 MXU scheme;
- values are kept in redundant AMM form (< c·p for a tracked
  coefficient c); adds and subtracts are channelwise and *don't*
  reduce — only the Montgomery product does (every ``fmul`` output is
  < (k+2)·p ≈ 30·p).  Subtraction adds a fixed multiple of p to stay
  positive; the group-law formulas stack at most two subtractions, so
  a two-level slack policy (2^14·p, then 2^16·p) keeps every value
  positive and every product far inside the ~64 bits of headroom the
  bases carry over p (worst pairing ≈ 2^34 ≪ 2^64);
- "is zero (mod p)" — needed by the unified group law for the
  identity/doubling lanes — uses the α-consistency trick from RSA
  verify: v < c·p is a multiple of p iff w_j = v_j·(p⁻¹ mod p_j)
  agrees across every channel (then v = w_0·p exactly, because
  |v − w_0·p| < M).  Exact provided c < min channel prime (~3833), so
  the law only tests *fresh* values: differences of ``fmul`` outputs
  with the small slack (bound 62·p) and the Z coordinate, which is
  kept eligible by construction — ``jac_double`` computes
  Z3 = 2·Y1·Z1 (a mult, not the (Y+Z)²−γ−δ trick), which also keeps
  the identity's Z an *exact* integer 0 through every operation;
- scalar mult is fixed 4-bit windows over 64 steps: 4 doublings + a
  one-hot table select + one unified add per window — constant-time,
  uniform across the batch (reference hot loop this accelerates:
  crypto/threshold/ecdsa/ecdsa.go:31-59, plus identity-cert ECDSA).

Selection: ``ops.ec.scalar_mult_hosts`` routes here per
``BFTKV_EC_BACKEND`` (limb | rns | auto); ``crypto/ec.py`` remains the
host correctness oracle either way.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from bftkv_tpu.crypto.ec import P256
from bftkv_tpu.ops import limb, rns

__all__ = ["scalar_mult_hosts", "scalar_base_mult_hosts"]

_DIGITS = 16  # 256 bits / 16-bit digits
_WINDOW = 4
_NWIN = 256 // _WINDOW

# fsub slack multiples of p.  SMALL: for differences of fmul outputs
# (< 30p) that must stay is_zero-eligible.  L1: subtrahend is an fmul
# output or a short add-chain of them (< 2^12·p).  L2: subtrahend is
# itself an L1 fsub output (< 2^14.1·p).
_S_SMALL = 32
_S_L1 = 1 << 14
_S_L2 = 1 << 16

_PRF = np.float32(rns.PR)
_INV_PRF = np.float32(1.0 / rns.PR)
_I64 = np.float32(1.0 / 64.0)


# -- channel-major field primitives (tensors (k, T); constants (k, 1)) --


def _barrett(x, inv_p, p):
    q = jnp.floor(x * inv_p)
    r = x - q * p
    r = jnp.where(r < 0, r + p, r)
    r = jnp.where(r < 0, r + p, r)
    r = jnp.where(r >= p, r - p, r)
    r = jnp.where(r >= p, r - p, r)
    return r


def _mulmod(a, b, inv_p, p):
    return _barrett(a * b, inv_p, p)


def _addmod(a, b, p):
    s = a + b
    return jnp.where(s >= p, s - p, s)


def _submod(a, b, p):
    d = a - b
    return jnp.where(d < 0, d + p, d)


def _mod_r(x):
    return x - jnp.floor(x * _INV_PRF) * _PRF


def _split6(x):
    hi = jnp.floor(x * _I64)
    return x - hi * 64.0, hi


def _dot(m, x):
    return lax.dot_general(
        m.astype(jnp.bfloat16),
        x.astype(jnp.bfloat16),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def _dot6(mlo, mhi, x):
    """Exact M @ x for 12-bit integral operands via 6-bit bf16 planes:
    M is pre-split (rows = output channels), x is (k, T)."""
    xlo, xhi = _split6(x)
    return (
        _dot(mlo, xlo),
        _dot(mlo, xhi) + _dot(mhi, xlo),
        _dot(mhi, xhi),
    )


def _red6(rlo, rhi, x):
    """Redundant-channel row-reduce: Σ_i r[i]·x[i, :] → (1, T) planes."""
    xlo, xhi = _split6(x)
    s = lambda v: jnp.sum(v, axis=0, keepdims=True)
    return (
        s(rlo * xlo),
        s(rlo * xhi) + s(rhi * xlo),
        s(rhi * xhi),
    )


def _combine(sll, smid, shh, inv_p, p):
    a = _barrett(sll, inv_p, p)
    b = _barrett(smid, inv_p, p)
    d = _barrett(shh, inv_p, p)
    b6 = _barrett(b * 64.0, inv_p, p)
    d12 = _barrett(_barrett(d * 64.0, inv_p, p) * 64.0, inv_p, p)
    return _addmod(_addmod(a, b6, p), d12, p)


def _combine_r(sll, smid, shh):
    return _mod_r(
        _mod_r(sll) + _mod_r(smid * 64.0) + _mod_r(_mod_r(shh * 64.0) * 64.0)
    )


class _P256RNS:
    """Fixed-modulus RNS field context, channel-major device constants."""

    def __init__(self):
        ctx = rns.context(_DIGITS, 256)
        self.ctx = ctx
        self.k = k = ctx.k
        p = P256.p
        f32 = lambda xs: np.asarray(xs, dtype=np.float32)
        col = lambda xs: jnp.asarray(f32(xs)[:, None])  # (k, 1)

        self.pb = col(ctx.p_all[:k])
        self.pq = col(ctx.p_all[k:])
        self.ib = col(1.0 / ctx.p_all[:k])
        self.iq = col(1.0 / ctx.p_all[k:])
        self.invMi_b = col(ctx.invMi_b)
        self.invMi_q = col(ctx.invMi_q)
        self.Mq_mod_b = col(ctx.Mq_mod_b)
        self.invM_q = col(ctx.invM_q)
        self.invMq_pr = np.float32(ctx.invMq_pr)
        self.invM_pr = np.float32(ctx.invM_pr)
        nrow = ctx.key_rows(p)
        n_all = np.asarray(nrow[0])
        self.nb = col(n_all[:k])
        self.nq = col(n_all[k:])
        self.nr = jnp.asarray(np.full((1, 1), float(nrow[1]), np.float32))
        self.neg_ninv_b = col(np.asarray(nrow[2]))

        # Extension matrices, pre-transposed for Eᵀ @ x and pre-split.
        E1 = (ctx._E1[0] + 64.0 * ctx._E1[1]).astype(np.int64)  # (k, k+1)
        E2 = (ctx._E2[0] + 64.0 * ctx._E2[1]).astype(np.int64)
        split = lambda m: (
            jnp.asarray((m & 63).astype(np.float32)),
            jnp.asarray((m >> 6).astype(np.float32)),
        )
        self.E1qT = split(E1[:, :k].T)  # (k_q, k_b)
        self.E1r = split(E1[:, k:])  # (k_b, 1) column, used as reduce
        self.E2bT = split(E2[:, :k].T)
        self.E2r = split(E2[:, k:])

        self.pinv_b = col([pow(p % q, -1, q) for q in ctx.pb])

        def const_of(v: int):
            return (
                col([v % q for q in ctx.pb]),
                col([v % q for q in ctx.pq]),
                jnp.asarray(np.full((1, 1), v % rns.PR, np.float32)),
            )

        self.sp = {
            _S_SMALL: const_of(_S_SMALL * p),
            _S_L1: const_of(_S_L1 * p),
            _S_L2: const_of(_S_L2 * p),
        }
        self.one_m = const_of(ctx.M % p)
        self.zero = const_of(0)

    # -- field ops (triplets (xb (k,T), xq (k,T), xr (1,T))) -----------

    def fmul(self, a, b):
        """RNS Montgomery product (Bajard AMM + Shenoy), channel-major."""
        ab, aq, ar = a
        bb, bq, br = b
        db = _mulmod(ab, bb, self.ib, self.pb)
        dq = _mulmod(aq, bq, self.iq, self.pq)
        dr = _mod_r(ar * br)

        qb = _mulmod(db, self.neg_ninv_b, self.ib, self.pb)
        sigma = _mulmod(qb, self.invMi_b, self.ib, self.pb)
        sll, smid, shh = _dot6(*self.E1qT, sigma)
        qhat_q = _combine(sll, smid, shh, self.iq, self.pq)
        rll, rmid, rhh = _red6(*self.E1r, sigma)
        qhat_r = _combine_r(rll, rmid, rhh)

        t = _mulmod(qhat_q, self.nq, self.iq, self.pq)
        rq = _mulmod(_addmod(dq, t, self.pq), self.invM_q, self.iq, self.pq)
        rr = _mod_r(_mod_r(dr + _mod_r(qhat_r * self.nr)) * self.invM_pr)

        sigma2 = _mulmod(rq, self.invMi_q, self.iq, self.pq)
        zll, zmid, zhh = _dot6(*self.E2bT, sigma2)
        ext_b = _combine(zll, zmid, zhh, self.ib, self.pb)
        wll, wmid, whh = _red6(*self.E2r, sigma2)
        ext_r = _combine_r(wll, wmid, whh)
        alpha = _mod_r(_mod_r(ext_r - rr + _PRF) * self.invMq_pr)
        corr = _barrett(alpha * self.Mq_mod_b, self.ib, self.pb)
        rb = _submod(ext_b, corr, self.pb)
        return rb, rq, rr

    def fadd(self, a, b):
        return (
            _addmod(a[0], b[0], self.pb),
            _addmod(a[1], b[1], self.pq),
            _mod_r(a[2] + b[2]),
        )

    def fsub(self, a, b, s: int = _S_L1):
        """a − b + s·p (s·p ≡ 0 mod p keeps the residue class; s must
        exceed b's bound coefficient so the value stays positive)."""
        sp = self.sp[s]
        return (
            _addmod(_submod(a[0], b[0], self.pb), sp[0], self.pb),
            _addmod(_submod(a[1], b[1], self.pq), sp[1], self.pq),
            _mod_r(a[2] - b[2] + sp[2] + _PRF),
        )

    def fdbl(self, a):
        return self.fadd(a, a)

    def is_zero(self, v):
        """(T,) bool: v ≡ 0 (mod p), exact for v < (min prime)·p."""
        w = _mulmod(v[0], self.pinv_b, self.ib, self.pb)
        alpha = w[:1, :]
        return jnp.all(w == alpha, axis=0) & (
            alpha[0, :] <= np.float32(2 * _S_SMALL)
        )

    def select(self, cond, a, b):
        """Per-lane triplet select; cond is (T,)."""
        c = cond[None, :]
        return tuple(jnp.where(c, x, y) for x, y in zip(a, b))

    # -- group law (Jacobian, unified / branch-free) -------------------

    def jac_double(self, X1, Y1, Z1):
        """dbl-2001-b shape for a = −3, except Z3 = 2·Y1·Z1: a mult
        keeps Z3 < 60p (is_zero-eligible) and maps the identity's
        exact-0 Z to exact 0 (0 is absorbing through fmul/fadd)."""
        delta = self.fmul(Z1, Z1)
        gamma = self.fmul(Y1, Y1)
        beta = self.fmul(X1, gamma)
        t0 = self.fsub(X1, delta, _S_L1)
        t1 = self.fadd(X1, delta)
        alpha = self.fmul(t0, self.fadd(self.fdbl(t1), t1))
        beta4 = self.fdbl(self.fdbl(beta))  # < 120p
        X3 = self.fsub(self.fmul(alpha, alpha), self.fdbl(beta4), _S_L1)
        Z3 = self.fdbl(self.fmul(Y1, Z1))
        g2 = self.fmul(gamma, gamma)
        Y3 = self.fsub(
            self.fmul(alpha, self.fsub(beta4, X3, _S_L2)),
            self.fdbl(self.fdbl(self.fdbl(g2))),
            _S_L1,
        )
        return X3, Y3, Z3

    def jac_add(self, P1, P2):
        X1, Y1, Z1 = P1
        X2, Y2, Z2 = P2
        Z1Z1 = self.fmul(Z1, Z1)
        Z2Z2 = self.fmul(Z2, Z2)
        U1 = self.fmul(X1, Z2Z2)
        U2 = self.fmul(X2, Z1Z1)
        S1 = self.fmul(self.fmul(Y1, Z2), Z2Z2)
        S2 = self.fmul(self.fmul(Y2, Z1), Z1Z1)
        # H/R: differences of fmul outputs with the SMALL slack — the
        # only values (besides Z) the is_zero test ever sees.
        H = self.fsub(U2, U1, _S_SMALL)
        R = self.fsub(S2, S1, _S_SMALL)
        H2 = self.fmul(H, H)
        H3 = self.fmul(H2, H)
        U1H2 = self.fmul(U1, H2)
        X3 = self.fsub(
            self.fsub(self.fmul(R, R), H3, _S_L1), self.fdbl(U1H2), _S_L1
        )
        Y3 = self.fsub(
            self.fmul(R, self.fsub(U1H2, X3, _S_L2)),
            self.fmul(S1, H3),
            _S_L1,
        )
        Z3 = self.fmul(self.fmul(Z1, Z2), H)

        dX, dY, dZ = self.jac_double(X1, Y1, Z1)

        inf1 = self.is_zero(Z1)
        inf2 = self.is_zero(Z2)
        same_x = self.is_zero(H) & ~inf1 & ~inf2
        same_y = self.is_zero(R)
        is_dbl = same_x & same_y
        to_inf = same_x & ~same_y  # P + (−P) = O

        X = self.select(is_dbl, dX, X3)
        Y = self.select(is_dbl, dY, Y3)
        Z = self.select(is_dbl, dZ, Z3)
        Z = self.select(to_inf, tuple(jnp.zeros_like(c) for c in Z), Z)
        X = self.select(inf1, X2, self.select(inf2, X1, X))
        Y = self.select(inf1, Y2, self.select(inf2, Y1, Y))
        Z = self.select(inf1, Z2, self.select(inf2, Z1, Z))
        return X, Y, Z

    # -- host codecs ---------------------------------------------------

    def encode_points(self, pts: list):
        """Affine host points (None = identity) → Montgomery RNS batch."""
        p = P256.p
        M = self.ctx.M
        one = M % p
        xs, ys, zs = [], [], []
        for pt in pts:
            if pt is None:
                xs.append(one)  # placeholder; Z = 0 marks identity
                ys.append(one)
                zs.append(0)
            else:
                xs.append((pt[0] * M) % p)
                ys.append((pt[1] * M) % p)
                zs.append(one)
        return tuple(self._ints_to_res(v) for v in (xs, ys, zs))

    def _ints_to_res(self, vals: list[int]):
        ctx = self.ctx
        t = len(vals)
        out_b = np.empty((self.k, t), dtype=np.float32)
        out_q = np.empty((self.k, t), dtype=np.float32)
        out_r = np.empty((1, t), dtype=np.float32)
        for i, v in enumerate(vals):
            out_b[:, i] = [v % q for q in ctx.pb]
            out_q[:, i] = [v % q for q in ctx.pq]
            out_r[0, i] = v % rns.PR
        return (jnp.asarray(out_b), jnp.asarray(out_q), jnp.asarray(out_r))

    def encode_points_into(self, pts: list, res: np.ndarray) -> None:
        """:meth:`encode_points`, but written into columns of a
        persistent staging block ``res`` of shape ``(3, 2k+1, T)`` —
        X/Y/Z on the leading axis, the b/q/r channel rows stacked on
        the middle one.  Same encoding, zero fresh allocation."""
        ctx = self.ctx
        p = P256.p
        one = ctx.M % p
        for i, pt in enumerate(pts):
            if pt is None:
                vals = (one, one, 0)  # Z = 0 marks identity
            else:
                vals = ((pt[0] * ctx.M) % p, (pt[1] * ctx.M) % p, one)
            for comp, v in zip(res, vals):
                comp[: self.k, i] = [v % q for q in ctx.pb]
                comp[self.k : 2 * self.k, i] = [v % q for q in ctx.pq]
                comp[2 * self.k, i] = v % rns.PR

    def decode_points(self, X, Y, Z) -> list:
        """Jacobian Montgomery RNS batch → affine host points.  The
        final Z inversion is host-side ``pow`` (one ~µs op per point —
        not worth a device Fermat chain)."""
        ctx = self.ctx
        p = P256.p
        ones = tuple(jnp.ones_like(c) for c in X)
        outs = []
        for comp in (X, Y, Z):
            plain = self.fmul(comp, ones)  # strip the Montgomery factor
            sigma = _mulmod(plain[0], self.invMi_b, self.ib, self.pb)
            vals = rns._sigma_to_ints(ctx, np.asarray(sigma).T)
            outs.append([v % p for v in vals])
        xs, ys, zs = outs
        pts = []
        for x, y, z in zip(xs, ys, zs):
            if z == 0:
                pts.append(None)
                continue
            zi = pow(z, -1, p)
            zi2 = zi * zi % p
            pts.append((x * zi2 % p, y * zi2 * zi % p))
        return pts


@functools.lru_cache(maxsize=1)
def _engine() -> _P256RNS:
    return _P256RNS()


def _bcast(c, t: int):
    return tuple(jnp.broadcast_to(a, (a.shape[0], t)) for a in c)


@functools.lru_cache(maxsize=1)
def _scalar_mult_fn():
    eng = _engine()

    def run(Xb, Xq, Xr, Yb, Yq, Yr, Zb, Zq, Zr, nibbles_t):
        P = ((Xb, Xq, Xr), (Yb, Yq, Yr), (Zb, Zq, Zr))
        t = Xb.shape[1]
        one_m = _bcast(eng.one_m, t)
        ident = (one_m, one_m, _bcast(eng.zero, t))
        # Window table t[j] = j·P (t[0] = identity), 15 unified adds.
        tab = [ident, P]
        for _ in range(14):
            tab.append(eng.jac_add(tab[-1], P))
        # Stack on a leading window axis for the one-hot select.
        cat = [
            [jnp.stack([w[i][j] for w in tab]) for j in range(3)]
            for i in range(3)
        ]

        def sel(mask16, i):
            # mask16: (16, 1, T) one-hot; reduce over the window axis.
            return tuple(
                jnp.sum(mask16 * cat[i][j], axis=0) for j in range(3)
            )

        def body(acc, nib):
            for _ in range(_WINDOW):
                acc = eng.jac_double(*acc)
            m16 = (
                nib[None, None, :]
                == jnp.arange(16, dtype=jnp.float32)[:, None, None]
            ).astype(jnp.float32)
            q = (sel(m16, 0), sel(m16, 1), sel(m16, 2))
            return eng.jac_add(acc, q), None

        acc, _ = lax.scan(body, ident, nibbles_t)
        return acc

    return jax.jit(run)


def _nibbles(scalars: list[int]) -> np.ndarray:
    """(NWIN, T) f32 window values, most-significant first."""
    ks = [s % P256.n for s in scalars]
    ed = limb.ints_to_limbs(ks, _DIGITS)  # (T, 16) 16-bit digits
    nib = np.empty((len(ks), _NWIN), dtype=np.float32)
    nib[:, 0::4] = ed & 0xF
    nib[:, 1::4] = (ed >> 4) & 0xF
    nib[:, 2::4] = (ed >> 8) & 0xF
    nib[:, 3::4] = (ed >> 12) & 0xF
    nib = nib[:, ::-1]
    return np.ascontiguousarray(nib.T)


def _ec_staging(padded: int):
    """Persistent EC-identity staging slot for one padded batch size.

    One ring per padded width (``ec:8``, ``ec:16``, ...) under the
    shared :mod:`bftkv_tpu.ops.devbuf` pool — the third width class of
    the device plane next to the RSA-2048/3072 pow rings.  Each slot
    carries a ``pad_lo`` watermark: columns ``pad_lo:`` are known to
    hold the identity-point encoding from an earlier call, so the
    steady state re-encodes only live rows and never re-pays the
    Python residue loop for the pad region.
    """
    from bftkv_tpu.ops import devbuf

    k = _engine().k

    def make():
        return {
            "res": np.empty((3, 2 * k + 1, padded), dtype=np.float32),
            "nib": np.empty((_NWIN, padded), dtype=np.float32),
            "pad_lo": np.full(1, padded, dtype=np.int64),
        }

    if not devbuf.enabled():
        return None, devbuf.Slot(make())
    ring = devbuf.ring_for(f"ec:{padded}", make, width="ec")
    slot = ring.acquire()
    if slot is None:
        return None, ring.fresh()
    return ring, slot


def scalar_mult_hosts(points: list, scalars: list[int]) -> list:
    """Batched k·P on the RNS field core; same contract as
    :func:`bftkv_tpu.ops.ec.scalar_mult_hosts` (power-of-two padding,
    floor 8).  Operands stage through a persistent ``devbuf`` ring
    (width class ``ec``); pad columns hold the identity point exactly
    as the historical pad-with-None lists did, so results are
    bit-identical with staging on or off."""
    if not points:
        return []
    from bftkv_tpu import ops

    ops.enable_compile_cache()
    eng = _engine()
    k = eng.k
    t = len(points)
    padded = max(8, 1 << (t - 1).bit_length())
    ring, slot = _ec_staging(padded)
    try:
        res, nib = slot["res"], slot["nib"]
        eng.encode_points_into(points, res[:, :, :t])
        nib[:, :t] = _nibbles(scalars)
        # Identity-pad only the columns a previous (larger) batch
        # dirtied; columns past the slot's watermark are already the
        # identity encoding from an earlier call.
        pad_lo = int(slot["pad_lo"][0])
        if t < pad_lo:
            eng.encode_points_into(
                [None] * (pad_lo - t), res[:, :, t:pad_lo]
            )
            nib[:, t:pad_lo] = 0.0
        slot["pad_lo"][0] = t
        X, Y, Z = (
            (
                jnp.asarray(comp[:k]),
                jnp.asarray(comp[k : 2 * k]),
                jnp.asarray(comp[2 * k :]),
            )
            for comp in res
        )
        out = _scalar_mult_fn()(*X, *Y, *Z, jnp.asarray(nib))
        # decode_points materializes the outputs, which forces the
        # launch that read the staged buffers to completion — the slot
        # is safe to recycle once we return.  (On the exception path a
        # ghost launch may still *read* the slot after release; jit
        # never writes into numpy operands, and the ghost's outputs
        # are discarded, so the next acquirer is unaffected.)
        return eng.decode_points(*out)[:t]
    finally:
        if ring is not None:
            ring.release(slot)


def scalar_base_mult_hosts(scalars: list[int]) -> list:
    return scalar_mult_hosts([(P256.gx, P256.gy)] * len(scalars), scalars)
