"""P-256 scalar multiplication on the RNS/MXU field core.

The limb-based P-256 kernel (:mod:`bftkv_tpu.ops.ec`) pays the same
tax the limb RSA kernels did: every field multiply is a 16-step digit
convolution of *emulated* integer multiplies on the VPU (556 scalar
mults/s at batch 64 — the weakest kernel in the round-3 record).  This
module applies the RNS playbook that made RSA fast
(:mod:`bftkv_tpu.ops.rns`) to the P-256 field:

- field elements live as residues over ~54 primes of ~12 bits (two
  bases + a 2^12 redundant channel), so a field multiply is one
  channelwise f32 Barrett pass plus two base extensions that run as
  exact bf16 MXU matmuls — no emulated integer arithmetic anywhere;
- the modulus is FIXED (the P-256 prime), so all Montgomery/extension
  constants are compile-time and broadcast — zero per-row key traffic;
- values are kept in redundant AMM form (< c·p for a tracked
  coefficient c); adds and subtracts are channelwise and *don't*
  reduce — only the Montgomery product does (every ``fmul`` output is
  < (k+2)·p ≈ 30·p).  Subtraction adds a fixed multiple of p to stay
  positive; the group-law formulas stack at most two subtractions, so
  a two-level slack policy (2^14·p, then 2^16·p) keeps every value
  positive and every product far inside the ~64 bits of headroom the
  bases carry over p (worst pairing ≈ 2^34 ≪ 2^64);
- "is zero (mod p)" — needed by the unified group law for the
  identity/doubling lanes — uses the α-consistency trick from RSA
  verify: v < c·p is a multiple of p iff w_j = v_j·(p⁻¹ mod p_j)
  agrees across every channel (then v = w_0·p exactly, because
  |v − w_0·p| < M).  Exact provided c < min channel prime (~3833), so
  the law only tests *fresh* values: differences of ``fmul`` outputs
  with the small slack (bound 62·p) and the Z coordinate, which is
  kept eligible by construction — ``jac_double`` computes
  Z3 = 2·Y1·Z1 (a mult, not the (Y+Z)²−γ−δ trick), which also keeps
  the identity's Z an *exact* integer 0 through every operation;
- scalar mult is fixed 4-bit windows over 64 steps: 4 doublings + a
  one-hot table select + one unified add per window — constant-time,
  uniform across the batch (reference hot loop this accelerates:
  crypto/threshold/ecdsa/ecdsa.go:31-59, plus identity-cert ECDSA).

Selection: ``ops.ec.scalar_mult_hosts`` routes here per
``BFTKV_EC_BACKEND`` (limb | rns | auto); ``crypto/ec.py`` remains the
host correctness oracle either way.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from bftkv_tpu.crypto.ec import P256
from bftkv_tpu.ops import limb, rns

__all__ = ["scalar_mult_hosts", "scalar_base_mult_hosts"]

_DIGITS = 16  # 256 bits / 16-bit digits
_WINDOW = 4
_NWIN = 256 // _WINDOW

# fsub slack multiples of p.  SMALL: for differences of fmul outputs
# (< 30p) that must stay is_zero-eligible.  L1: subtrahend is an fmul
# output or a short add-chain of them (< 2^12·p).  L2: subtrahend is
# itself an L1 fsub output (< 2^14.1·p).
_S_SMALL = 32
_S_L1 = 1 << 14
_S_L2 = 1 << 16


class _P256RNS:
    """Fixed-modulus RNS field context + device constants."""

    def __init__(self):
        ctx = rns.context(_DIGITS, 256)
        self.ctx = ctx
        self.cn = rns._Consts(ctx)
        self.k = ctx.k
        p = P256.p
        key = ctx.key_rows(p)
        self.key = tuple(
            jnp.asarray(
                np.asarray(a)[None]
                if np.ndim(a)
                else np.full((1, 1), a, dtype=np.float32)
            )
            for a in key
        )
        f32 = lambda xs: np.asarray(xs, dtype=np.float32)

        def const_of(v: int):
            """Residues of integer v as a broadcastable RNS triplet."""
            return (
                jnp.asarray(f32([v % q for q in ctx.pb])[None]),
                jnp.asarray(f32([v % q for q in ctx.pq])[None]),
                jnp.asarray(np.full((1, 1), v % rns.PR, dtype=np.float32)),
            )

        self.sp = {
            _S_SMALL: const_of(_S_SMALL * p),
            _S_L1: const_of(_S_L1 * p),
            _S_L2: const_of(_S_L2 * p),
        }
        # p⁻¹ mod p_j over base B — the is_zero α extractor.
        self.pinv_b = jnp.asarray(
            f32([pow(p % q, -1, q) for q in ctx.pb])[None]
        )
        r_int = ctx.M % p  # the Montgomery "one"
        self.one_m = const_of(r_int)
        self.zero = const_of(0)

    # -- field ops (triplets (xb (T,k), xq (T,k), xr (T,1))) -----------

    def fmul(self, a, b):
        return rns._mont_mul(self.cn, a, b, self.key)

    def fadd(self, a, b):
        cn = self.cn
        return (
            rns._addmod(a[0], b[0], cn.pb),
            rns._addmod(a[1], b[1], cn.pq),
            rns._mod_r(a[2] + b[2]),
        )

    def fsub(self, a, b, s: int = _S_L1):
        """a − b + s·p (s·p ≡ 0 mod p keeps the residue class; s must
        exceed b's bound coefficient so the value stays positive)."""
        sp = self.sp[s]
        cn = self.cn
        return (
            rns._addmod(rns._submod(a[0], b[0], cn.pb), sp[0], cn.pb),
            rns._addmod(rns._submod(a[1], b[1], cn.pq), sp[1], cn.pq),
            rns._mod_r(a[2] - b[2] + sp[2] + rns._PRF),
        )

    def fdbl(self, a):
        return self.fadd(a, a)

    def is_zero(self, v):
        """(T,) bool: v ≡ 0 (mod p), exact for v < (min prime)·p."""
        cn = self.cn
        w = rns._mulmod(v[0], self.pinv_b, cn.ib, cn.pb)
        alpha = w[:, :1]
        return jnp.all(w == alpha, axis=1) & (
            alpha[:, 0] <= np.float32(2 * _S_SMALL)
        )

    def select(self, cond, a, b):
        """Per-lane triplet select; cond is (T,)."""
        c = cond[:, None]
        return tuple(jnp.where(c, x, y) for x, y in zip(a, b))

    # -- group law (Jacobian, unified / branch-free) -------------------

    def jac_double(self, X1, Y1, Z1):
        """dbl-2001-b shape for a = −3, except Z3 = 2·Y1·Z1: a mult
        keeps Z3 < 60p (is_zero-eligible) and maps the identity's
        exact-0 Z to exact 0 (0 is absorbing through fmul/fadd)."""
        delta = self.fmul(Z1, Z1)
        gamma = self.fmul(Y1, Y1)
        beta = self.fmul(X1, gamma)
        t0 = self.fsub(X1, delta, _S_L1)
        t1 = self.fadd(X1, delta)
        alpha = self.fmul(t0, self.fadd(self.fdbl(t1), t1))
        beta4 = self.fdbl(self.fdbl(beta))  # < 120p
        X3 = self.fsub(self.fmul(alpha, alpha), self.fdbl(beta4), _S_L1)
        Z3 = self.fdbl(self.fmul(Y1, Z1))
        g2 = self.fmul(gamma, gamma)
        Y3 = self.fsub(
            self.fmul(alpha, self.fsub(beta4, X3, _S_L2)),
            self.fdbl(self.fdbl(self.fdbl(g2))),
            _S_L1,
        )
        return X3, Y3, Z3

    def jac_add(self, P1, P2):
        X1, Y1, Z1 = P1
        X2, Y2, Z2 = P2
        Z1Z1 = self.fmul(Z1, Z1)
        Z2Z2 = self.fmul(Z2, Z2)
        U1 = self.fmul(X1, Z2Z2)
        U2 = self.fmul(X2, Z1Z1)
        S1 = self.fmul(self.fmul(Y1, Z2), Z2Z2)
        S2 = self.fmul(self.fmul(Y2, Z1), Z1Z1)
        # H/R: differences of fmul outputs with the SMALL slack — the
        # only values (besides Z) the is_zero test ever sees.
        H = self.fsub(U2, U1, _S_SMALL)
        R = self.fsub(S2, S1, _S_SMALL)
        H2 = self.fmul(H, H)
        H3 = self.fmul(H2, H)
        U1H2 = self.fmul(U1, H2)
        X3 = self.fsub(
            self.fsub(self.fmul(R, R), H3, _S_L1), self.fdbl(U1H2), _S_L1
        )
        Y3 = self.fsub(
            self.fmul(R, self.fsub(U1H2, X3, _S_L2)),
            self.fmul(S1, H3),
            _S_L1,
        )
        Z3 = self.fmul(self.fmul(Z1, Z2), H)

        dX, dY, dZ = self.jac_double(X1, Y1, Z1)

        inf1 = self.is_zero(Z1)
        inf2 = self.is_zero(Z2)
        same_x = self.is_zero(H) & ~inf1 & ~inf2
        same_y = self.is_zero(R)
        is_dbl = same_x & same_y
        to_inf = same_x & ~same_y  # P + (−P) = O

        X = self.select(is_dbl, dX, X3)
        Y = self.select(is_dbl, dY, Y3)
        Z = self.select(is_dbl, dZ, Z3)
        Z = self.select(to_inf, tuple(jnp.zeros_like(c) for c in Z), Z)
        X = self.select(inf1, X2, self.select(inf2, X1, X))
        Y = self.select(inf1, Y2, self.select(inf2, Y1, Y))
        Z = self.select(inf1, Z2, self.select(inf2, Z1, Z))
        return X, Y, Z

    # -- host codecs ---------------------------------------------------

    def encode_points(self, pts: list):
        """Affine host points (None = identity) → Montgomery RNS batch."""
        p = P256.p
        M = self.ctx.M
        one = M % p
        xs, ys, zs = [], [], []
        for pt in pts:
            if pt is None:
                xs.append(one)  # placeholder; Z = 0 marks identity
                ys.append(one)
                zs.append(0)
            else:
                xs.append((pt[0] * M) % p)
                ys.append((pt[1] * M) % p)
                zs.append(one)
        return tuple(self._ints_to_res(v) for v in (xs, ys, zs))

    def _ints_to_res(self, vals: list[int]):
        ctx = self.ctx
        t = len(vals)
        out_b = np.empty((t, self.k), dtype=np.float32)
        out_q = np.empty((t, self.k), dtype=np.float32)
        out_r = np.empty((t, 1), dtype=np.float32)
        for i, v in enumerate(vals):
            out_b[i] = [v % q for q in ctx.pb]
            out_q[i] = [v % q for q in ctx.pq]
            out_r[i, 0] = v % rns.PR
        return (jnp.asarray(out_b), jnp.asarray(out_q), jnp.asarray(out_r))

    def decode_points(self, X, Y, Z) -> list:
        """Jacobian Montgomery RNS batch → affine host points.  The
        final Z inversion is host-side ``pow`` (one ~µs op per point —
        not worth a device Fermat chain)."""
        ctx = self.ctx
        p = P256.p
        ones = tuple(jnp.ones_like(c) for c in X)
        outs = []
        for comp in (X, Y, Z):
            plain = self.fmul(comp, ones)  # strip the Montgomery factor
            sigma = rns._mulmod(
                plain[0], self.cn.invMi_b, self.cn.ib, self.cn.pb
            )
            vals = rns._sigma_to_ints(ctx, np.asarray(sigma))
            outs.append([v % p for v in vals])
        xs, ys, zs = outs
        pts = []
        for x, y, z in zip(xs, ys, zs):
            if z == 0:
                pts.append(None)
                continue
            zi = pow(z, -1, p)
            zi2 = zi * zi % p
            pts.append((x * zi2 % p, y * zi2 * zi % p))
        return pts


@functools.lru_cache(maxsize=1)
def _engine() -> _P256RNS:
    return _P256RNS()


def _bcast(c, like):
    return tuple(
        jnp.broadcast_to(a, (like.shape[0],) + a.shape[1:]) for a in c
    )


@functools.lru_cache(maxsize=1)
def _scalar_mult_fn():
    eng = _engine()

    def run(Xb, Xq, Xr, Yb, Yq, Yr, Zb, Zq, Zr, nibbles_t):
        P = ((Xb, Xq, Xr), (Yb, Yq, Yr), (Zb, Zq, Zr))
        one_m = _bcast(eng.one_m, Xb)
        ident = (one_m, one_m, _bcast(eng.zero, Xb))
        # Window table t[j] = j·P (t[0] = identity), 15 unified adds.
        tab = [ident, P]
        for _ in range(14):
            tab.append(eng.jac_add(tab[-1], P))
        k = eng.k
        # Concatenate per coordinate/component for the one-hot select.
        cat = [
            [jnp.concatenate([t[i][j] for t in tab], axis=1)
             for j in range(3)]
            for i in range(3)
        ]

        def sel(nib, i):
            comps = []
            for j, width in ((0, k), (1, k), (2, 1)):
                tcat = cat[i][j]
                acc = jnp.zeros_like(tcat[:, :width])
                for w in range(16):
                    m = (nib == np.float32(w)).astype(jnp.float32)
                    acc = acc + m * tcat[:, w * width : (w + 1) * width]
                comps.append(acc)
            return tuple(comps)

        def body(acc, nib):
            for _ in range(_WINDOW):
                acc = eng.jac_double(*acc)
            nibc = nib[:, None]
            q = (sel(nibc, 0), sel(nibc, 1), sel(nibc, 2))
            return eng.jac_add(acc, q), None

        acc, _ = lax.scan(body, ident, nibbles_t)
        return acc

    return jax.jit(run)


def _nibbles(scalars: list[int]) -> np.ndarray:
    """(NWIN, T) f32 window values, most-significant first."""
    ks = [s % P256.n for s in scalars]
    ed = limb.ints_to_limbs(ks, _DIGITS)  # (T, 16) 16-bit digits
    nib = np.empty((len(ks), _NWIN), dtype=np.float32)
    nib[:, 0::4] = ed & 0xF
    nib[:, 1::4] = (ed >> 4) & 0xF
    nib[:, 2::4] = (ed >> 8) & 0xF
    nib[:, 3::4] = (ed >> 12) & 0xF
    nib = nib[:, ::-1]
    return np.ascontiguousarray(nib.T)


def scalar_mult_hosts(points: list, scalars: list[int]) -> list:
    """Batched k·P on the RNS field core; same contract as
    :func:`bftkv_tpu.ops.ec.scalar_mult_hosts` (power-of-two padding,
    floor 8)."""
    if not points:
        return []
    eng = _engine()
    t = len(points)
    padded = max(8, 1 << (t - 1).bit_length())
    points = list(points) + [None] * (padded - t)
    scalars = list(scalars) + [0] * (padded - t)
    X, Y, Z = eng.encode_points(points)
    nib = _nibbles(scalars)
    out = _scalar_mult_fn()(*X, *Y, *Z, jnp.asarray(nib))
    return eng.decode_points(*out)[:t]


def scalar_base_mult_hosts(scalars: list[int]) -> list:
    return scalar_mult_hosts([(P256.gx, P256.gy)] * len(scalars), scalars)
