"""Shared batched-modexp engine: route big-int exponentiations to the TPU.

Every distributed-crypto subsystem in the reference bottoms out in
``big.Int.Exp`` loops — TPA's DH rounds (crypto/auth/auth.go), threshold
RSA's per-fragment signing (crypto/threshold/rsa/rsa.go:140-178), and
threshold DSA's partial-R combination (crypto/threshold/dsa/dsa.go:33-52).
This engine replaces those per-item loops with one
``ops.rsa.power_batch`` launch per request batch.

Policy: batches below ``min_batch`` (default 4, override with
``BFTKV_TPU_MIN_MODEXP_BATCH``) run as host ``pow`` — a single modexp
doesn't amortize a kernel launch. Per-modulus Montgomery precomputation
is LRU-bounded since moduli can be influenced by remote peers.
"""

from __future__ import annotations

import logging
from collections import OrderedDict

import numpy as np
from bftkv_tpu import flags

__all__ = ["BatchModExp"]


class BatchModExp:
    _shared = None
    _DOM_CACHE_MAX = 64

    def __init__(self, min_batch: int | None = None):
        if min_batch is None:
            min_batch = int(flags.raw("BFTKV_TPU_MIN_MODEXP_BATCH", "4"))
        self.min_batch = min_batch
        self._domains: "OrderedDict[tuple[int, int], object]" = OrderedDict()

    @classmethod
    def shared(cls) -> "BatchModExp":
        if cls._shared is None:
            cls._shared = cls()
        return cls._shared

    def _domain(self, n: int, nlimbs: int):
        from bftkv_tpu.ops import bigint

        key = (n, nlimbs)
        dom = self._domains.get(key)
        if dom is None:
            dom = bigint.MontgomeryDomain(n, nlimbs)
            self._domains[key] = dom
            if len(self._domains) > self._DOM_CACHE_MAX:
                self._domains.popitem(last=False)
        else:
            self._domains.move_to_end(key)
        return dom

    # Exponents can outgrow the modulus (threshold-RSA fragments double
    # in width per tree level — rsa.go:97-117). Past this limb width the
    # window loop dominates and host pow wins; cap the device path.
    MAX_EXP_LIMBS = 256  # 4096 bits

    def modexp(self, pairs: list[tuple[int, int]], n: int) -> list[int]:
        """[(base, exp)] → [base^exp mod n] — one kernel launch when the
        batch is big enough and ``n`` is odd (Montgomery-compatible)."""
        if not pairs:
            return []
        if len(pairs) < self.min_batch or n % 2 == 0 or n <= 1:
            return [pow(b % n, e, n) for b, e in pairs]
        from bftkv_tpu.ops import limb
        from bftkv_tpu.ops import rsa as rsa_ops

        nlimbs = limb.nlimbs_for_bits(n.bit_length())
        max_e = max(e for _, e in pairs)

        # Prefer the RNS windowed-modexp kernel (~10x the limb kernel at
        # batch): it covers moduli/exponents up to the context width.
        # Sub-2^12 primes cannot fund a 4096-bit base pair, so wider
        # operands (threshold-RSA fragment exponents grow past the key
        # size per tree level, rsa.go:97-117) stay on the limb path.
        # power_mod_rns stages operands through the persistent devbuf
        # ring for its width class, so per-call marshalling here is
        # just the list splits below.
        width = max(n.bit_length(), max_e.bit_length())
        nb = next((w for w in (1024, 2048) if width <= w), None)
        if nb is not None:
            from bftkv_tpu.metrics import registry as metrics
            from bftkv_tpu.ops import rns

            try:
                vals = rns.power_mod_rns(
                    [b for b, _ in pairs],
                    [e for _, e in pairs],
                    [n] * len(pairs),
                    n_bits=nb,
                )
            except Exception:
                # power_mod_rns signals every *legitimately* incapable
                # input by returning None; an exception is an
                # unexpected defect — degrade, but loudly.
                metrics.incr("modexp.rns_error")
                logging.getLogger(__name__).exception(
                    "RNS modexp failed; falling back to limb kernel"
                )
                vals = None
            if vals is not None:
                metrics.incr("modexp.rns_staged", len(pairs))
                return vals
            # else: RNS-incapable modulus (None) or logged error —
            # fall through to the limb path either way.

        e_limbs = max(limb.nlimbs_for_bits(max_e.bit_length()), 1)
        if e_limbs > self.MAX_EXP_LIMBS:
            return [pow(b % n, e, n) for b, e in pairs]
        # Bucket the exponent width (64/128/256 limbs) so varying widths
        # reuse a handful of compiled programs instead of one each.
        for bucket in (64, 128, 256):
            if e_limbs <= bucket:
                e_limbs = bucket
                break
        dom = self._domain(n, nlimbs)
        base = limb.ints_to_limbs([b % n for b, _ in pairs], nlimbs)
        exp = limb.ints_to_limbs([e for _, e in pairs], e_limbs)
        out = rsa_ops.power_batch(
            base,
            exp,
            np.broadcast_to(dom.n, base.shape),
            np.broadcast_to(dom.n_prime, base.shape),
            np.broadcast_to(dom.r2, base.shape),
            np.broadcast_to(dom.one_mont, base.shape),
        )
        return limb.limbs_to_ints(np.asarray(out))
