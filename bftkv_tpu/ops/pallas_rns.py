"""Pallas TPU kernels: fused RNS Montgomery chains, VMEM-resident.

The XLA RNS kernels (:mod:`bftkv_tpu.ops.rns`) put the base-extension
matmuls on the MXU, but every elementwise Barrett link between matmuls
is its own XLA loop fusion reading and writing HBM: a windowed-modexp
sign chain is 256 scan steps x 5 Montgomery products x ~25 channel
arrays of traffic, so the chain is bandwidth-bound, not compute-bound
(docs/PERFORMANCE.md "Known ceilings"; reference sign hot loop:
crypto/pgp/crypto_pgp.go:346-371).  Here one ``pallas_call`` runs the
*entire* chain per batch tile — digit→residue conversion, the full
4-bit-window scan (or the 18-product e=65537 verify chain), and the
CRT/consistency epilogue — with the accumulator, window table, and
base-extension matrices VMEM-resident throughout.  HBM traffic drops
to the operands once each way; the dots still ride the MXU (6-bit
split operands as exact bf16 matmuls, f32 accumulate).

Channel geometry: the RNS bases have k channels (94 for the 1024-bit
sign context, 188 for 2048-bit verify); everything is padded to a
lane-aligned ``kpad`` (multiple of 128) with dummy channels p = 1
whose residues are identically zero — Barrett with p = 1 maps any
integral value to 0, and all padded matrix rows/columns are zero, so
the padding is inert end to end.  The 2^12 redundant channel rides as
(T, 1) arrays with power-of-two Barrett (exact), as in ops/rns.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from bftkv_tpu.ops import rns

__all__ = ["pow_pallas", "verify_pallas", "TILE_POW", "TILE_VERIFY"]

from bftkv_tpu import flags

#: Batch rows per grid step.  Budgeted against ~16 MB VMEM/core:
#: the pow chain (kpad=128) holds its 16-entry window table (~4 MB at
#: tile 256) plus ~5 MB of key rows/consts/temps — comfortable at 256.
#: The verify chain has no table but its kpad is 256 (k=188 channels)
#: and it streams ELEVEN row-blocked inputs, each double-buffered by
#: the Mosaic pipeline (~7 MB at tile 256 for inputs alone, plus ~4 MB
#: consts and the live temporaries) — tight enough that tile 128 is
#: the safe default; the first live-hardware measurement can raise it
#: via env (BFTKV_PALLAS_TILE_VERIFY / _POW).
def _tile_env(name: str, default: str) -> int:
    """Validated tile size: a power of two ≥ 8 (TPU sublane multiple;
    power-of-two so the callers' padded batches always divide it).
    Fail fast at import — a bad knob must not surface as a deep Mosaic
    error or a silent per-flush XLA fallback."""
    raw = flags.raw(name, default)
    try:
        v = int(raw)
    except ValueError:
        raise ValueError(f"{name} must be an integer, got {raw!r}") from None
    if v < 8 or (v & (v - 1)):
        raise ValueError(f"{name} must be a power of two >= 8, got {v}")
    return v


TILE_POW = _tile_env("BFTKV_PALLAS_TILE_POW", "256")
TILE_VERIFY = _tile_env("BFTKV_PALLAS_TILE_VERIFY", "128")
PR = rns.PR
_PRF = np.float32(PR)
_INV_PRF = np.float32(1.0 / PR)
_I64 = np.float32(1.0 / 64.0)


# ---------------------------------------------------------------------------
# Padded, lane-aligned constants (host side, cached per context)
# ---------------------------------------------------------------------------


class _PadConsts:
    """ops/rns constants re-laid-out for the fused kernel: channel axis
    padded to a multiple of 128, the redundant-channel column split out
    of the extension matrices (it becomes a VPU row-reduce), matrices
    pre-split into 6-bit bf16-exact planes."""

    def __init__(self, ctx: rns.RNSContext):
        k, digits = ctx.k, ctx.digits
        kpad = -(-k // 128) * 128
        self.k, self.kpad, self.digits = k, kpad, digits

        def padv(v, fill=0.0):
            out = np.full((1, kpad), fill, dtype=np.float32)
            out[0, :k] = v
            return out

        self.pb = padv(ctx.p_all[:k], fill=1.0)
        self.pq = padv(ctx.p_all[k:], fill=1.0)
        self.ib = (np.float32(1.0) / self.pb)
        self.iq = (np.float32(1.0) / self.pq)
        self.invMi_b = padv(ctx.invMi_b)
        self.invMi_q = padv(ctx.invMi_q)
        self.Mq_mod_b = padv(ctx.Mq_mod_b)
        self.invM_q = padv(ctx.invM_q)
        self.invMq_pr = float(ctx.invMq_pr)
        self.invM_pr = float(ctx.invM_pr)

        # Rebuild integer matrices from the stored exact 6-bit planes.
        E1 = (ctx._E1[0] + 64.0 * ctx._E1[1]).astype(np.int64)  # (k, k+1)
        E2 = (ctx._E2[0] + 64.0 * ctx._E2[1]).astype(np.int64)
        D = (ctx._D[0] + 64.0 * ctx._D[1]).astype(np.int64)  # (2d, 2k+1)

        def padm(m, rows, cols):
            out = np.zeros((rows, cols), dtype=np.int64)
            out[: m.shape[0], : m.shape[1]] = m
            return out

        split = lambda m: (
            (m & 63).astype(np.float32),
            (m >> 6).astype(np.float32),
        )
        self.E1q = split(padm(E1[:, :k], kpad, kpad))
        self.E1r = split(padm(E1[:, k:].T, 1, kpad))  # (1, kpad)
        self.E2b = split(padm(E2[:, :k], kpad, kpad))
        self.E2r = split(padm(E2[:, k:].T, 1, kpad))
        self.Db = split(padm(D[:, :k], 2 * digits, kpad))
        self.Dq = split(padm(D[:, k : 2 * k], 2 * digits, kpad))
        self.Dr = split(padm(D[:, 2 * k :].T, 1, 2 * digits))

    def arrays(self) -> tuple:
        """Operand order for the pallas_call const inputs."""
        return (
            self.pb, self.ib, self.pq, self.iq,
            self.invMi_b, self.invMi_q, self.Mq_mod_b, self.invM_q,
            *self.E1q, *self.E1r, *self.E2b, *self.E2r,
            *self.Db, *self.Dq, *self.Dr,
        )


@functools.lru_cache(maxsize=4)
def _pad_consts(digits: int, n_bits: int) -> _PadConsts:
    return _PadConsts(rns.context(digits, n_bits))


# ---------------------------------------------------------------------------
# Kernel math (jnp ops on VMEM-resident values; shared by pow & verify)
# ---------------------------------------------------------------------------


def _barrett(x, inv_p, p):
    q = jnp.floor(x * inv_p)
    r = x - q * p
    r = jnp.where(r < 0, r + p, r)
    r = jnp.where(r < 0, r + p, r)
    r = jnp.where(r >= p, r - p, r)
    r = jnp.where(r >= p, r - p, r)
    return r


def _mulmod(a, b, inv_p, p):
    return _barrett(a * b, inv_p, p)


def _addmod(a, b, p):
    s = a + b
    return jnp.where(s >= p, s - p, s)


def _submod(a, b, p):
    d = a - b
    return jnp.where(d < 0, d + p, d)


def _mod_r(x):
    return x - jnp.floor(x * _INV_PRF) * _PRF


def _split6(x):
    hi = jnp.floor(x * _I64)
    return x - hi * 64.0, hi


def _dot(a, b):
    return lax.dot_general(
        a.astype(jnp.bfloat16),
        b.astype(jnp.bfloat16),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def _dot6(x, mlo, mhi):
    """Exact x @ M for 12-bit integral operands via 6-bit bf16 planes.
    Returns the (ll, mid, hh) partial planes (each < 2^22)."""
    xlo, xhi = _split6(x)
    return _dot(xlo, mlo), _dot(xlo, mhi) + _dot(xhi, mlo), _dot(xhi, mhi)


def _red6(x, rlo, rhi):
    """Row-reduce variant for the redundant channel: Σ_i x[:,i]·r[i]
    as exact partial planes, (T, 1) each."""
    xlo, xhi = _split6(x)
    s = lambda v: jnp.sum(v, axis=1, keepdims=True)
    return (
        s(xlo * rlo),
        s(xlo * rhi) + s(xhi * rlo),
        s(xhi * rhi),
    )


def _combine(sll, smid, shh, inv_p, p):
    a = _barrett(sll, inv_p, p)
    b = _barrett(smid, inv_p, p)
    d = _barrett(shh, inv_p, p)
    b6 = _barrett(b * 64.0, inv_p, p)
    d12 = _barrett(_barrett(d * 64.0, inv_p, p) * 64.0, inv_p, p)
    return _addmod(_addmod(a, b6, p), d12, p)


def _combine_r(sll, smid, shh):
    return _mod_r(
        _mod_r(sll) + _mod_r(smid * 64.0) + _mod_r(_mod_r(shh * 64.0) * 64.0)
    )


class _Ctx:
    """Constants loaded from refs once per kernel invocation."""

    def __init__(self, refs, invMq_pr, invM_pr):
        (
            self.pb, self.ib, self.pq, self.iq,
            self.invMi_b, self.invMi_q, self.Mq_mod_b, self.invM_q,
            e1q_lo, e1q_hi, e1r_lo, e1r_hi,
            e2b_lo, e2b_hi, e2r_lo, e2r_hi,
            db_lo, db_hi, dq_lo, dq_hi, dr_lo, dr_hi,
        ) = [r[:] for r in refs]
        self.E1q = (e1q_lo, e1q_hi)
        self.E1r = (e1r_lo, e1r_hi)
        self.E2b = (e2b_lo, e2b_hi)
        self.E2r = (e2r_lo, e2r_hi)
        self.Db = (db_lo, db_hi)
        self.Dq = (dq_lo, dq_hi)
        self.Dr = (dr_lo, dr_hi)
        self.invMq_pr = np.float32(invMq_pr)
        self.invM_pr = np.float32(invM_pr)

    # -- the Montgomery product (Bajard AMM + Shenoy), fully in VMEM --
    def mont_mul(self, a, b, key):
        ab, aq, ar = a
        bb, bq, br = b
        nb, nq, nr, ninvb = key[:4]
        db = _mulmod(ab, bb, self.ib, self.pb)
        dq = _mulmod(aq, bq, self.iq, self.pq)
        dr = _mod_r(ar * br)

        qb = _mulmod(db, ninvb, self.ib, self.pb)
        sigma = _mulmod(qb, self.invMi_b, self.ib, self.pb)
        sll, smid, shh = _dot6(sigma, *self.E1q)
        qhat_q = _combine(sll, smid, shh, self.iq, self.pq)
        rll, rmid, rhh = _red6(sigma, *self.E1r)
        qhat_r = _combine_r(rll, rmid, rhh)

        t = _mulmod(qhat_q, nq, self.iq, self.pq)
        rq = _mulmod(_addmod(dq, t, self.pq), self.invM_q, self.iq, self.pq)
        rr = _mod_r(_mod_r(dr + _mod_r(qhat_r * nr)) * self.invM_pr)

        sigma2 = _mulmod(rq, self.invMi_q, self.iq, self.pq)
        zll, zmid, zhh = _dot6(sigma2, *self.E2b)
        ext_b = _combine(zll, zmid, zhh, self.ib, self.pb)
        wll, wmid, whh = _red6(sigma2, *self.E2r)
        ext_r = _combine_r(wll, wmid, whh)
        alpha = _mod_r(_mod_r(ext_r - rr + _PRF) * self.invMq_pr)
        corr = _barrett(alpha * self.Mq_mod_b, self.ib, self.pb)
        rb = _submod(ext_b, corr, self.pb)
        return rb, rq, rr

    def to_residues(self, halves):
        """(T, 2·digits) 8-bit halves → residue triplet."""
        sll, smid, shh = _dot6(halves, *self.Db)
        xb = _combine(sll, smid, shh, self.ib, self.pb)
        tll, tmid, thh = _dot6(halves, *self.Dq)
        xq = _combine(tll, tmid, thh, self.iq, self.pq)
        rll, rmid, rhh = _red6(halves, *self.Dr)
        xr = _combine_r(rll, rmid, rhh)
        return xb, xq, xr

    def ones_like(self, x):
        return (
            jnp.ones_like(x[0]),
            jnp.ones_like(x[1]),
            jnp.ones_like(x[2]),
        )


# ---------------------------------------------------------------------------
# Fused windowed modexp (the sign chain)
# ---------------------------------------------------------------------------


def _pow_body(invMq_pr, invM_pr, w_steps, *refs):
    (base_ref, nib_ref, nb_ref, nq_ref, nr_ref, ninvb_ref,
     m2b_ref, m2q_ref, m2r_ref, *const_refs) = refs[:-1]
    out_ref = refs[-1]
    cx = _Ctx(const_refs, invMq_pr, invM_pr)

    key = (nb_ref[:], nq_ref[:], nr_ref[:], ninvb_ref[:])
    m2 = (m2b_ref[:], m2q_ref[:], m2r_ref[:])
    base = cx.to_residues(base_ref[:])
    ones = cx.ones_like(base)
    base_m = cx.mont_mul(base, m2, key)
    one_m = cx.mont_mul(m2, ones, key)

    # 16-entry window table (Montgomery form), VMEM-resident.
    tab = [one_m, base_m]
    for _ in range(14):
        tab.append(cx.mont_mul(tab[-1], base_m, key))
    tb = jnp.concatenate([t[0] for t in tab], axis=1)  # (T, 16·kpad)
    tq = jnp.concatenate([t[1] for t in tab], axis=1)
    tr = jnp.concatenate([t[2] for t in tab], axis=1)  # (T, 16)
    kpad = base[0].shape[1]

    def step(i, acc):
        for _ in range(4):
            acc = cx.mont_mul(acc, acc, key)
        nib = jnp.transpose(nib_ref[pl.ds(i, 1), :])  # (T, 1) f32
        sel_b = jnp.zeros_like(acc[0])
        sel_q = jnp.zeros_like(acc[1])
        sel_r = jnp.zeros_like(acc[2])
        for w in range(16):
            m = (nib == np.float32(w)).astype(jnp.float32)
            sel_b = sel_b + m * tb[:, w * kpad : (w + 1) * kpad]
            sel_q = sel_q + m * tq[:, w * kpad : (w + 1) * kpad]
            sel_r = sel_r + m * tr[:, w : w + 1]
        return cx.mont_mul(acc, (sel_b, sel_q, sel_r), key)

    acc = lax.fori_loop(0, w_steps, step, one_m)
    vb, _vq, _vr = cx.mont_mul(acc, ones, key)  # out of Montgomery form
    out_ref[:] = _mulmod(vb, cx.invMi_b, cx.ib, cx.pb)  # CRT σ over B


@functools.lru_cache(maxsize=8)
def _pow_prep(k: int, kpad: int):
    """Jitted gather/pad prologue, built once per (k, kpad).

    Hoisted out of pow_pallas so the hot sign path doesn't re-trace the
    prologue on every dispatcher flush (ADVICE r4 #4) — the pallas_call
    is cached by _pow_call; this keeps prep cached symmetrically.
    """

    @jax.jit
    def prep(idx, ukey):
        n_all, n_r, neg_ninv_b, _ninv, m2_all, m2_r = tuple(
            u[idx] for u in ukey
        )
        pad = lambda x: jnp.pad(x, ((0, 0), (0, kpad - k)))
        return (
            pad(n_all[:, :k]), pad(n_all[:, k:]), n_r,
            pad(neg_ninv_b),
            pad(m2_all[:, :k]), pad(m2_all[:, k:]), m2_r,
        )

    return prep


@functools.lru_cache(maxsize=8)
def _verify_prep(k: int, kpad: int):
    """Jitted gather/pad prologue for the verify chain (see _pow_prep)."""

    @jax.jit
    def prep(idx, ukey):
        n_all, n_r, neg_ninv_b, ninv_all, m2_all, m2_r = tuple(
            u[idx] for u in ukey
        )
        pad = lambda x: jnp.pad(x, ((0, 0), (0, kpad - k)))
        return (
            pad(n_all[:, :k]), pad(n_all[:, k:]), n_r,
            pad(neg_ninv_b),
            pad(ninv_all[:, :k]), pad(ninv_all[:, k:]),
            pad(m2_all[:, :k]), pad(m2_all[:, k:]), m2_r,
        )

    return prep


@functools.lru_cache(maxsize=8)
def _pow_call(digits: int, n_bits: int, tile: int, interpret: bool):
    pc = _pad_consts(digits, n_bits)
    kpad, w_steps = pc.kpad, digits * 4
    consts = tuple(jnp.asarray(a) for a in pc.arrays())
    kernel = functools.partial(
        _pow_body, pc.invMq_pr, pc.invM_pr, w_steps
    )

    @jax.jit
    def run(base_h, nib_t, nb, nq, nr, ninvb, m2b, m2q, m2r):
        batch = base_h.shape[0]
        grid = batch // tile
        row = lambda width: pl.BlockSpec(
            (tile, width), lambda i: (i, 0), memory_space=pltpu.VMEM
        )
        full = lambda a: pl.BlockSpec(
            a.shape, lambda i: (0, 0), memory_space=pltpu.VMEM
        )
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((batch, kpad), jnp.float32),
            grid=(grid,),
            in_specs=[
                row(2 * digits),
                pl.BlockSpec(  # nibbles ride (W, T): blocked on axis 1
                    (w_steps, tile), lambda i: (0, i),
                    memory_space=pltpu.VMEM,
                ),
                row(kpad), row(kpad), row(1), row(kpad),
                row(kpad), row(kpad), row(1),
                *[full(c) for c in consts],
            ],
            out_specs=row(kpad),
            interpret=interpret,
        )(base_h, nib_t, nb, nq, nr, ninvb, m2b, m2q, m2r, *consts)

    return run


def pow_pallas(
    base_halves_u8: np.ndarray,  # (T, 2·digits) uint8
    exp_nibbles_t_u8: np.ndarray,  # (W, T) uint8, MS nibble first
    idx: np.ndarray,  # (T,) int32 into ukey
    ukey: tuple,  # stacked unique key rows (rns.stack_key_rows)
    *,
    digits: int,
    n_bits: int,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Drop-in for the XLA ``_jitted_pow`` path: returns (T, kpad) σ
    whose first k columns match ``rns._pow_kernel``'s output."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    t = base_halves_u8.shape[0]
    tile = min(TILE_POW, t)
    if t % tile:
        # grid = t // tile would silently drop the tail rows; in-repo
        # callers pad to powers of two, but this is a documented
        # drop-in for arbitrary batches — refuse loudly instead.
        raise ValueError(f"batch {t} not a multiple of tile {tile}")
    pc = _pad_consts(digits, n_bits)
    k, kpad = pc.k, pc.kpad
    run = _pow_call(digits, n_bits, tile, interpret)

    # Gather + pad per-row key tensors on device (XLA, outside pallas).
    nb, nq, nr, ninvb, m2b, m2q, m2r = _pow_prep(k, kpad)(
        jnp.asarray(idx), tuple(jnp.asarray(u) for u in ukey)
    )
    return run(
        jnp.asarray(base_halves_u8).astype(jnp.float32),
        jnp.asarray(exp_nibbles_t_u8).astype(jnp.float32),
        nb, nq, nr, ninvb, m2b, m2q, m2r,
    )[:, :k]


# ---------------------------------------------------------------------------
# Fused e=65537 verify chain
# ---------------------------------------------------------------------------


def _verify_body(invMq_pr, invM_pr, k, *refs):
    (sig_ref, em_ref, nb_ref, nq_ref, nr_ref, ninvb_ref,
     ninv_b_ref, ninv_q_ref, m2b_ref, m2q_ref, m2r_ref, *const_refs) = refs[:-1]
    out_ref = refs[-1]
    cx = _Ctx(const_refs, invMq_pr, invM_pr)

    key = (nb_ref[:], nq_ref[:], nr_ref[:], ninvb_ref[:])
    s = cx.to_residues(sig_ref[:])
    em_b, em_q, _em_r = cx.to_residues(em_ref[:])
    m2 = (m2b_ref[:], m2q_ref[:], m2r_ref[:])
    sm = cx.mont_mul(s, m2, key)
    acc = sm
    for _ in range(16):
        acc = cx.mont_mul(acc, acc, key)
    acc = cx.mont_mul(acc, sm, key)
    ones = cx.ones_like(sm)
    vb, vq, _vr = cx.mont_mul(acc, ones, key)

    delta_b = _mulmod(
        _submod(vb, em_b, cx.pb), ninv_b_ref[:], cx.ib, cx.pb
    )
    delta_q = _mulmod(
        _submod(vq, em_q, cx.pq), ninv_q_ref[:], cx.iq, cx.pq
    )
    alpha = delta_b[:, :1]
    lane = lax.broadcasted_iota(jnp.int32, delta_b.shape, 1)
    okb = jnp.all((delta_b == alpha) | (lane >= k), axis=1, keepdims=True)
    okq = jnp.all((delta_q == alpha) | (lane >= k), axis=1, keepdims=True)
    out_ref[:] = (
        okb & okq & (alpha <= np.float32(k + 1))
    ).astype(jnp.float32)


@functools.lru_cache(maxsize=8)
def _verify_call(digits: int, n_bits: int, tile: int, interpret: bool):
    pc = _pad_consts(digits, n_bits)
    kpad = pc.kpad
    consts = tuple(jnp.asarray(a) for a in pc.arrays())
    kernel = functools.partial(
        _verify_body, pc.invMq_pr, pc.invM_pr, pc.k
    )

    @jax.jit
    def run(sig_h, em_h, nb, nq, nr, ninvb, ninv_b, ninv_q, m2b, m2q, m2r):
        batch = sig_h.shape[0]
        grid = batch // tile
        row = lambda width: pl.BlockSpec(
            (tile, width), lambda i: (i, 0), memory_space=pltpu.VMEM
        )
        full = lambda a: pl.BlockSpec(
            a.shape, lambda i: (0, 0), memory_space=pltpu.VMEM
        )
        out = pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((batch, 1), jnp.float32),
            grid=(grid,),
            in_specs=[
                row(2 * digits), row(2 * digits),
                row(kpad), row(kpad), row(1), row(kpad),
                row(kpad), row(kpad),
                row(kpad), row(kpad), row(1),
                *[full(c) for c in consts],
            ],
            out_specs=row(1),
            interpret=interpret,
        )(sig_h, em_h, nb, nq, nr, ninvb, ninv_b, ninv_q, m2b, m2q, m2r, *consts)
        return out[:, 0] > 0

    return run


def verify_pallas(
    sig_halves_u8: np.ndarray,
    em_halves_u8: np.ndarray,
    idx: np.ndarray,
    ukey: tuple,
    *,
    digits: int = rns.DIGITS,
    n_bits: int = 2048,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Fused-chain equivalent of ``rns.verify_e65537_rns_indexed``."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    t = sig_halves_u8.shape[0]
    tile = min(TILE_VERIFY, t)
    if t % tile:
        # Unwritten tail rows would be *uninitialized verdicts* — a
        # fail-open hazard.  Refuse; callers pad (rsa._verify_rns does).
        raise ValueError(f"batch {t} not a multiple of tile {tile}")
    pc = _pad_consts(digits, n_bits)
    k, kpad = pc.k, pc.kpad
    run = _verify_call(digits, n_bits, tile, interpret)

    args = _verify_prep(k, kpad)(
        jnp.asarray(idx), tuple(jnp.asarray(u) for u in ukey)
    )
    return run(
        jnp.asarray(sig_halves_u8).astype(jnp.float32),
        jnp.asarray(em_halves_u8).astype(jnp.float32),
        *args,
    )
