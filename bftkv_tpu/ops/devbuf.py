"""Persistent width-keyed staging buffer rings for device launches.

Every batched kernel entry point (``rns.power_mod_rns``, the EC
scalar-mult path) used to rebuild its padded operand arrays from
scratch on EVERY flush: convert ``t`` live rows plus up to
``padded - t`` PAD rows through the int→limb→half pipelines, then hand
freshly-allocated numpy arrays to the jit.  At mega-batch rates that
host-side marshalling is pure overhead — the pad region never carries
information (rows past ``t`` are discarded), yet it was re-converted
through the same big-int pipeline as live data, and the allocator
churned multi-MB arrays per launch.

This module owns the fix: one :class:`BufferRing` per (width class,
padded shape) holds a small ring of pre-allocated slot arrays that
flushes write into *in place*.  Live rows land in ``[:t]``; the pad
region is a broadcast copy of row 0 (bit-identical to the historical
pad-with-item-0 convention, so kernels see byte-for-byte the same
operands — the host/device parity oracle stays intact).  A slot is
exclusively owned from :meth:`BufferRing.acquire` until
:meth:`BufferRing.release` — the in-flight bit flips under the ring
lock and release asserts it, so a buffer can never be reused while a
flush (or its async completion) is still in flight.  When every slot
is in flight the ring does NOT block the collector behind the device:
``acquire`` returns ``None`` (counted as ``devbuf.overflow``) and the
caller falls back to a fresh allocation for that launch.

Ring saturation is a first-class capacity signal: the
``devbuf.saturation`` gauge (per ``width`` label) feeds the capacity
plane's dispatch resource row (DESIGN.md §22), so a fleet operator
sees "the buffer rings are the wall" next to device occupancy and
launch RTT.
"""

from __future__ import annotations

import threading
import time

from bftkv_tpu import flags
from bftkv_tpu.metrics import registry as metrics
from bftkv_tpu.devtools.lockwatch import named_lock

__all__ = ["BufferRing", "Slot", "enabled", "ring_for", "reset", "stats"]

_lock = named_lock("ops.devbuf")
_RINGS: dict[str, "BufferRing"] = {}


def enabled() -> bool:
    return flags.enabled("BFTKV_DISPATCH_DEVBUF")


class Slot:
    """One pre-allocated staging buffer set (a dict of numpy arrays).

    Ownership protocol: exclusively the acquirer's from ``acquire()``
    until ``release()``.  ``seq`` increments per acquisition so a
    stale release (double-release after an async completion raced a
    crash path) is detectable instead of silently corrupting the next
    flush's operands.
    """

    __slots__ = ("arrays", "in_flight", "seq")

    def __init__(self, arrays: dict):
        self.arrays = arrays
        self.in_flight = False
        self.seq = 0

    def __getitem__(self, name: str):
        return self.arrays[name]


class BufferRing:
    """A fixed ring of staging slots for one width class.

    ``make`` builds one slot's array dict; it runs at ring creation
    (all slots pre-allocated up front — a launch never pays allocator
    latency) and whenever an overflow fallback needs a throwaway slot.
    ``width`` is the bounded metrics label value (a limb count such as
    ``"128"``, or ``"ec"``).
    """

    def __init__(self, key: str, make, *, slots: int | None = None,
                 width: str = "all"):
        if slots is None:
            slots = flags.get_int("BFTKV_DISPATCH_DEVBUF_RING") or 4
        self.key = key
        self.width = width
        self._make = make
        self._cv = threading.Condition(_lock)
        self._slots = [Slot(make()) for _ in range(max(1, slots))]
        self.overflows = 0
        self.acquires = 0

    def _gauge(self) -> None:
        busy = sum(1 for s in self._slots if s.in_flight)
        metrics.gauge(
            "devbuf.in_flight", busy, labels={"width": self.width}
        )
        metrics.gauge(
            "devbuf.saturation",
            busy / len(self._slots),
            labels={"width": self.width},
        )

    def acquire(self, timeout: float = 0.0) -> Slot | None:
        """A free slot, or ``None`` when the whole ring is in flight
        (after waiting up to ``timeout``).  ``None`` tells the caller
        to allocate fresh for this launch — the ring bounds memory, it
        must never bound liveness (a wedged device completion would
        otherwise deadlock every later flush)."""
        with self._cv:
            deadline = None
            while True:
                for s in self._slots:
                    if not s.in_flight:
                        s.in_flight = True
                        s.seq += 1
                        self.acquires += 1
                        self._gauge()
                        return s
                if timeout <= 0:
                    break
                if deadline is None:
                    deadline = time.monotonic() + timeout
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._cv.wait(timeout=remaining):
                    break
            self.overflows += 1
            metrics.incr("devbuf.overflow", labels={"width": self.width})
            self._gauge()
            return None

    def fresh(self) -> Slot:
        """An unpooled slot for the overflow path: same shapes, same
        write-in-place fill code, but owned by this launch alone and
        garbage-collected after it."""
        s = Slot(self._make())
        s.in_flight = True
        s.seq = 1
        return s

    def release(self, slot: Slot) -> None:
        if slot not in self._slots:
            return  # overflow (fresh) slot: nothing to return to the ring
        with self._cv:
            assert slot.in_flight, "devbuf: release of a slot not in flight"
            slot.in_flight = False
            self._gauge()
            self._cv.notify()


def ring_for(key: str, make, *, slots: int | None = None,
             width: str = "all") -> BufferRing:
    """The process-wide ring for ``key`` (created on first use).

    ``key`` encodes the full padded shape family (e.g.
    ``pow:38:608:256:64``) so a shape change mints a new ring instead
    of corrupting an old one; ``width`` is the bounded label the
    ring's gauges carry.
    """
    with _lock:
        r = _RINGS.get(key)
        if r is None:
            r = _RINGS[key] = BufferRing(
                key, make, slots=slots, width=width
            )
        return r


def stats() -> dict:
    """Per-ring occupancy snapshot (sidecar /info + tests)."""
    with _lock:
        return {
            key: {
                "width": r.width,
                "slots": len(r._slots),
                "in_flight": sum(1 for s in r._slots if s.in_flight),
                "acquires": r.acquires,
                "overflows": r.overflows,
            }
            for key, r in _RINGS.items()
        }


def reset() -> None:
    """Drop every ring (tests; a leaked in-flight slot dies with it)."""
    with _lock:
        _RINGS.clear()
