"""Batched big-integer arithmetic as JAX array programs.

This is the TPU re-design of the reference's per-item ``math/big`` hot
loops (``big.Int.Exp`` in crypto/auth/auth.go, crypto/threshold/rsa/rsa.go,
and RSA verify inside ``openpgp.CheckDetachedSignature`` — SURVEY.md §2).
Numbers are ``(batch, L)`` uint32 arrays of 16-bit digits (see
``bftkv_tpu.ops.limb``); every operation below is shape-static, branch-free
and batch-leading, so it jits once and vmaps/shards over the batch axis.

Design notes (TPU-first, no transliteration):

- digit products of 16-bit limbs are exact in uint32; column sums are kept
  exact by a lo/hi split (each partial sum stays under 2^24 for L ≤ 256);
- carry propagation is *parallel*: two local passes reduce lane values to
  digit + {0,1} carry, then a Kogge–Stone generate/propagate
  ``lax.associative_scan`` resolves the remaining ripple in O(log L) — no
  sequential limb loop anywhere;
- multiplication is a gather-based Toeplitz product: ``b`` is gathered
  into anti-diagonal alignment once, then the whole digit-product tensor
  reduces along one axis — XLA fuses this into a single pass;
- modular arithmetic is Montgomery form (REDC with R = 2^(16·L));
  exponentiation is fixed-4-bit-window with constant-time table gathers
  under ``lax.fori_loop`` (uniform schedule — SURVEY.md §7 hard part #3).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from bftkv_tpu.ops import limb as limb_codec
from bftkv_tpu.ops.limb import LIMB_BITS, LIMB_MASK

__all__ = [
    "MontgomeryDomain",
    "add",
    "carry_resolve",
    "geq",
    "mont_exp",
    "mont_mul",
    "mont_pow_static",
    "mul",
    "sub_mod_r",
]


def _shift_up(x: jnp.ndarray) -> jnp.ndarray:
    """Multiply by the limb base: out[..., k] = x[..., k-1], out[..., 0] = 0."""
    pad = [(0, 0)] * (x.ndim - 1) + [(1, 0)]
    return jnp.pad(x, pad)[..., :-1]


@functools.partial(jax.jit, static_argnums=1)
def carry_resolve(x: jnp.ndarray, out_len: int) -> jnp.ndarray:
    """Resolve lane values (< 2^32) into canonical 16-bit digits.

    The represented value Σ x_k·2^(16k) must fit in ``out_len`` digits.
    Two local passes bound each lane's outstanding carry to one bit, then a
    generate/propagate associative scan finishes the ripple in log time.
    """
    k = x.shape[-1]
    w = max(out_len, k) + 1
    x = jnp.pad(x.astype(jnp.uint32), [(0, 0)] * (x.ndim - 1) + [(0, w - k)])
    # Pass 1: split digit/carry (carry ≤ 2^16-1).
    e = (x & LIMB_MASK) + _shift_up(x >> LIMB_BITS)  # < 2^17
    # Pass 2: now carries are single bits.
    t = (e & LIMB_MASK) + _shift_up(e >> LIMB_BITS)  # ≤ 2^16
    r = t & LIMB_MASK
    g = (t >> LIMB_BITS).astype(jnp.bool_)  # generate
    p = r == LIMB_MASK  # propagate

    def comb(lo, hi):
        glo, plo = lo
        ghi, phi = hi
        return ghi | (phi & glo), plo & phi

    gg, _ = lax.associative_scan(comb, (g, p), axis=-1)
    carry_in = _shift_up(gg.astype(jnp.uint32))
    out = (r + carry_in) & LIMB_MASK
    return out[..., :out_len]


@functools.lru_cache(maxsize=None)
def _toeplitz_index(nl: int, ncols: int) -> tuple[np.ndarray, np.ndarray]:
    """idx[i, k] = k - i (clipped), mask[i, k] = 0 ≤ k - i < nl."""
    i = np.arange(nl)[:, None]
    k = np.arange(ncols)[None, :]
    d = k - i
    mask = (d >= 0) & (d < nl)
    return np.clip(d, 0, nl - 1).astype(np.int32), mask


def _mul_cols(a: jnp.ndarray, b: jnp.ndarray, ncols: int) -> jnp.ndarray:
    """Unresolved column sums of a·b, first ``ncols`` digit positions."""
    nl = a.shape[-1]
    idx, mask = _toeplitz_index(nl, ncols)
    bg = jnp.where(mask, b[..., idx], 0)  # (..., nl, ncols)
    p = a[..., :, None] * bg  # exact uint32 products of 16-bit digits
    lo = (p & LIMB_MASK).sum(axis=-2)  # ≤ nl·(2^16-1) < 2^24 for nl ≤ 256
    hi = (p >> LIMB_BITS).sum(axis=-2)
    return lo + _shift_up(hi)  # < 2^25


@jax.jit
def mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Full product, ``(..., L) × (..., L) → (..., 2L)``."""
    nl = a.shape[-1]
    return carry_resolve(_mul_cols(a, b, 2 * nl), 2 * nl)


def _mul_lo(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Low half of the product (mod R), ``(..., L) → (..., L)``."""
    nl = a.shape[-1]
    return carry_resolve(_mul_cols(a, b, nl), nl)


@functools.partial(jax.jit, static_argnums=2)
def add(a: jnp.ndarray, b: jnp.ndarray, out_len: int) -> jnp.ndarray:
    """a + b into ``out_len`` digits (must fit)."""
    w = max(a.shape[-1], b.shape[-1])

    def ext(x):
        return jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, w - x.shape[-1])])

    return carry_resolve(ext(a) + ext(b), out_len)


@jax.jit
def sub_mod_r(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """(a - b) mod R over the common digit width (two's-complement add)."""
    comp = (LIMB_MASK - jnp.asarray(b)).astype(jnp.uint32)
    s = jnp.asarray(a) + comp
    s = s.at[..., 0].add(1)
    return carry_resolve(s, a.shape[-1])


@jax.jit
def geq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Lexicographic a ≥ b over the last axis; returns (...,) bool."""
    ne = a != b
    # Highest differing digit (0 if all equal — then a == b there, so ≥).
    rev_arg = jnp.argmax(ne[..., ::-1], axis=-1)
    idx = a.shape[-1] - 1 - rev_arg
    at = jnp.take_along_axis(a, idx[..., None], axis=-1)[..., 0]
    bt = jnp.take_along_axis(b, idx[..., None], axis=-1)[..., 0]
    return at >= bt


def _cond_sub(t: jnp.ndarray, n: jnp.ndarray, hi: jnp.ndarray) -> jnp.ndarray:
    """t (+ hi·R) − n if that quantity is ≥ 0 and t < 2n; else t. L digits."""
    need = hi.astype(jnp.bool_) | geq(t, n)
    return jnp.where(need[..., None], sub_mod_r(t, n), t)


@jax.jit
def mont_mul(
    a: jnp.ndarray, b: jnp.ndarray, n: jnp.ndarray, n_prime: jnp.ndarray
) -> jnp.ndarray:
    """Montgomery product abR⁻¹ mod n (REDC). All inputs < n, L digits."""
    nl = a.shape[-1]
    t_cols = _mul_cols(a, b, 2 * nl)  # unresolved T = a·b
    t_lo = carry_resolve(t_cols[..., :nl], nl)  # T mod R (low half exact)
    m = _mul_lo(t_lo, jnp.broadcast_to(n_prime, t_lo.shape))
    mn_cols = _mul_cols(m, jnp.broadcast_to(n, m.shape), 2 * nl)
    # (T + m·n) / R: sum the unresolved columns, resolve into 2L+1 digits.
    s = carry_resolve(t_cols + mn_cols, 2 * nl + 1)  # sums < 2^26: exact
    t = s[..., nl : 2 * nl]
    hi = s[..., 2 * nl]
    return _cond_sub(t, jnp.broadcast_to(n, t.shape), hi)


@jax.jit
def to_mont(
    x: jnp.ndarray, r2: jnp.ndarray, n: jnp.ndarray, n_prime: jnp.ndarray
) -> jnp.ndarray:
    return mont_mul(x, jnp.broadcast_to(r2, x.shape), n, n_prime)


@jax.jit
def from_mont(x: jnp.ndarray, n: jnp.ndarray, n_prime: jnp.ndarray) -> jnp.ndarray:
    one = jnp.zeros_like(x).at[..., 0].set(1)
    return mont_mul(x, one, n, n_prime)


@functools.partial(jax.jit, static_argnums=1)
def mont_pow_static(
    a_mont: jnp.ndarray,
    e: int,
    n: jnp.ndarray,
    n_prime: jnp.ndarray,
) -> jnp.ndarray:
    """a^e in Montgomery form for a *static public* exponent (e.g. 65537).

    The square-and-multiply chain unrolls at trace time — RSA verify with
    e = 65537 is 17 Montgomery products, the ideal TPU case (SURVEY.md §7).
    """
    if e <= 0:
        raise ValueError("mont_pow_static: exponent must be positive")
    acc = a_mont
    for bit in bin(e)[3:]:  # skip leading 1
        acc = mont_mul(acc, acc, n, n_prime)
        if bit == "1":
            acc = mont_mul(acc, a_mont, n, n_prime)
    return acc


_WINDOW = 4


@jax.jit
def mont_exp(
    a_mont: jnp.ndarray,
    e: jnp.ndarray,
    n: jnp.ndarray,
    n_prime: jnp.ndarray,
    one_mont: jnp.ndarray,
) -> jnp.ndarray:
    """a^e in Montgomery form; ``e`` is a per-element (or shared) limb array.

    Fixed 4-bit windows with constant-time table gathers: a uniform
    schedule of 4 squarings + 1 table-select product per window, identical
    across the batch — no data-dependent control flow, so the whole loop
    compiles to one fused XLA while-region.
    """
    a_mont, n, n_prime, one_mont = jnp.broadcast_arrays(a_mont, n, n_prime, one_mont)
    e = jnp.asarray(e, dtype=jnp.uint32)
    if e.ndim < a_mont.ndim:
        e = jnp.broadcast_to(e, a_mont.shape[:-1] + e.shape[-1:])
    e_limbs = e.shape[-1]
    nwin = e_limbs * (LIMB_BITS // _WINDOW)

    # Power table t[j] = a^j·R mod n for j in [0, 16), shape (..., 16, L).
    def step(prev, _):
        nxt = mont_mul(prev, a_mont, n, n_prime)
        return nxt, nxt

    _, powers = lax.scan(step, one_mont, None, length=15)
    # scan stacks on axis 0: (15, ..., L) → (..., 16, L)
    powers = jnp.moveaxis(powers, 0, -2)
    table = jnp.concatenate([one_mont[..., None, :], powers], axis=-2)

    def body(j, acc):
        # Window j counts from the most significant end.
        widx = nwin - 1 - j
        limb_idx = widx // (LIMB_BITS // _WINDOW)
        shift = (widx % (LIMB_BITS // _WINDOW)) * _WINDOW
        wv = (
            jnp.take_along_axis(
                e, jnp.broadcast_to(limb_idx, e.shape[:-1])[..., None], axis=-1
            )[..., 0]
            >> shift
        ) & (2**_WINDOW - 1)
        for _ in range(_WINDOW):
            acc = mont_mul(acc, acc, n, n_prime)
        sel = jnp.take_along_axis(
            table, wv[..., None, None].astype(jnp.int32), axis=-2
        )[..., 0, :]
        return mont_mul(acc, sel, n, n_prime)

    return lax.fori_loop(0, nwin, body, one_mont)


class MontgomeryDomain:
    """Host-side precomputation for one odd modulus.

    Holds ``n``, ``n' = -n⁻¹ mod R`` and ``R² mod n`` as limb arrays ready
    to broadcast against ``(batch, L)`` operands. Stack several with
    ``np.stack`` for per-element moduli.
    """

    def __init__(self, n: int, nlimbs: int | None = None):
        if n % 2 == 0:
            raise ValueError("Montgomery modulus must be odd")
        if nlimbs is None:
            nlimbs = limb_codec.nlimbs_for_bits(n.bit_length())
        self.n_int = n
        self.nlimbs = nlimbs
        r = 1 << (LIMB_BITS * nlimbs)
        if n >= r:
            raise ValueError("modulus does not fit limb count")
        self.r_int = r
        n_prime = (-pow(n, -1, r)) % r
        r2 = (r * r) % n
        self.n = limb_codec.int_to_limbs(n, nlimbs)
        self.n_prime = limb_codec.int_to_limbs(n_prime, nlimbs)
        self.r2 = limb_codec.int_to_limbs(r2, nlimbs)
        self.one_mont = limb_codec.int_to_limbs(r % n, nlimbs)

    def encode(self, xs: list[int]) -> np.ndarray:
        """ints → Montgomery-form limb batch (host-side, for setup paths)."""
        return limb_codec.ints_to_limbs(
            [(x * self.r_int) % self.n_int for x in xs], self.nlimbs
        )

    def decode(self, a) -> list[int]:
        """Montgomery-form limb batch → ints (host-side)."""
        return [
            (x * pow(self.r_int, -1, self.n_int)) % self.n_int
            for x in limb_codec.limbs_to_ints(np.asarray(a))
        ]
