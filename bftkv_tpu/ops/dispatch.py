"""Cross-request batching dispatcher — the TPU verification sidecar.

The reference verifies signatures one at a time inside each request
handler (crypto_pgp.go:485-500 called from server.go:207,300).  On TPU
that wastes the device: a single RSA-2048 e=65537 verify is ~17 modmuls
over 64 limbs — three orders of magnitude below a v5e's appetite.  The
dispatcher turns per-request verify calls from *concurrent* server
handlers into shared device launches:

- callers submit their (message, sig, key) batches and block on a
  future;
- a collector thread flushes when ``max_batch`` items are pending or
  ``max_wait`` elapsed since the first pending item (latency floor for
  low load — SURVEY §7 hard part 2);
- one ``VerifierDomain.verify_batch`` launch serves every caller in the
  flush; results are scattered back to the futures.

Deployment stance: replicas are mutually distrusting, so a dispatcher
serves exactly one replica's trust domain (or an in-process cluster in
tests/benchmarks, where the host is one trust domain by construction).
Batch-occupancy and latency are exported through
:mod:`bftkv_tpu.metrics` as ``dispatch.batch`` / ``dispatch.wait``.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from bftkv_tpu.metrics import registry as metrics

__all__ = ["VerifyDispatcher", "install", "uninstall", "get"]


class _Pending:
    __slots__ = ("items", "event", "result", "error")

    def __init__(self, items):
        self.items = items
        self.event = threading.Event()
        self.result = None
        self.error: Exception | None = None


class VerifyDispatcher:
    """Accumulates verify requests across threads into device batches."""

    def __init__(self, verifier=None, *, max_batch: int = 1024, max_wait: float = 0.002):
        if verifier is None:
            from bftkv_tpu.crypto import rsa as rsamod

            verifier = rsamod.VerifierDomain()
        self.verifier = verifier
        self.max_batch = max_batch
        self.max_wait = max_wait
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._queue: list[_Pending] = []
        self._queued_items = 0
        self._running = False
        self._thread: threading.Thread | None = None

    # -- lifecycle --------------------------------------------------------

    def start(self) -> "VerifyDispatcher":
        with self._lock:
            if self._running:
                return self
            self._running = True
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        with self._cv:
            self._running = False
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    # -- caller side ------------------------------------------------------

    def verify(self, items: list) -> np.ndarray:
        """Blocking batched verify; safe from any thread."""
        if not items:
            return np.zeros((0,), dtype=bool)
        p = _Pending(items)
        t0 = time.perf_counter()
        with self._cv:
            # _running is checked under the lock: a stop() racing with an
            # unlocked check could let the collector exit after the check
            # but before the append, stranding this entry forever.
            running = self._running
            if running:
                self._queue.append(p)
                self._queued_items += len(items)
                self._cv.notify_all()
        if not running:
            return self.verifier.verify_batch(items)
        p.event.wait()
        metrics.observe("dispatch.wait", time.perf_counter() - t0)
        if p.error is not None:
            raise p.error
        return p.result

    # -- collector --------------------------------------------------------

    def _run(self) -> None:
        while True:
            with self._cv:
                while self._running and not self._queue:
                    self._cv.wait()
                if not self._running and not self._queue:
                    return
                # Wait for more work up to max_wait after the first
                # pending item, unless the batch target is already met.
                deadline = time.monotonic() + self.max_wait
                while (
                    self._running
                    and self._queued_items < self.max_batch
                    and (remaining := deadline - time.monotonic()) > 0
                ):
                    self._cv.wait(timeout=remaining)
                batch = self._queue
                self._queue = []
                self._queued_items = 0
            self._flush(batch)

    def _flush(self, batch: list[_Pending]) -> None:
        flat = [it for p in batch for it in p.items]
        metrics.observe("dispatch.batch", len(flat))
        metrics.incr("dispatch.flushes")
        metrics.incr("dispatch.verifies", len(flat))
        try:
            if len(flat) <= self.max_batch:
                ok = self.verifier.verify_batch(flat)
            else:
                # A burst can out-run the collector and drain as one
                # oversized queue; chunk the device launches so padded
                # batch shapes stay bounded by max_batch.
                ok = np.concatenate(
                    [
                        self.verifier.verify_batch(flat[i : i + self.max_batch])
                        for i in range(0, len(flat), self.max_batch)
                    ]
                )
        except Exception as e:
            for p in batch:
                p.error = e
                p.event.set()
            return
        off = 0
        for p in batch:
            p.result = ok[off : off + len(p.items)]
            off += len(p.items)
            p.event.set()


_global: VerifyDispatcher | None = None
_global_lock = threading.Lock()


def install(dispatcher: VerifyDispatcher | None = None) -> VerifyDispatcher:
    """Install (and start) the process-wide dispatcher; verification
    call sites (``CollectiveSignature.verify``) route through it."""
    global _global
    with _global_lock:
        if _global is not None:
            _global.stop()
        _global = (dispatcher or VerifyDispatcher()).start()
        return _global


def uninstall() -> None:
    global _global
    with _global_lock:
        if _global is not None:
            _global.stop()
            _global = None


def get() -> VerifyDispatcher | None:
    return _global
