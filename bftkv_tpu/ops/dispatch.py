"""Cross-request batching dispatchers — the TPU crypto sidecar.

The reference runs every RSA operation one at a time inside each request
handler (crypto_pgp.go:485-500 called from server.go:207,300; DetachSign
at crypto_pgp.go:346-371).  On TPU that wastes the device: a single
RSA-2048 e=65537 verify is ~17 modmuls over 128 limbs — three orders of
magnitude below a v5e's appetite — and host ``pow`` holds the GIL, so
per-handler signing also serializes the whole server.  The dispatchers
turn per-request crypto calls from *concurrent* threads into shared
device launches:

- callers submit their item batches and block on a future;
- a collector thread flushes when ``max_batch`` items are pending or
  ``max_wait`` elapsed since the first pending item (latency floor for
  low load — SURVEY §7 hard part 2);
- one batched kernel launch serves every caller in the flush; results
  are scattered back to the futures;
- up to ``pipeline`` flushes run concurrently (default 2): batch N+1's
  host assembly and transfer overlap batch N's device round trip (the
  device stream serializes the kernels; on a tunneled accelerator the
  ~100 ms launch RTT otherwise leaves the device idle between flushes).

Two instances exist: the **verify** dispatcher (collective-signature
verification, ``VerifierDomain.verify_batch``) and the **sign**
dispatcher (collective-signature share issuance,
``SignerDomain.sign_batch`` — batched CRT modexp).  Both fall back to
host crypto below their crossover batch size.

Deployment stance: replicas are mutually distrusting, so a dispatcher
serves exactly one replica's trust domain (or an in-process cluster in
tests/benchmarks, where the host is one trust domain by construction).
Batch-occupancy and latency are exported through
:mod:`bftkv_tpu.metrics` as ``<name>.batch`` / ``<name>.wait``.
"""

from __future__ import annotations

import queue
import threading
import time

import numpy as np

from bftkv_tpu import trace
from bftkv_tpu.faults import failpoint as fp
from bftkv_tpu.metrics import registry as metrics
from bftkv_tpu import flags
from bftkv_tpu.devtools.lockwatch import named_lock

__all__ = [
    "VerifyDispatcher",
    "SignDispatcher",
    "ModexpDispatcher",
    "install",
    "uninstall",
    "get",
    "install_signer",
    "uninstall_signer",
    "get_signer",
    "uninstall_all",
    "note_launch_rtt",
    "observed_launch_rtt",
    "recalibrate",
]


#: Sentinel crossover meaning "the device never wins for this backend".
ALWAYS_HOST = 1 << 30

_CALIBRATION: dict | None = None
_calibration_lock = named_lock("dispatch.calibration")
_LAUNCH_RTT_EWMA: float | None = None


def note_launch_rtt(seconds: float) -> None:
    """Feed one observed launch round trip into the online-recalibration
    EWMA (α = 0.2) and the ``dispatch.launch_rtt`` gauge.

    The boot-time calibration probes a trivial jitted op; real flushes
    measure the thing itself.  :func:`recalibrate` prefers this series
    over a fresh probe, so a tunneled accelerator whose RTT drifts (or
    a device that appears mid-run) re-prices the crossover from what
    launches actually cost."""
    global _LAUNCH_RTT_EWMA
    with _calibration_lock:
        prev = _LAUNCH_RTT_EWMA
        _LAUNCH_RTT_EWMA = (
            seconds if prev is None else 0.8 * prev + 0.2 * seconds
        )
        metrics.gauge("dispatch.launch_rtt", _LAUNCH_RTT_EWMA)


def observed_launch_rtt() -> float | None:
    with _calibration_lock:
        return _LAUNCH_RTT_EWMA


def calibration(force: bool = False) -> dict:
    """Measured host-verify cost vs device launch RTT, once per process.

    The host/device crossover used to be a hard-coded constant
    (``VerifierDomain.HOST_CROSSOVER = 192``), which is wrong in both
    directions: on a locally-attached accelerator the launch RTT is a
    few ms, so protocol-sized batches (~24 items at cluster_4) should
    engage the device but never reached the constant; on a CPU backend
    the XLA kernels are slower than host ``pow`` at EVERY batch size
    (the RNS kernels are MXU-shaped), so the constant let 16-writer
    bursts cross it and sink whole seconds into CPU-XLA flushes
    (BENCH_r05: 1,126 device signs on the CPU fallback).

    Measures (a) per-item host e=65537 verify cost via raw ``pow`` on a
    fixed 2048-bit modulus and (b) the device launch round trip via a
    trivial jitted op on device-resident operands — a lower bound on
    any real kernel launch.  ``crossover ≈ rtt / host_per_item`` is the
    batch size where one launch starts beating the host loop.  On a CPU
    "device" the kernels themselves lose to host ``pow`` regardless of
    batch, so the crossover pins to :data:`ALWAYS_HOST`.
    """
    global _CALIBRATION
    with _calibration_lock:
        if _CALIBRATION is not None and not force:
            return _CALIBRATION
        import jax

        backend = jax.default_backend()
        env = flags.raw("BFTKV_DISPATCH_CROSSOVER")
        if env is not None:
            # Operator override: outranks every measurement.  ≤ 0 pins
            # always-host; a positive value is the verify crossover
            # batch size (and un-pins the backend regardless of what a
            # probe would say — the operator knows their accelerator).
            x = int(env)
            pinned = x <= 0
            cal = {
                "backend": backend,
                "host_verify_s": None,
                "device_rtt_s": _LAUNCH_RTT_EWMA,
                "verify_crossover": ALWAYS_HOST if pinned else x,
                "sign_crossover": ALWAYS_HOST if pinned else None,
                "prefer_host": pinned,
                "source": "override",
            }
            metrics.gauge(
                "dispatch.crossover", -1 if pinned else x
            )
            _CALIBRATION = cal
            return cal
        # Host per-item cost: raw pow on a fixed odd 2048-bit modulus —
        # the dominant term of a host verify, no keygen required.
        n = (1 << 2047) + 973  # odd, full-width; exactness is irrelevant
        s = (1 << 2040) // 7
        t0 = time.perf_counter()
        reps = 12
        for _ in range(reps):
            pow(s, 65537, n)
        host_s = (time.perf_counter() - t0) / reps
        if backend == "cpu":
            cal = {
                "backend": backend,
                "host_verify_s": host_s,
                "device_rtt_s": None,
                "verify_crossover": ALWAYS_HOST,
                "sign_crossover": ALWAYS_HOST,
                "prefer_host": True,
                "source": "probe",
            }
        else:
            # Online recalibration: once real flushes have measured
            # their own round trips (note_launch_rtt), the EWMA of the
            # thing itself outranks the trivial-op probe — the probe is
            # a lower bound, the EWMA is the price actually paid.
            rtt = _LAUNCH_RTT_EWMA
            source = "observed"
            if rtt is None:
                import jax.numpy as jnp

                f = jax.jit(lambda x: x * 2 + 1)
                x = jax.device_put(jnp.zeros((256, 128), jnp.uint32))
                jax.block_until_ready(f(x))  # compile outside the timing
                t0 = time.perf_counter()
                for _ in range(3):
                    jax.block_until_ready(f(x))
                rtt = (time.perf_counter() - t0) / 3
                source = "probe"
            cal = {
                "backend": backend,
                "host_verify_s": host_s,
                "device_rtt_s": rtt,
                # Floor of 16 so a noisy fast-RTT measurement cannot
                # push tiny batches onto the device.
                "verify_crossover": max(16, int(rtt / max(host_s, 1e-7))),
                # Sign launches are far heavier than the probe op;
                # keep the signer's proven default on real devices.
                "sign_crossover": None,
                "prefer_host": False,
                "source": source,
            }
        metrics.gauge(
            "dispatch.crossover",
            -1 if cal["verify_crossover"] == ALWAYS_HOST
            else cal["verify_crossover"],
        )
        _CALIBRATION = cal
        return cal


class _Pending:
    __slots__ = ("items", "event", "result", "error")

    def __init__(self, items):
        self.items = items
        self.event = threading.Event()
        self.result = None
        self.error: Exception | None = None


class _BatchDispatcher:
    """Accumulates per-thread requests into shared device batches."""

    #: metrics prefix; subclasses override.
    name = "dispatch"

    #: Flushes in flight at once (``BFTKV_DISPATCH_PIPELINE`` overrides).
    #: A flush is [host assembly | device round trip | scatter]; with a
    #: single stream the device idles through both host phases, and on
    #: a tunneled accelerator the ~100 ms launch RTT dominates them.
    #: Two in-flight flushes let batch N+1 assemble and transfer while
    #: batch N computes — jax dispatch is async and the device stream
    #: serializes the actual kernels, so on an accelerator this is pure
    #: overlap.  On CPU the "device" is the host: a second flush worker
    #: contends with the kernel for cores instead of filling idle
    #: device time (measured ~14% slower on the 16-replica batched
    #: bench), so the default resolves per backend at start().  1
    #: forces strict serial flushing.
    DEFAULT_PIPELINE_TPU = 2

    def __init__(
        self,
        *,
        max_batch: int = 1024,
        max_wait: float = 0.002,
        pipeline: int | None = None,
        calibrate: bool | None = None,
    ):

        self.max_batch = max_batch
        self.max_wait = max_wait
        if calibrate is None:
            calibrate = flags.raw("BFTKV_DISPATCH_CALIBRATE", "1") != "0"
        self._calibrate = calibrate
        #: True once install-time calibration decides the host beats a
        #: device launch at ANY batch this backend can see — call sites
        #: (``Signer.issue_many``, :meth:`VerifyDispatcher.verify`) then
        #: skip the collector wait + flush queue and run host inline.
        self._prefer_host = False
        if pipeline is None:
            env = flags.raw("BFTKV_DISPATCH_PIPELINE")
            pipeline = int(env) if env else None
        self.pipeline = max(1, pipeline) if pipeline is not None else None
        self._inflight: threading.BoundedSemaphore | None = None
        self._work: "queue.SimpleQueue[list[_Pending] | None]" | None = None
        self._workers: list[threading.Thread] = []
        #: Async mega-batch dispatch (``BFTKV_DISPATCH_ASYNC``): flushes
        #: whose subclass implements :meth:`_launch_batch` hand the
        #: device a non-blocking launch and return immediately; a single
        #: completion-drain thread finalizes launches FIFO and scatters
        #: results, so flush N+1's host assembly overlaps flush N's
        #: device execution.  ``off`` restores the fully synchronous
        #: flush (pre-r11 behavior, byte for byte).
        self._async = flags.enabled("BFTKV_DISPATCH_ASYNC")
        self._completions: "queue.SimpleQueue | None" = None
        self._async_slots: threading.BoundedSemaphore | None = None
        self._drain: threading.Thread | None = None
        self._lock = named_lock("dispatch.batcher")
        self._cv = threading.Condition(self._lock)
        self._queue: list[_Pending] = []
        self._queued_items = 0
        self._running = False
        self._thread: threading.Thread | None = None

    # -- subclass hooks ---------------------------------------------------

    def _run_batch(self, items: list):
        """One batched launch; returns a sequence aligned with items."""
        raise NotImplementedError

    def _launch_batch(self, items: list):
        """Non-blocking launch hook for the async path: stage ``items``
        into (persistent) device buffers, hand the kernel launch to the
        device WITHOUT blocking on its result, and return a zero-arg
        completion callable that blocks on the device and returns a
        sequence aligned with ``items``.  Return ``None`` to decline —
        the flush then takes the synchronous :meth:`_run_batch` path
        (the default: only subclasses with a genuinely async device
        tier opt in)."""
        return None

    def prefer_host(self, n_items: int) -> bool:
        """True when calibration proved these items end on host either
        way, so the caller should skip the dispatcher round trip."""
        return self._prefer_host

    def _combine(self, chunks: list):
        return np.concatenate(chunks)

    def _empty(self):
        return np.zeros((0,), dtype=bool)

    # -- lifecycle --------------------------------------------------------

    def start(self):
        if self.pipeline is None:
            # Deferred so constructing a dispatcher never forces jax
            # backend init; by start() the process has long since chosen.
            import jax

            self.pipeline = (
                self.DEFAULT_PIPELINE_TPU
                if jax.default_backend() == "tpu"
                else 1
            )
        with self._lock:
            if self._running:
                return self
            self._running = True
        if self.pipeline > 1 and not self._workers:
            # Persistent flush workers (no per-flush thread churn; a
            # thread-creation failure surfaces HERE, before any caller
            # has a future at stake).  The semaphore bounds batches
            # handed off but not yet flushed, so the collector stalls
            # — and submits keep coalescing — when the pipeline is full.
            self._inflight = threading.BoundedSemaphore(self.pipeline)
            self._work = queue.SimpleQueue()
            self._workers = [
                threading.Thread(
                    target=self._flush_worker,
                    args=(self._work, self._inflight),
                    daemon=True,
                )
                for _ in range(self.pipeline)
            ]
            for w in self._workers:
                w.start()
        if self._async and self._drain is None:
            # One drain thread regardless of pipeline width: completions
            # finalize FIFO, so async callers observe the same wake
            # ordering the synchronous path gave them.  The semaphore
            # bounds launches dispatched but not yet finalized —
            # assembly of the next flush overlaps the device, but a slow
            # device cannot accumulate unbounded staged batches.
            self._completions = queue.SimpleQueue()
            self._async_slots = threading.BoundedSemaphore(
                (self.pipeline or 1) + 1
            )
            self._drain = threading.Thread(
                target=self._completion_drain,
                args=(self._completions,),
                daemon=True,
            )
            self._drain.start()
        self._thread = threading.Thread(target=self._collector, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        with self._cv:
            self._running = False
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        # Drain the worker pool: queued batches flush first (FIFO),
        # then each worker eats one sentinel and exits.  Joining the
        # workers IS the no-caller-left-waiting guarantee; a worker
        # wedged past the timeout (hung device call) is abandoned as a
        # daemon thread — its callers are hung on the device either way.
        if self._workers:
            for _ in self._workers:
                self._work.put(None)
            for w in self._workers:
                w.join(timeout=5)
            self._workers = []
            self._work = None
            self._inflight = None
        # Drain the completion thread LAST: the collector and every
        # flush worker are joined above, so all async launches are
        # already enqueued ahead of this sentinel (FIFO) — no caller's
        # completion can arrive after it.
        if self._drain is not None:
            self._completions.put(None)
            self._drain.join(timeout=5)
            self._drain = None
            self._completions = None
            self._async_slots = None

    def _flush_worker(self, work, inflight) -> None:
        # Queue + semaphore ride in as locals: a worker abandoned by a
        # timed-out stop() join must keep releasing the OLD semaphore,
        # never a successor pool's (instance attrs are re-created on
        # restart).
        while True:
            batch = work.get()
            if batch is None:
                return
            try:
                self._flush(batch)
            finally:
                inflight.release()

    # -- caller side ------------------------------------------------------

    def submit(self, items: list):
        """Blocking batched call; safe from any thread."""
        if not items:
            return self._empty()
        p = _Pending(items)
        t0 = time.perf_counter()
        with self._cv:
            # _running is checked under the lock: a stop() racing with an
            # unlocked check could let the collector exit after the check
            # but before the append, stranding this entry forever.
            running = self._running
            if running:
                self._queue.append(p)
                self._queued_items += len(items)
                self._cv.notify_all()
        if not running:
            return self._run_batch(items)
        if trace.capture() is not None:
            # Inside a request trace: the queue wait is the "dispatch"
            # phase of that request's wall-clock budget (DESIGN.md §18).
            # No active trace (background flushers, bench drivers) —
            # skip the span rather than minting orphan roots.
            with trace.span(
                "dispatch.wait",
                attrs={"items": len(items), "pool": self.name},
            ):
                p.event.wait()
        else:
            p.event.wait()
        metrics.observe(f"{self.name}.wait", time.perf_counter() - t0)
        if p.error is not None:
            raise p.error
        return p.result

    # -- collector --------------------------------------------------------

    def _collector(self) -> None:
        # Local refs for the same reason as _flush_worker: a collector
        # that outlives a timed-out stop() join must finish against the
        # pool it started with.
        inflight, work = self._inflight, self._work
        while True:
            with self._cv:
                while self._running and not self._queue:
                    self._cv.wait()
                if not self._running and not self._queue:
                    return
                # Wait for more work up to max_wait after the first
                # pending item, unless the batch target is already met.
                deadline = time.monotonic() + self.max_wait
                while (
                    self._running
                    and self._queued_items < self.max_batch
                    and (remaining := deadline - time.monotonic()) > 0
                ):
                    self._cv.wait(timeout=remaining)
                # Bounded pop: whole pending entries up to ``max_batch``
                # items (always at least one).  Draining the queue
                # unboundedly would merge every queued caller's batch
                # into one flush and make EACH wait for ALL — the
                # head-of-line latency no chunking inside the flush can
                # undo (results scatter only when the whole flush
                # returns).  The remainder flushes on the next loop
                # iteration, so a burst still coalesces into
                # max_batch-sized launches.
                batch = []
                taken = 0
                while self._queue and (
                    not batch
                    or taken + len(self._queue[0].items) <= self.max_batch
                ):
                    p = self._queue.pop(0)
                    batch.append(p)
                    taken += len(p.items)
                self._queued_items -= taken
            if self.pipeline == 1:
                self._flush(batch)
            else:
                # Bounded hand-off: at most ``pipeline`` batches past
                # this point.  With the permit held, the collector
                # stalls (stops draining the queue) whenever the
                # pipeline is full, so submits keep coalescing into
                # bigger batches — the same backpressure the serial
                # collector had.
                inflight.acquire()
                if not self._running:
                    # stop() began while we waited for a permit; the
                    # sentinels may already be queued ahead of this
                    # batch.  Flush inline so these callers are served,
                    # not stranded behind a drained pool.
                    try:
                        self._flush(batch)
                    finally:
                        inflight.release()
                else:
                    work.put(batch)

    def _flush(self, batch: list[_Pending]) -> None:
        if fp.ARMED:
            # ``dispatch.flush`` failpoint: a stalled device launch —
            # every caller blocked on this flush waits it out, which is
            # exactly what a wedged accelerator round trip looks like.
            act = fp.fire("dispatch.flush", name=self.name)
            if act is not None and act.kind == "stall":
                time.sleep(fp.delay_seconds(act))
        flat = [it for p in batch for it in p.items]
        occupancy = len(flat) / self.max_batch
        metrics.observe(f"{self.name}.batch", len(flat))
        metrics.gauge(f"{self.name}.occupancy", occupancy)
        metrics.incr(f"{self.name}.flushes")
        metrics.incr(f"{self.name}.items", len(flat))
        # Device-occupancy: items-per-LAUNCH vs the calibrated max batch.
        # Distinct from ``.occupancy`` when an oversized flush chunks
        # into several launches — each launch is then near-full even
        # though flat/max_batch > 1 (capacity plane reads this gauge).
        launches = max(1, -(-len(flat) // self.max_batch))
        metrics.incr(f"{self.name}.launches", launches)
        metrics.gauge(
            f"{self.name}.device_occupancy",
            len(flat) / (launches * self.max_batch),
            labels={"width": "all"},
        )
        t0 = time.perf_counter()
        if (
            self._async
            and self._completions is not None
            and len(flat) <= self.max_batch
        ):
            # Async path: ask the subclass for a non-blocking launch.
            # Semaphore + completion queue ride in as locals for the
            # same abandoned-worker reason as _flush_worker.
            slots, completions = self._async_slots, self._completions
            slots.acquire()
            completion = None
            try:
                with trace.span(
                    f"{self.name}.launch",
                    attrs={"batch_size": len(flat)},
                    phase="dispatch",
                ):
                    completion = self._launch_batch(flat)
            except Exception as e:
                slots.release()
                for p in batch:
                    p.error = e
                    p.event.set()
                return
            if completion is not None:
                completions.put((batch, len(flat), completion, t0, slots))
                return
            slots.release()
        # Each flush is its own (root) trace: device batches are shared
        # across requests, so they cannot belong to any one request's
        # trace — the span carries the batch shape and, once the launch
        # returns, the measured items/s the batch actually achieved.
        with trace.span(
            f"{self.name}.flush",
            attrs={
                "batch_size": len(flat),
                "occupancy": round(occupancy, 4),
            },
            # Dynamic name: declare the phase explicitly (the
            # span-phase lint cannot resolve f-strings with no
            # leading literal against trace.SPAN_PHASES).
            phase="dispatch",
        ) as sp:
            try:
                if len(flat) <= self.max_batch:
                    out = self._run_batch(flat)
                else:
                    # A burst can out-run the collector and drain as one
                    # oversized queue; chunk the device launches so padded
                    # batch shapes stay bounded by max_batch.
                    out = self._combine(
                        [
                            self._run_batch(flat[i : i + self.max_batch])
                            for i in range(0, len(flat), self.max_batch)
                        ]
                    )
            except Exception as e:
                # Swallow, never raise: the error reaches every caller
                # through its future, and raising here would kill the
                # collector / flush-worker thread for good.
                sp.attrs["error"] = repr(e)
                for p in batch:
                    p.error = e
                    p.event.set()
                return
            dt = time.perf_counter() - t0
            metrics.observe(f"{self.name}.flush.seconds", dt)
            if dt > 0:
                throughput = len(flat) / dt
                sp.attrs["items_per_s"] = round(throughput, 1)
                metrics.gauge(f"{self.name}.throughput", throughput)
        off = 0
        for p in batch:
            p.result = out[off : off + len(p.items)]
            off += len(p.items)
            p.event.set()

    def _completion_drain(self, completions) -> None:
        # Finalizes async launches strictly FIFO: block on the device
        # result, scatter to futures, feed the observed round trip into
        # online recalibration.  A completion that raises reaches its
        # callers through their futures — the drain thread, like the
        # flush workers, must never die to an item error.
        while True:
            entry = completions.get()
            if entry is None:
                return
            batch, n_items, completion, t0, slots = entry
            try:
                out = completion()
            except Exception as e:
                for p in batch:
                    p.error = e
                    p.event.set()
                continue
            finally:
                slots.release()
            dt = time.perf_counter() - t0
            metrics.observe(f"{self.name}.flush.seconds", dt)
            if dt > 0:
                metrics.gauge(f"{self.name}.throughput", n_items / dt)
            note_launch_rtt(dt)
            off = 0
            for p in batch:
                p.result = out[off : off + len(p.items)]
                off += len(p.items)
                p.event.set()


class VerifyDispatcher(_BatchDispatcher):
    """Batched signature verification (items: (message, sig, PublicKey))."""

    name = "dispatch"  # historical metric names kept stable

    def __init__(
        self,
        verifier=None,
        *,
        max_batch: int = 1024,
        max_wait: float = 0.002,
        pipeline: int | None = None,
        calibrate: bool | None = None,
    ):
        super().__init__(
            max_batch=max_batch,
            max_wait=max_wait,
            pipeline=pipeline,
            calibrate=calibrate,
        )
        if verifier is None:
            from bftkv_tpu.crypto import rsa as rsamod

            verifier = rsamod.VerifierDomain()
        self.verifier = verifier

    def start(self):
        super().start()
        if self._calibrate:
            self.apply_calibration(calibration())
        return self

    def apply_calibration(self, cal: dict) -> None:
        """(Re-)apply a calibration verdict — called at start() and by
        :func:`recalibrate` when online measurement moves the pin."""
        # An explicit env threshold is the operator's word and
        # outranks the measurement.
        if flags.raw("BFTKV_HOST_VERIFY_THRESHOLD") is None:
            self.verifier.host_threshold = cal["verify_crossover"]
        self._prefer_host = cal["prefer_host"]

    def _run_batch(self, items: list):
        return self.verifier.verify_batch(items)

    def verify(self, items: list) -> np.ndarray:
        if self._prefer_host:
            # Calibrated all-host backend: the flush would run the same
            # host loop anyway; inline skips max_wait + queueing.
            metrics.incr("dispatch.verifies", len(items))
            return self.verifier.verify_batch(items)
        out = self.submit(items)
        metrics.incr("dispatch.verifies", len(items))
        return out


class SignDispatcher(_BatchDispatcher):
    """Batched signing (items: (message, PrivateKey) — RSA or EC P-256).

    The server-side hot loop this absorbs is collective-signature share
    issuance — one private op per server per sign request
    (reference: crypto_pgp.go:346-371 via server.go:264) — which
    otherwise serializes the whole process behind the GIL.  A flush
    partitions by algorithm: RSA items ride one CRT-modexp launch; EC
    items group by key and ride one nonce base-mult launch per key
    (ADVICE r4 #3: EC used to bypass the dispatcher, so concurrent
    writers' EC batches never coalesced across threads).
    """

    name = "signdispatch"

    #: A sign launch costs ~115 ms regardless of batch, so waiting
    #: 20 ms to fill it is cheap: measured at 16 replicas, 2 ms flushes
    #: give batch-p50 ~17 and ~2 writes/s; 20 ms gives ~41 and ~15.
    DEFAULT_MAX_WAIT = 0.02

    def __init__(
        self,
        signer=None,
        *,
        max_batch: int = 1024,
        max_wait: float | None = None,
        pipeline: int | None = None,
        calibrate: bool | None = None,
    ):
        super().__init__(
            max_batch=max_batch,
            max_wait=self.DEFAULT_MAX_WAIT if max_wait is None else max_wait,
            pipeline=pipeline,
            calibrate=calibrate,
        )
        if signer is None:
            from bftkv_tpu.crypto import rsa as rsamod

            signer = rsamod.SignerDomain()
        self.signer = signer
        # The signer's proven built-in crossover, captured before any
        # calibration pin touches it: a later recalibration that
        # un-pins the backend (accelerator appeared) restores this
        # rather than leaving the boot-time ALWAYS_HOST in place.
        self._signer_default_threshold = getattr(
            signer, "host_threshold", None
        )

    def start(self):
        super().start()
        if self._calibrate:
            self.apply_calibration(calibration())
        return self

    def apply_calibration(self, cal: dict) -> None:
        self._prefer_host = cal["prefer_host"]
        if flags.raw("BFTKV_HOST_SIGN_THRESHOLD") is not None:
            return
        if cal["sign_crossover"] is not None:
            # CPU backend: any flush that still lands here (e.g. a
            # caller ignoring prefer_host) must host-sign rather
            # than sink seconds into a CPU-XLA modexp launch.
            self.signer.host_threshold = cal["sign_crossover"]
        elif self._signer_default_threshold is not None:
            # Backend (re-)engaged: the pin above may still be in place
            # from an earlier all-host verdict — restore the signer's
            # proven default crossover.
            self.signer.host_threshold = self._signer_default_threshold

    def _run_batch(self, items: list):
        from bftkv_tpu.crypto import cert as certmod

        ec_pos = [i for i, (_, k) in enumerate(items) if certmod.is_ec(k)]
        if not ec_pos:
            return self.signer.sign_batch(items)
        from bftkv_tpu.crypto import ecdsa as _ecdsa

        out: list = [None] * len(items)
        ec_set = set(ec_pos)
        rsa_pos = [i for i in range(len(items)) if i not in ec_set]
        if rsa_pos:
            for i, sig in zip(
                rsa_pos, self.signer.sign_batch([items[i] for i in rsa_pos])
            ):
                out[i] = sig
        # Group EC items by key object so each key's messages share one
        # nonce base-mult launch (ecdsa.sign_batch signs for one key).
        groups: dict[int, tuple] = {}
        for i in ec_pos:
            msg, key = items[i]
            groups.setdefault(id(key), (key, []))[1].append((i, msg))
        for key, pairs in groups.values():
            # EC entry point occupancy: one nonce base-mult launch per
            # key group; fill is this group's share of the batch cap.
            metrics.gauge(
                "signdispatch.device_occupancy",
                min(1.0, len(pairs) / self.max_batch),
                labels={"width": "ec"},
            )
            for (i, _), sig in zip(
                pairs, _ecdsa.sign_batch([m for _, m in pairs], key)
            ):
                out[i] = sig
        return out

    def _combine(self, chunks: list):
        return [sig for chunk in chunks for sig in chunk]

    def _empty(self):
        return []

    def sign(self, message: bytes, key) -> bytes:
        return self.submit([(message, key)])[0]


class ModexpDispatcher(_BatchDispatcher):
    """Batched raw modular exponentiation (items: (base, exp, mod) ints).

    The sidecar's third op class: tenants outsource arbitrary modexps
    (threshold-share combination, protocol experiments) and spot-check
    the answers themselves — the service is untrusted by construction,
    so correctness never depends on it (DESIGN.md §17.3).  Odd moduli
    go through the Montgomery native kernel (GIL-releasing host tier);
    everything else falls back to ``pow``.  Batches at or above
    ``device_threshold`` attempt one RNS device launch per width group
    first — on an accelerator that is the shard_map fan-out path the
    sign dispatcher already uses.
    """

    name = "modexpdispatch"

    def __init__(
        self,
        *,
        max_batch: int = 1024,
        max_wait: float = 0.002,
        pipeline: int | None = None,
        calibrate: bool | None = None,
        device_threshold: int | None = None,
    ):
        super().__init__(
            max_batch=max_batch,
            max_wait=max_wait,
            pipeline=pipeline,
            calibrate=calibrate,
        )
        # Same crossover semantics as the signer: below it, one native
        # host modexp per item beats any launch.  ALWAYS_HOST on CPU
        # backends (set by the sidecar from calibration()).
        self.device_threshold = (
            device_threshold
            if device_threshold is not None
            else ALWAYS_HOST
        )

    def apply_calibration(self, cal: dict) -> None:
        self._prefer_host = cal["prefer_host"]
        self.device_threshold = (
            ALWAYS_HOST if cal["prefer_host"] else cal["verify_crossover"]
        )

    def _width_groups(self, items: list, device_idx: list[int]):
        from bftkv_tpu.ops import limb as limb_ops

        # One launch per limb-width group (uniform kernel shapes).
        by_width: dict[int, list[int]] = {}
        for i in device_idx:
            w = limb_ops.nlimbs_for_bits(items[i][2].bit_length())
            by_width.setdefault(w, []).append(i)
        return by_width

    def _note_device_group(self, w: int, idxs: list[int]) -> None:
        metrics.incr("modexp.device", len(idxs))
        # Per-limb-width device occupancy: widths are the handful of
        # deployed modulus sizes, so the label stays bounded (capacity
        # plane joins on `width`).
        metrics.gauge(
            "modexpdispatch.device_occupancy",
            min(1.0, len(idxs) / self.max_batch),
            labels={"width": str(w)},
        )

    def _run_batch(self, items: list) -> list[int]:
        out: list[int | None] = [None] * len(items)
        device_idx: list[int] = []
        if len(items) >= self.device_threshold:
            device_idx = [
                i
                for i, (b, e, m) in enumerate(items)
                if m > 2 and m % 2 == 1 and e >= 0 and 0 <= b
            ]
        if device_idx:
            from bftkv_tpu.ops import rns as rns_ops

            for w, idxs in self._width_groups(items, device_idx).items():
                try:
                    vals = rns_ops.power_mod_rns(
                        [items[i][0] for i in idxs],
                        [items[i][1] for i in idxs],
                        [items[i][2] for i in idxs],
                        n_bits=w * 16,
                    )
                except Exception:
                    vals = None  # incapable/hostile moduli: host below
                if vals is not None:
                    self._note_device_group(w, idxs)
                    for i, v in zip(idxs, vals):
                        out[i] = int(v)
        self._host_fill(items, out)
        return out  # type: ignore[return-value]

    def _launch_batch(self, items: list):
        """Async tier: dispatch EVERY width group's launch before
        blocking on ANY — RSA-2048 and RSA-3072 super-flushes ride the
        device stream back to back instead of round-tripping one group
        at a time.  Declines (``None`` → sync path) below the device
        threshold or when the batch mixes in device-ineligible items,
        so the host tier's behavior is untouched on calibrated-host
        backends."""
        if len(items) < self.device_threshold:
            return None
        if not all(
            m > 2 and m % 2 == 1 and e >= 0 and 0 <= b
            for b, e, m in items
        ):
            return None
        from bftkv_tpu.ops import rns as rns_ops

        launches: list[tuple[int, list[int], object]] = []
        for w, idxs in self._width_groups(
            items, list(range(len(items)))
        ).items():
            try:
                d = rns_ops.power_mod_rns(
                    [items[i][0] for i in idxs],
                    [items[i][1] for i in idxs],
                    [items[i][2] for i in idxs],
                    n_bits=w * 16,
                    defer=True,
                )
            except Exception:
                d = None  # incapable moduli: host fallback on complete
            launches.append((w, idxs, d))

        def complete() -> list[int]:
            out: list[int | None] = [None] * len(items)
            for w, idxs, d in launches:
                vals = None
                if d is not None:
                    try:
                        vals = d.wait()
                    except Exception:
                        vals = None  # device failure: host fallback
                if vals is not None:
                    self._note_device_group(w, idxs)
                    for i, v in zip(idxs, vals):
                        out[i] = int(v)
            self._host_fill(items, out)
            return out  # type: ignore[return-value]

        return complete

    def _host_fill(self, items: list, out: list) -> None:
        """Host tier for every item the device didn't answer."""
        from bftkv_tpu.crypto import rsa as rsamod

        host = 0
        for i, (b, e, m) in enumerate(items):
            if out[i] is not None:
                continue
            host += 1
            if m <= 0:
                raise ValueError("modexp: modulus must be positive")
            if (
                rsamod._MM is not None
                and m % 2 == 1
                and m > 2
                and e >= 0
                and 0 <= b
            ):
                out[i] = rsamod._native_powmod(
                    b % m, e, rsamod._mont_params(m)
                )
            else:
                out[i] = pow(b, e, m)
        if host:
            metrics.incr("modexp.host", host)

    def _combine(self, chunks: list):
        return [v for chunk in chunks for v in chunk]

    def _empty(self):
        return []

    def powmod(self, base: int, exp: int, mod: int) -> int:
        return self.submit([(base, exp, mod)])[0]


_global: VerifyDispatcher | None = None
_global_signer: SignDispatcher | None = None
_global_lock = named_lock("dispatch.install")


def install(dispatcher: VerifyDispatcher | None = None) -> VerifyDispatcher:
    """Install (and start) the process-wide verify dispatcher;
    verification call sites (``CollectiveSignature.verify``) route
    through it."""
    global _global
    with _global_lock:
        if _global is not None:
            _global.stop()
        _global = (dispatcher or VerifyDispatcher()).start()
        return _global


def uninstall() -> None:
    global _global
    with _global_lock:
        if _global is not None:
            _global.stop()
            _global = None


def get() -> VerifyDispatcher | None:
    return _global


def install_signer(dispatcher: SignDispatcher | None = None) -> SignDispatcher:
    """Install (and start) the process-wide sign dispatcher; signing
    call sites (``Signer.issue``) route through it."""
    global _global_signer
    with _global_lock:
        if _global_signer is not None:
            _global_signer.stop()
        _global_signer = (dispatcher or SignDispatcher()).start()
        return _global_signer


def uninstall_signer() -> None:
    global _global_signer
    with _global_lock:
        if _global_signer is not None:
            _global_signer.stop()
            _global_signer = None


def get_signer() -> SignDispatcher | None:
    return _global_signer


def recalibrate() -> dict:
    """Force a fresh calibration and re-apply it to the installed
    dispatchers.

    This is the piece the boot-time pin was missing: ``calibration``
    always supported ``force=True`` but nothing ever called it after
    process start, so an accelerator attached (or un-wedged) mid-run
    could never flip the ``ALWAYS_HOST`` verdict.  Exposed to operators
    through the sidecar's ``/recalibrate`` devtools hook and run
    periodically by the sidecar (``BFTKV_DISPATCH_RECAL_S``)."""
    cal = calibration(force=True)
    with _global_lock:
        for d in (_global, _global_signer):
            if d is not None and d._calibrate:
                d.apply_calibration(cal)
    return cal


def uninstall_all() -> None:
    uninstall()
    uninstall_signer()
