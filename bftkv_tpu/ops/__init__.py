"""bftkv_tpu.ops — batched TPU kernels for the crypto data plane.

The reference's hot loops (SURVEY.md §2 "hot crypto loops") are per-item
``math/big`` modexps and per-signature PGP verifies. Here they are
array programs: big integers are ``(batch, limbs)`` arrays of 16-bit
digits, and every sign/verify/combine is a batched, jit-compiled kernel.

Modules:
- ``limb``   — host-side codec between Python ints and limb arrays
- ``bigint`` — batched limb arithmetic: mul, Montgomery REDC, modexp
- ``ec``     — batched P-256 point arithmetic (Jacobian), scalar mult
- ``tally``  — vmapped quorum/graph boolean reductions
"""

from bftkv_tpu.ops import bigint, limb  # noqa: F401
