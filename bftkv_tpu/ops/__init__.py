"""bftkv_tpu.ops — batched TPU kernels for the crypto data plane.

The reference's hot loops (SURVEY.md §2 "hot crypto loops") are per-item
``math/big`` modexps and per-signature PGP verifies. Here they are
array programs: big integers are ``(batch, limbs)`` arrays of 16-bit
digits, and every sign/verify/combine is a batched, jit-compiled kernel.

Modules:
- ``limb``   — host-side codec between Python ints and limb arrays
- ``bigint`` — batched limb arithmetic: mul, Montgomery REDC, modexp
- ``ec``     — batched P-256 point arithmetic (Jacobian), scalar mult
- ``tally``  — vmapped quorum/graph boolean reductions
"""

import os as _os

from bftkv_tpu.ops import bigint, limb  # noqa: F401
from bftkv_tpu import flags


def enable_compile_cache() -> None:
    """Point jax at a persistent compilation cache (idempotent).

    The RNS kernels compile in tens of seconds per bucket shape on TPU;
    with the cache, daemon restarts and repeat bench runs skip XLA
    entirely.  ``BFTKV_COMPILE_CACHE`` overrides the location; an empty
    value disables.  Called lazily by every device entry point.
    """
    path = flags.raw(
        "BFTKV_COMPILE_CACHE",
        _os.path.expanduser("~/.cache/jax_bftkv"),
    )
    if not path:
        return
    try:
        import jax

        if jax.config.jax_compilation_cache_dir != path:
            jax.config.update("jax_compilation_cache_dir", path)
    except Exception:
        pass  # cache is an optimization, never a failure
