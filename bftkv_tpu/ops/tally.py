"""Batched quorum tallies as vmapped boolean reductions.

The reference evaluates quorum predicates one candidate set at a time
with O(|s1|·|s2|) nested loops inside every multicast callback
(reference: quorum/wotqs/wotqs.go:144-206 ``intersection``). Here a
whole *batch* of candidate sets — e.g. the signer sets of thousands of
concurrent reads during a revoke-on-read sweep, or per-request ack sets
in the benchmark harness — tallies against every quorum clique in one
einsum on device (BASELINE.json: "vote tallying ... vmapped reduction
over replica batches").

Inputs are dense boolean arrays over a node universe of size U:
``membership`` is ``(nqc, U)`` (one row per quorum clique) and
``candidates`` is ``(batch, U)``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = [
    "counts",
    "is_quorum_batch",
    "is_sufficient_batch",
    "is_threshold_batch",
    "reject_batch",
    "equivocation_pairs",
]


@jax.jit
def counts(membership: jnp.ndarray, candidates: jnp.ndarray) -> jnp.ndarray:
    """Intersection sizes, ``(batch, nqc)`` int32."""
    return jnp.einsum(
        "qu,bu->bq",
        membership.astype(jnp.int32),
        candidates.astype(jnp.int32),
    )


@jax.jit
def is_threshold_batch(
    membership: jnp.ndarray, candidates: jnp.ndarray, threshold: jnp.ndarray
) -> jnp.ndarray:
    """(batch,) bool — wotqs.go:157-167 vectorized over candidate sets."""
    c = counts(membership, candidates)
    ok = (threshold[None, :] <= 0) | (c >= threshold[None, :])
    any_qc = membership.shape[0] > 0
    return jnp.all(ok, axis=-1) & any_qc


@jax.jit
def is_quorum_batch(
    membership: jnp.ndarray,
    candidates: jnp.ndarray,
    f: jnp.ndarray,
    min_: jnp.ndarray,
) -> jnp.ndarray:
    """(batch,) bool — wotqs.go:144-155."""
    c = counts(membership, candidates)
    ok = (f[None, :] <= 0) | (c >= min_[None, :])
    return jnp.all(ok, axis=-1) & (membership.shape[0] > 0)


@jax.jit
def is_sufficient_batch(
    membership: jnp.ndarray, candidates: jnp.ndarray, suff: jnp.ndarray
) -> jnp.ndarray:
    """(batch,) bool — wotqs.go:169-176."""
    c = counts(membership, candidates)
    return jnp.any((suff[None, :] > 0) & (c >= suff[None, :]), axis=-1)


@jax.jit
def reject_batch(
    membership: jnp.ndarray, candidates: jnp.ndarray, f: jnp.ndarray
) -> jnp.ndarray:
    """(batch,) bool — wotqs.go:178-185 (vacuously true with no qcs)."""
    c = counts(membership, candidates)
    ok = (f[None, :] > 0) & (c > f[None, :])
    return jnp.all(ok, axis=-1)


@jax.jit
def equivocation_pairs(signer_sets: jnp.ndarray) -> jnp.ndarray:
    """Signers that signed two different values at the same timestamp.

    ``signer_sets`` is ``(nvalues, U)`` bool — one row per distinct value
    observed at one timestamp, marking which nodes signed it. Returns a
    ``(U,)`` bool mask of equivocators: nodes present in more than one
    row (the batched form of the reference's revoke-on-read scan,
    protocol/client.go:304-341).
    """
    per_node = signer_sets.astype(jnp.int32).sum(axis=0)
    return per_node >= 2
