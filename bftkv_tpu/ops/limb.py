"""Host-side codec between Python ints and fixed-limb arrays.

Representation: a non-negative big integer is a little-endian vector of
``LIMB_BITS``-bit digits stored in ``uint32`` lanes, shape ``(..., nlimbs)``.
16-bit digits are chosen so a digit product fits ``uint32`` exactly and a
column of up to 2^16 digit products fits in 32 bits after a lo/hi split —
the TPU VPU has no 64-bit multiply (SURVEY.md §7 "hard parts" #1).
"""

from __future__ import annotations

import numpy as np

LIMB_BITS = 16
LIMB_MASK = (1 << LIMB_BITS) - 1


def nlimbs_for_bits(bits: int) -> int:
    return -(-bits // LIMB_BITS)


def int_to_limbs(x: int, nlimbs: int) -> np.ndarray:
    """Encode a Python int into a little-endian limb vector."""
    if x < 0:
        raise ValueError("int_to_limbs: negative")
    if x >> (LIMB_BITS * nlimbs):
        raise ValueError(f"int_to_limbs: {x.bit_length()} bits > {nlimbs} limbs")
    # One to_bytes + frombuffer instead of a Python limb loop: the RNS
    # verifier sustains >500k items/s, where per-limb Python would
    # dominate end-to-end time.
    raw = x.to_bytes(nlimbs * 2, "little")
    return np.frombuffer(raw, dtype="<u2").astype(np.uint32)


def limbs_to_int(a: np.ndarray) -> int:
    """Decode a little-endian limb vector (one number, 1-D)."""
    a = np.asarray(a, dtype=np.uint64)
    x = 0
    for i in range(a.shape[-1] - 1, -1, -1):
        x = (x << LIMB_BITS) | int(a[..., i])
    return x


def ints_to_limbs(xs: list[int] | tuple[int, ...], nlimbs: int) -> np.ndarray:
    """Encode a batch of ints, shape ``(len(xs), nlimbs)``."""
    return np.stack([int_to_limbs(x, nlimbs) for x in xs])


def limbs_to_ints(a: np.ndarray) -> list[int]:
    """Decode a batch, shape ``(batch, nlimbs)`` → list of ints."""
    return [limbs_to_int(row) for row in np.asarray(a)]
