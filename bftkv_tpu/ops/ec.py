"""Batched P-256 point arithmetic as JAX array programs.

The TPU redesign of the EC capability the reference gets from Go's
``crypto/elliptic`` — the threshold-ECDSA hot loop (partial R =
scalar-base-mult per server, combine = Σ λ_i·R_i point ops,
reference: crypto/threshold/ecdsa/ecdsa.go:31-59).  Field elements are
``(batch, 16)`` uint32 arrays of 16-bit digits in Montgomery form over
the existing big-int engine (:mod:`bftkv_tpu.ops.bigint`); points are
Jacobian ``(X, Y, Z)`` with Z = 0 encoding the identity.

Branch-free by construction (SURVEY.md §7 hard part #3): the unified
group law evaluates both the generic-add and the doubling formulas and
``where``-selects per lane, so the whole scalar multiplication — fixed
4-bit windows, 64 × (4 doublings + constant-time table gather + add) —
compiles to one fused XLA loop with no data-dependent control flow.
``crypto/ec.py`` is the host oracle these kernels are property-tested
against.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from bftkv_tpu.crypto.ec import P256
from bftkv_tpu.ops import bigint, limb
from bftkv_tpu import flags

__all__ = ["P256Domain", "p256"]

L = 16  # 256 bits / 16-bit digits
_WINDOW = 4
_NWIN = 256 // _WINDOW


class P256Domain:
    """Host-side constants for the P-256 field and group order."""

    def __init__(self):
        self.dom = bigint.MontgomeryDomain(P256.p, L)
        c = lambda x: limb.int_to_limbs(x, L)
        self.p = self.dom.n
        self.n_prime = self.dom.n_prime
        self.r2 = self.dom.r2
        self.one_m = self.dom.one_mont  # R mod p  (1 in Montgomery form)
        self.rp = c((1 << (16 * L)) - P256.p)  # R - p, for mod-R add of p
        self.a_m = c((P256.a * self.dom.r_int) % P256.p)
        self.b_m = c((P256.b * self.dom.r_int) % P256.p)
        self.gx_m = c((P256.gx * self.dom.r_int) % P256.p)
        self.gy_m = c((P256.gy * self.dom.r_int) % P256.p)
        self.p_minus_2 = c(P256.p - 2)
        self.zero = np.zeros(L, dtype=np.uint32)

    # -- host codecs ------------------------------------------------------

    def encode_points(self, pts: list) -> tuple[np.ndarray, ...]:
        """Affine host points (or None) → (X_m, Y_m, Z_m) Jacobian batch."""
        xs, ys, zs = [], [], []
        r = self.dom.r_int
        for pt in pts:
            if pt is None:
                xs.append(self.one_m)
                ys.append(self.one_m)
                zs.append(self.zero)
            else:
                xs.append(limb.int_to_limbs((pt[0] * r) % P256.p, L))
                ys.append(limb.int_to_limbs((pt[1] * r) % P256.p, L))
                zs.append(self.one_m)
        return np.stack(xs), np.stack(ys), np.stack(zs)

    def encode_scalars(self, ks: list[int]) -> np.ndarray:
        return limb.ints_to_limbs([k % P256.n for k in ks], L)

    def decode_points(self, xa, ya, inf) -> list:
        """Affine Montgomery batch (+ infinity mask) → host points."""
        rinv = pow(self.dom.r_int, -1, P256.p)
        out = []
        for x, y, z in zip(
            limb.limbs_to_ints(np.asarray(xa)),
            limb.limbs_to_ints(np.asarray(ya)),
            np.asarray(inf),
        ):
            out.append(None if z else ((x * rinv) % P256.p, (y * rinv) % P256.p))
        return out


@functools.lru_cache(maxsize=1)
def p256() -> P256Domain:
    return P256Domain()


# ---------------------------------------------------------------------------
# Field ops (all operands < p, Montgomery form, shape (..., L))
# ---------------------------------------------------------------------------


def _consts(shape_like):
    d = p256()
    bc = lambda a: jnp.broadcast_to(jnp.asarray(a), shape_like.shape)
    return bc(d.p), bc(d.n_prime), bc(d.rp)


def _fmul(a, b):
    p, npr, _ = _consts(a)
    return bigint.mont_mul(a, b, p, npr)


def _fadd(a, b):
    p, _, _ = _consts(a)
    s = bigint.carry_resolve(a + b, L + 1)
    t, hi = s[..., :L], s[..., L]
    return bigint._cond_sub(t, p, hi)


def _fsub(a, b):
    _, _, rp = _consts(a)
    d = bigint.sub_mod_r(a, b)
    # a < b ⇒ wrapped: subtract (R - p) ≡ add p (mod R).
    wrapped = ~bigint.geq(a, b)
    return jnp.where(wrapped[..., None], bigint.sub_mod_r(d, rp), d)


def _fdbl(a):
    return _fadd(a, a)


def _is_zero(a):
    return jnp.all(a == 0, axis=-1)


# ---------------------------------------------------------------------------
# Group law (Jacobian, unified / branch-free)
# ---------------------------------------------------------------------------


def _jac_double(X1, Y1, Z1):
    """dbl-2001-b for a = -3; identity (Z=0) maps to identity."""
    delta = _fmul(Z1, Z1)
    gamma = _fmul(Y1, Y1)
    beta = _fmul(X1, gamma)
    t0 = _fsub(X1, delta)
    t1 = _fadd(X1, delta)
    alpha = _fmul(t0, _fadd(_fdbl(t1), t1))  # 3*(X1-δ)(X1+δ)
    beta4 = _fdbl(_fdbl(beta))
    X3 = _fsub(_fmul(alpha, alpha), _fdbl(beta4))
    t2 = _fadd(Y1, Z1)
    Z3 = _fsub(_fsub(_fmul(t2, t2), gamma), delta)
    g2 = _fmul(gamma, gamma)
    Y3 = _fsub(_fmul(alpha, _fsub(beta4, X3)), _fdbl(_fdbl(_fdbl(g2))))
    return X3, Y3, Z3


def _jac_add(X1, Y1, Z1, X2, Y2, Z2):
    """add-2007-bl shaped unified add with where-selected edge cases."""
    Z1Z1 = _fmul(Z1, Z1)
    Z2Z2 = _fmul(Z2, Z2)
    U1 = _fmul(X1, Z2Z2)
    U2 = _fmul(X2, Z1Z1)
    S1 = _fmul(_fmul(Y1, Z2), Z2Z2)
    S2 = _fmul(_fmul(Y2, Z1), Z1Z1)
    H = _fsub(U2, U1)
    R = _fsub(S2, S1)
    H2 = _fmul(H, H)
    H3 = _fmul(H2, H)
    U1H2 = _fmul(U1, H2)
    X3 = _fsub(_fsub(_fmul(R, R), H3), _fdbl(U1H2))
    Y3 = _fsub(_fmul(R, _fsub(U1H2, X3)), _fmul(S1, H3))
    Z3 = _fmul(_fmul(Z1, Z2), H)

    dX, dY, dZ = _jac_double(X1, Y1, Z1)

    inf1 = _is_zero(Z1)
    inf2 = _is_zero(Z2)
    same_x = _is_zero(H) & ~inf1 & ~inf2
    same_y = _is_zero(R)
    is_dbl = same_x & same_y
    to_inf = same_x & ~same_y  # P + (-P) = O

    def sel(cond, a, b):
        return jnp.where(cond[..., None], a, b)

    X = sel(is_dbl, dX, X3)
    Y = sel(is_dbl, dY, Y3)
    Z = sel(is_dbl, dZ, Z3)
    Z = jnp.where(to_inf[..., None], 0, Z)
    X = sel(inf1, X2, sel(inf2, X1, X))
    Y = sel(inf1, Y2, sel(inf2, Y1, Y))
    Z = sel(inf1, Z2, sel(inf2, Z1, Z))
    return X, Y, Z


# ---------------------------------------------------------------------------
# Scalar multiplication
# ---------------------------------------------------------------------------


@jax.jit
def scalar_mult_jac(X, Y, Z, k):
    """k·P over the batch, Jacobian in/out, fixed 4-bit windows.

    Uniform schedule: every element does the same 4 doublings + one
    constant-time table select + one unified add per window.
    """
    d = p256()
    one_m = jnp.broadcast_to(jnp.asarray(d.one_m), X.shape)
    zero = jnp.zeros_like(X)

    # Table t[j] = j·P, j ∈ [0, 16): t[0] = O, t[j] = t[j-1] + P.
    def tstep(carry, _):
        cX, cY, cZ = carry
        nX, nY, nZ = _jac_add(cX, cY, cZ, X, Y, Z)
        return (nX, nY, nZ), (nX, nY, nZ)

    (_, _, _), (tX, tY, tZ) = lax.scan(
        tstep, (one_m, one_m, zero), None, length=15
    )
    # scan stacks on axis 0 → (..., 16, L) with the identity prepended.
    pre = lambda t0, ts: jnp.concatenate(
        [t0[..., None, :], jnp.moveaxis(ts, 0, -2)], axis=-2
    )
    tX = pre(one_m, tX)
    tY = pre(one_m, tY)
    tZ = pre(zero, tZ)

    def body(j, acc):
        aX, aY, aZ = acc
        widx = _NWIN - 1 - j
        limb_idx = widx // (16 // _WINDOW)
        shift = (widx % (16 // _WINDOW)) * _WINDOW
        wv = (
            jnp.take_along_axis(
                k, jnp.broadcast_to(limb_idx, k.shape[:-1])[..., None], axis=-1
            )[..., 0]
            >> shift
        ) & (2**_WINDOW - 1)
        for _ in range(_WINDOW):
            aX, aY, aZ = _jac_double(aX, aY, aZ)
        gather = lambda t: jnp.take_along_axis(
            t, wv[..., None, None].astype(jnp.int32), axis=-2
        )[..., 0, :]
        return _jac_add(aX, aY, aZ, gather(tX), gather(tY), gather(tZ))

    return lax.fori_loop(0, _NWIN, body, (one_m, one_m, zero))


@jax.jit
def to_affine(X, Y, Z):
    """Jacobian → affine Montgomery coords + infinity mask."""
    d = p256()
    shape = X.shape
    bc = lambda a: jnp.broadcast_to(jnp.asarray(a), shape)
    p, npr = bc(d.p), bc(d.n_prime)
    inf = _is_zero(Z)
    # Z = 1 for identity lanes so the inversion stays well-defined.
    Zs = jnp.where(inf[..., None], bc(d.one_m), Z)
    zinv = bigint.mont_exp(Zs, bc(d.p_minus_2), p, npr, bc(d.one_m))
    zinv2 = _fmul(zinv, zinv)
    xa = _fmul(X, zinv2)
    ya = _fmul(Y, _fmul(zinv2, zinv))
    return xa, ya, inf


@jax.jit
def add_batch(X1, Y1, Z1, X2, Y2, Z2):
    return _jac_add(X1, Y1, Z1, X2, Y2, Z2)


# ---------------------------------------------------------------------------
# Host-facing helpers
# ---------------------------------------------------------------------------


def _use_rns_backend() -> bool:
    """``BFTKV_EC_BACKEND``: "limb" (this module's Montgomery-limb
    kernel), "rns" (the MXU field core, :mod:`bftkv_tpu.ops.ec_rns`),
    or "auto" (default): RNS on a TPU backend — where the limb kernel's
    emulated integer multiplies are the round-3 bottleneck (556
    mults/s @ 64) — and limb on CPU."""

    mode = flags.raw("BFTKV_EC_BACKEND", "auto")
    if mode == "rns":
        return True
    if mode == "auto":
        return jax.default_backend() == "tpu"
    return False


def scalar_mult_hosts(points: list, scalars: list[int]) -> list:
    """Batched k·P on device for host affine points / int scalars.

    Batches pad to power-of-two buckets (floor 8): the jitted kernel
    compiles per shape and XLA compilation is expensive on TPU.
    """
    if not points:
        return []
    from bftkv_tpu import ops

    ops.enable_compile_cache()
    if _use_rns_backend():
        try:
            from bftkv_tpu.ops import ec_rns

            return ec_rns.scalar_mult_hosts(points, scalars)
        except Exception:
            import logging

            logging.getLogger("bftkv_tpu.ops.ec").exception(
                "RNS EC backend failed; falling back to the limb kernel"
            )
    d = p256()
    k = len(points)
    padded = max(8, 1 << (k - 1).bit_length())
    points = list(points) + [None] * (padded - k)
    scalars = list(scalars) + [0] * (padded - k)
    X, Y, Z = d.encode_points(points)
    ke = d.encode_scalars(scalars)
    jX, jY, jZ = scalar_mult_jac(X, Y, Z, ke)
    return d.decode_points(*to_affine(jX, jY, jZ))[:k]


def scalar_base_mult_hosts(scalars: list[int]) -> list:
    return scalar_mult_hosts([(P256.gx, P256.gy)] * len(scalars), scalars)


def linear_combine_hosts(points: list, scalars: list[int]):
    """Σ k_i·P_i: the scalar mults (the 99% of the work) ride one
    batched launch; the final Σ over ≤ threshold-many points is host
    adds — the threshold-ECDSA combine (ecdsa.go:43-52)."""
    acc = None
    for pt in scalar_mult_hosts(points, scalars):
        acc = P256.add(acc, pt)
    return acc
