"""Pallas TPU kernel: the full RSA-2048 e=65537 verify chain in VMEM.

The XLA verify kernel (:mod:`bftkv_tpu.ops.rsa`) is HBM-bound: its
gather-based digit product materializes a ``(batch, 128, 256)``
intermediate (~0.5 GB at batch 4096) for every Montgomery product, and
19 products round-trip that traffic per verify. Here one
``pallas_call`` runs the *entire* chain — to-Montgomery, 17 products
for e = 65537, from-Montgomery, compare — on a VMEM-resident batch
tile, so the only HBM traffic is the operands once each way.

Representation inside the kernel: 16-bit digits in u32 lanes, one
number per sublane row, 128 digit lanes (exactly one lane tile).
Digit products are accumulated with per-limb broadcast and dynamic
lane shifts (``x`` padded into a doubled buffer + ``lax.dynamic_slice``
— no gathers), and carries resolve in log time via a Kogge–Stone
generate/propagate pass, mirroring :func:`bftkv_tpu.ops.bigint.carry_resolve`.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["verify_e65537", "TILE"]

L = 128  # limbs (2048 bits / 16-bit digits)
M16 = 0xFFFF  # python int: jnp scalars would be captured consts in the kernel
TILE = 256  # batch rows per grid step


def _up_dyn(x: jnp.ndarray, s) -> jnp.ndarray:
    """Shift lanes up by (possibly traced) ``s``: out[k] = x[k-s], 0-fill.

    ``pltpu.roll`` supports traced shifts; lanes that wrapped around are
    masked off. Shifts may legitimately reach W (the phi half-product of
    the top limb in mod-R space): the mask then zeroes everything.
    """
    rolled = pltpu.roll(x, s, axis=1)
    lane = lax.broadcasted_iota(jnp.int32, x.shape, 1)
    return jnp.where(lane >= s, rolled, 0)


def _limb(a: jnp.ndarray, i) -> jnp.ndarray:
    """a[:, i] as (T, 1) for a traced ``i`` (no dynamic_slice in Mosaic):
    rotate lane i down to lane 0, then statically slice."""
    w = a.shape[1]
    return pltpu.roll(a, w - i, axis=1)[:, :1]


def _up1(x: jnp.ndarray, s: int) -> jnp.ndarray:
    """Static lane shift up (for carry resolution)."""
    if s == 0:
        return x
    t, w = x.shape
    return jnp.pad(x, ((0, 0), (s, 0)))[:, :w]


def _resolve(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Lane values (< 2^26) → canonical 16-bit digits + carry-out.

    Two local passes bound outstanding carries to one bit, then a
    Kogge–Stone generate/propagate scan finishes in log2(W) steps.
    """
    w = x.shape[1]
    c1 = x >> 16
    e = (x & M16) + _up1(c1, 1)
    cout = c1[:, w - 1 :]
    c2 = e >> 16
    t = (e & M16) + _up1(c2, 1)
    cout = cout + c2[:, w - 1 :]
    r = t & M16
    g = t >> 16  # 0/1
    p = (r == M16).astype(jnp.uint32)
    s = 1
    while s < w:
        g = g | (p & _up1(g, s))
        p = p & _up1(p, s)
        s *= 2
    digits = (r + _up1(g, 1)) & M16
    cout = cout + g[:, w - 1 :]
    return digits, cout


def _mul_cols(a: jnp.ndarray, b2: jnp.ndarray) -> jnp.ndarray:
    """Unresolved digit-product column sums.

    ``a`` is (T, 128); ``b2`` is (T, W) with the second operand in the
    low 128 lanes (W = 256 for a full product, 128 for a mod-R
    product — lanes shifted past W simply drop, which *is* mod R).
    Each step broadcasts one limb of ``a`` and shifts ``b2``'s digit
    products into place; lane sums stay < 2^25.
    """
    acc = jnp.zeros_like(b2)

    def body(i, acc):
        ai = _limb(a, i)
        prod = ai * b2
        plo = prod & M16
        phi = prod >> 16
        return acc + _up_dyn(plo, i) + _up_dyn(phi, i + 1)

    return lax.fori_loop(0, L, body, acc)


def _make_mont_mul(n, nprime, n2):
    """mont_mul closure over the (per-tile) modulus arrays.

    ``n``/``nprime`` are (T, 128); ``n2`` is n padded to (T, 256).
    """

    def mont_mul(a, b2):
        """REDC: a·b·R⁻¹ mod n.  ``a`` (T,128) digits, ``b2`` (T,256)
        with digits in the low half.  Returns (T,128) digits < n."""
        t_cols = _mul_cols(a, b2)  # (T,256) unresolved
        t_lo, _ = _resolve(t_cols[:, :L])
        m_cols = _mul_cols(t_lo, nprime)  # (T,128): product mod R
        m, _ = _resolve(m_cols)
        mn_cols = _mul_cols(m, n2)  # (T,256)
        s_digits, cout = _resolve(t_cols + mn_cols)
        hi = s_digits[:, L:]
        # Conditional subtract: value = cout·R + hi; reduce below n.
        comp = M16 - n
        sub = hi + comp
        one0 = (
            lax.broadcasted_iota(jnp.int32, hi.shape, 1) == 0
        ).astype(jnp.uint32)
        sub_digits, sub_cout = _resolve(sub + one0)
        need = (cout + sub_cout) > 0  # hi >= n  or overflow bit set
        return jnp.where(need, sub_digits, hi)

    return mont_mul


def _pad256(x):
    return jnp.concatenate([x, jnp.zeros_like(x)], axis=1)


def _verify_kernel(sig_ref, em_ref, n_ref, np_ref, r2_ref, out_ref):
    n = n_ref[:]
    nprime = np_ref[:]
    n2 = _pad256(n)
    mont_mul = _make_mont_mul(n, nprime, n2)

    s_m = mont_mul(sig_ref[:], _pad256(r2_ref[:]))  # to Montgomery form
    s_m2 = _pad256(s_m)

    def sq(_, acc):
        return mont_mul(acc, _pad256(acc))

    acc = lax.fori_loop(0, 16, sq, s_m)  # s^(2^16)
    acc = mont_mul(acc, s_m2)  # s^65537 (Montgomery)
    one = (
        lax.broadcasted_iota(jnp.int32, n.shape, 1) == 0
    ).astype(jnp.uint32)
    v = mont_mul(acc, _pad256(one))  # from Montgomery form
    out_ref[:] = v ^ em_ref[:]


@functools.partial(jax.jit, static_argnames=("interpret",))
def verify_e65537(sig, em, n, nprime, r2, *, interpret: bool = False):
    """sig^65537 mod n == em over the batch; Pallas chain kernel.

    Operands are (batch, 128) uint32 16-bit-digit arrays with batch a
    multiple of TILE (the caller pads). Returns (batch,) bool.
    """
    batch = sig.shape[0]
    grid = batch // TILE
    spec = pl.BlockSpec((TILE, L), lambda i: (i, 0), memory_space=pltpu.VMEM)
    diff = pl.pallas_call(
        _verify_kernel,
        out_shape=jax.ShapeDtypeStruct((batch, L), jnp.uint32),
        grid=(grid,),
        in_specs=[spec] * 5,
        out_specs=spec,
        interpret=interpret,
    )(sig, em, n, nprime, r2)
    return jnp.all(diff == 0, axis=-1)
