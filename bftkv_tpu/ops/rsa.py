"""Batched RSA kernels: the signature-verification hot path on TPU.

In the reference every server runs one RSA-2048 verify per signer per
sign/write request (``openpgp.CheckDetachedSignature`` inside
crypto/pgp/crypto_pgp.go:485-500, called from protocol/server.go:207,300 —
O(n²) verifies cluster-wide per write; SURVEY.md §2 "hot crypto loops").
Here a whole batch of signatures — across requests, signers and replicas —
verifies in one jitted program: 17 Montgomery products for e = 65537,
then a vmapped digit comparison against the expected PKCS#1 encoding.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from bftkv_tpu.ops import bigint

__all__ = ["power_batch", "verify_batch_e65537"]

F4 = 65537


@jax.jit
def verify_batch_e65537(
    sig: jnp.ndarray,
    em: jnp.ndarray,
    n: jnp.ndarray,
    n_prime: jnp.ndarray,
    r2: jnp.ndarray,
) -> jnp.ndarray:
    """sig^65537 mod n == em, elementwise over the batch.

    All operands are ``(batch, L)`` digit arrays (per-element public keys —
    a batch may mix keys freely). Returns ``(batch,)`` bool.
    """
    s_mont = bigint.to_mont(sig, r2, n, n_prime)
    v_mont = bigint.mont_pow_static(s_mont, F4, n, n_prime)
    v = bigint.from_mont(v_mont, n, n_prime)
    return jnp.all(v == em, axis=-1)


@jax.jit
def power_batch(
    base: jnp.ndarray,
    e: jnp.ndarray,
    n: jnp.ndarray,
    n_prime: jnp.ndarray,
    r2: jnp.ndarray,
    one_mont: jnp.ndarray,
) -> jnp.ndarray:
    """base^e mod n with per-element full-width exponents.

    The workhorse for threshold-RSA partial signing (each server's modexp
    over its additive key fragments — reference: crypto/threshold/rsa/
    rsa.go:140-178) and for TPA's 2048-bit DH (crypto/auth/auth.go).
    """
    b_mont = bigint.to_mont(base, r2, n, n_prime)
    v_mont = bigint.mont_exp(
        b_mont, e, n, n_prime, jnp.broadcast_to(one_mont, b_mont.shape)
    )
    return bigint.from_mont(v_mont, n, n_prime)
