"""End-to-end request tracing: trace-id/span primitives, a bounded ring
collector, and structured slow-request logging.

The reference has no request correlation at all; ``metrics.py`` gives
whole-process counters.  Neither can answer the questions that steer
the store's performance work — *which replica* stalled a fan-out,
*which phase* of a three-phase write burned the latency budget, *how
full* the device verify batches actually ran (Thetacrypt ships
per-request tracing through its threshold-crypto RPC layer for exactly
this reason; "The Latency Price of Threshold Cryptosystems" shows the
threshold path is dominated by stragglers only per-peer spans find).

Deliberately dependency-free, same stance as :mod:`bftkv_tpu.metrics`:

- a **span** is one timed operation (name, trace id, span id, parent
  span id, start, duration, attrs).  ``span("client.write")`` is a
  context manager; nesting on one thread parents automatically through
  a thread-local stack;
- **propagation** crosses threads and nodes explicitly: ``capture()``
  snapshots the current context, ``attach(ctx)`` re-establishes it on
  another thread, and the transport fan-out carries the context inside
  the encrypted payload via the packet-level trace envelope
  (:func:`bftkv_tpu.packet.wrap_trace`) so server-side spans join the
  client's trace — including across processes over HTTP;
- the **collector** is a bounded ring of finished spans (no
  allocation growth under sustained traffic).  A *root* span (no
  parent) finishing over the slow threshold snapshots its whole trace
  into a separate slow ring and emits one JSON line on the
  ``bftkv_tpu.trace.slow`` logger — grep-able, machine-parseable, with
  top-level ``shard``/``peer`` attribution when the trace carries it;
- every recorded span gets a monotonic **sequence number**, and
  :meth:`Tracer.export` drains the ring incrementally from a caller
  cursor — the fleet collector's feed (``/trace?since=N`` on the
  daemon API): spans stop dying in per-process rings and stitch into
  cross-process trees in ``bftkv_tpu.obs``;
- ``/trace`` on the daemon API serves the recent and slow rings.

Span-name taxonomy and label-cardinality rules: docs/DESIGN.md §7.
``BFTKV_TRACE=off`` disables collection (spans become no-ops and no
trace context rides the wire); ``BFTKV_SLOW_TRACE_SECONDS`` sets the
slow threshold (default 1.0).

**Phases (DESIGN.md §18).**  Every span name resolves to exactly one
member of the CLOSED :data:`PHASES` enum via :data:`SPAN_PHASES` — the
vocabulary the critical-path attribution plane
(:mod:`bftkv_tpu.obs.critpath`) decomposes a write's wall clock into.
The registry is closed the same way ``metrics.LABEL_KEYS`` is: a new
span name must either match a declared entry or pass an explicit
``phase=`` (``tools/bftlint``'s ``span-phase`` rule rejects call sites
that would silently land in the implicit ``other`` bucket, because an
unattributed span is exactly the invisible latency this plane exists
to kill).
"""

from __future__ import annotations

import json
import logging
import os
import random
import threading
import time
from collections import deque
from bftkv_tpu import flags
from bftkv_tpu.devtools.lockwatch import named_lock

__all__ = [
    "PHASES",
    "SPAN_PHASES",
    "Span",
    "SpanContext",
    "Tracer",
    "attach",
    "capture",
    "new_id",
    "phase_of",
    "span",
    "tracer",
]

#: The closed phase enum the write/read wall-clock budget decomposes
#: into (ISSUE 15; DESIGN.md §18).  Adding a phase is a deliberate
#: schema change: the fleet collector's merged histograms and the
#: committed bench ``phase_budget`` trajectories key off these names.
PHASES = (
    "lease",     # presession/timestamp-lease work before the fan-out
    "fanout",    # fan-out machinery: sealing, staging, wave bookkeeping
    "rpc",       # on-the-wire time of peer RPCs (slowest-peer network)
    "server",    # remote admission + verify + storage (stitched spans)
    "dispatch",  # batching-dispatcher queue wait (collector + flush)
    "sidecar",   # shared-crypto-service round trips
    "combine",   # collective-signature combine/mint/verify (host side)
    "backfill",  # async certified-record back-fill tail
    "other",     # root self-time, quorum selection, uncategorized
)

#: Span name → phase.  Exact names win; a key ending in ``.`` is a
#: prefix rule (``rpc.`` covers every ``rpc.<cmd>``).  CLOSED: a span
#: name resolving to none of these lands in ``other`` at runtime, and
#: ``tools/bftlint`` rejects the call site unless it passes an
#: explicit ``phase=`` — new spans must declare their phase.
SPAN_PHASES: dict[str, str] = {
    # client roots + local bookkeeping
    "client.write": "other",
    "client.read": "other",
    "client.read_certified": "other",
    "client.write_many": "other",
    "client.read_many": "other",
    "quorum.select": "other",
    "fault.delay": "other",
    # presession / leases
    "presession.": "lease",
    # fan-out rounds (the span wraps the whole round; its rpc children
    # own the wire time, so the self-time left here is the fan-out
    # machinery itself)
    "phase.time": "fanout",
    "phase.sign": "fanout",
    "phase.write": "fanout",
    "phase.write_sign": "fanout",
    "read.certify": "fanout",
    "read.certified_only": "fanout",
    "read.certified_record": "fanout",
    # per-peer wire time
    "rpc.": "rpc",
    # remote side (stitched into the client's trace)
    "server.": "server",
    "storage.write": "server",
    # collective-signature host crypto
    "phase.ack": "combine",
    "verify.collective": "combine",
    # batching dispatcher + shared crypto service
    "dispatch.wait": "dispatch",
    "verify.flush": "dispatch",
    "sign.flush": "dispatch",
    "modexp.flush": "dispatch",
    "sidecar.call": "sidecar",
    # async tails + repair/anti-entropy planes
    "backfill.": "backfill",
    "sync.repair.backfill": "backfill",
    "sync.": "other",
    # edge gateway (own roots; their quorum-client children re-enter
    # the client.* taxonomy above)
    "gateway.": "other",
    "gateway_client.": "other",
}

#: Longest-match prefix rules, precomputed (longest first so
#: ``sync.repair.backfill`` beats ``sync.``).
_PREFIX_RULES = sorted(
    (k for k in SPAN_PHASES if k.endswith(".")),
    key=len, reverse=True,
)

_phase_memo: dict[str, str] = {}


def phase_of(name: str) -> str:
    """The declared phase of span ``name`` (``other`` for names outside
    the registry — bftlint keeps that set empty in-tree)."""
    p = _phase_memo.get(name)
    if p is None:
        p = SPAN_PHASES.get(name)
        if p is None:
            for prefix in _PREFIX_RULES:
                if name.startswith(prefix):
                    p = SPAN_PHASES[prefix]
                    break
            else:
                p = "other"
        _phase_memo[name] = p
    return p

slow_log = logging.getLogger("bftkv_tpu.trace.slow")

# Trace/span ids are correlation handles, not secrets (they only ever
# ride *inside* the encrypted transport envelope), so a seeded PRNG is
# fine — and ~100x cheaper than os.urandom per span.
_rng = random.Random(int.from_bytes(os.urandom(8), "big"))


def new_id() -> int:
    """A non-zero 63-bit id (0 is reserved as "absent" on the wire)."""
    return _rng.getrandbits(63) | 1


class SpanContext:
    """What propagation carries: (trace_id, span_id) of the parent."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: int, span_id: int):
        self.trace_id = trace_id
        self.span_id = span_id


class Span:
    __slots__ = (
        "trace_id",
        "span_id",
        "parent_id",
        "name",
        "start",
        "duration",
        "attrs",
        "seq",
        "phase",
        "_t0",
    )

    def __init__(self, trace_id, span_id, parent_id, name, attrs,
                 phase=None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.start = time.time()
        self.duration = 0.0
        self.attrs = attrs
        self.seq = 0  # assigned by Tracer.record under its lock
        #: Explicit phase override (dynamic-named spans); None =
        #: resolve from the SPAN_PHASES registry at export time.
        self.phase = phase
        self._t0 = time.perf_counter()

    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    def to_dict(self) -> dict:
        d = {
            "trace": f"{self.trace_id:016x}",
            "span": f"{self.span_id:016x}",
            "name": self.name,
            "start": self.start,
            "duration": self.duration,
            # Resolved lazily (exports are rare next to records) so the
            # record hot path never pays the registry lookup.
            "phase": self.phase or phase_of(self.name),
        }
        if self.parent_id is not None:
            d["parent"] = f"{self.parent_id:016x}"
        if self.attrs:
            d["attrs"] = self.attrs
        return d


#: Sink for spans created while tracing is disabled: attrs writes land
#: here and are discarded, so call sites never branch on enablement.
_NULL_SPAN = Span(0, 0, None, "", {})

_tls = threading.local()


def _stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def capture() -> SpanContext | None:
    """The current context — the innermost open span on this thread, or
    the remotely attached context, or None.  What the transport layer
    snapshots on the caller's thread before fanning out."""
    if not tracer.enabled:
        return None
    st = getattr(_tls, "stack", None)
    if st:
        return st[-1].context()
    return getattr(_tls, "remote", None)


class attach:
    """Re-establish a captured/propagated context on this thread, so
    the next ``span()`` parents to it.  ``attach(None)`` is a no-op
    shield (it masks any context leaked by a previous user of a pooled
    thread).  Restores the previous context on exit."""

    __slots__ = ("ctx", "_prev")

    def __init__(self, ctx: SpanContext | None):
        self.ctx = ctx

    def __enter__(self) -> SpanContext | None:
        self._prev = getattr(_tls, "remote", None)
        _tls.remote = self.ctx
        return self.ctx

    def __exit__(self, *exc) -> bool:
        _tls.remote = self._prev
        return False


class span:
    """Context manager: one timed span, auto-parented.

    Yields the :class:`Span` so callers can add attrs mid-flight
    (``sp.attrs["batch_size"] = n``).  On exit the span is recorded in
    the process tracer; an exception leaving the block lands in
    ``attrs["error"]`` (interned error message when available) and
    still propagates."""

    __slots__ = ("name", "attrs", "phase", "_sp")

    def __init__(self, name: str, attrs: dict | None = None,
                 phase: str | None = None):
        self.name = name
        self.attrs = attrs
        self.phase = phase

    def __enter__(self) -> Span:
        if not tracer.enabled:
            self._sp = None
            return _NULL_SPAN
        st = _stack()
        if st:
            parent = st[-1]
            trace_id, parent_id = parent.trace_id, parent.span_id
        else:
            remote = getattr(_tls, "remote", None)
            if remote is not None:
                trace_id, parent_id = remote.trace_id, remote.span_id
            else:
                trace_id, parent_id = new_id(), None
        sp = Span(trace_id, new_id(), parent_id, self.name,
                  dict(self.attrs) if self.attrs else {},
                  phase=self.phase)
        st.append(sp)
        self._sp = sp
        return sp

    def __exit__(self, etype, exc, tb) -> bool:
        sp = self._sp
        if sp is None:
            return False
        _stack().pop()
        sp.duration = time.perf_counter() - sp._t0
        if etype is not None:
            msg = getattr(exc, "message", None)
            sp.attrs["error"] = msg if isinstance(msg, str) else repr(exc)
        tracer.record(sp)
        return False


class Tracer:
    """Bounded ring collector + slow-trace capture.

    ``max_spans`` bounds total retained spans (the ring IS the storage
    — traces are grouped on demand); ``max_slow`` bounds retained slow
    traces.  All methods are thread-safe; the span hot path is one
    lock-guarded deque append."""

    def __init__(
        self,
        max_spans: int = 8192,
        slow_threshold: float | None = None,
        max_slow: int = 64,
    ):
        self.enabled = flags.raw("BFTKV_TRACE", "on").lower() not in (
            "off", "0", "false",
        )
        if slow_threshold is None:
            slow_threshold = float(
                flags.raw("BFTKV_SLOW_TRACE_SECONDS", "1.0")
            )
        self.slow_threshold = slow_threshold
        self._lock = named_lock("trace.collector")
        self._spans: "deque[Span]" = deque(maxlen=max_spans)
        self._slow: "deque[dict]" = deque(maxlen=max_slow)
        # Monotonic sequence of recorded spans — the export cursor.
        # Survives ring wrap-around: a drained reader can tell exactly
        # how many spans it lost to overwrite (export()'s "dropped").
        self._seq = 0
        # Cumulative ring-overwrite counts (spans/slow entries pushed
        # off the bounded rings before ANY reader drained them) —
        # attribution silently under-samples by exactly these, so they
        # ride every export doc and the trace.ring.dropped /
        # trace.slow.dropped gauges the fleet plane sums (ISSUE 15).
        # Reader-relative on purpose: a full ring whose tail every
        # scrape keeps up with loses nothing — counting raw evictions
        # would turn the gauge permanently nonzero on any long-lived
        # busy daemon and cry wolf forever.
        self._ring_dropped = 0
        self._slow_dropped = 0
        self._drained_to = 0  # highest seq any export() has covered
        self._slow_seq = 0  # monotonic count of slow captures
        self._slow_seen = 0  # _slow_seq at the last slow() read

    # -- recording --------------------------------------------------------

    def record(self, sp: Span) -> None:
        with self._lock:
            self._seq += 1
            sp.seq = self._seq
            if (
                len(self._spans) == self._spans.maxlen
                and self._spans[0].seq > self._drained_to
            ):
                self._ring_dropped += 1
            self._spans.append(sp)
        if sp.parent_id is None and sp.duration >= self.slow_threshold:
            self._capture_slow(sp)

    def _capture_slow(self, root: Span) -> None:
        spans = self.trace(root.trace_id)
        entry = {
            "trace_id": f"{root.trace_id:016x}",
            "root": root.name,
            "duration": root.duration,
            "start": root.start,
            "spans": spans,
        }
        # Attribution without grepping every daemon: the owning shard
        # (stamped on the root span by the routed client paths) and the
        # peer behind the slowest rpc.* span — the straggler that most
        # plausibly burned the budget.
        shard = root.attrs.get("shard")
        if shard is not None:
            entry["shard"] = shard
        rpcs = [
            s for s in spans
            if s["name"].startswith("rpc.") and s.get("attrs", {}).get("peer")
        ]
        if rpcs:
            entry["peer"] = max(rpcs, key=lambda s: s["duration"])[
                "attrs"
            ]["peer"]
        with self._lock:
            if len(self._slow) == self._slow.maxlen:
                # oldest retained entry is capture #(_slow_seq-maxlen+1)
                evicted = self._slow_seq - self._slow.maxlen + 1
                if evicted > self._slow_seen:
                    self._slow_dropped += 1
            self._slow_seq += 1
            self._slow.append(entry)
        # One grep-able JSON line per slow request: the root, its
        # duration, and a per-span breakdown compact enough for logs.
        try:
            slow_log.warning(json.dumps({
                "event": "slow_request",
                "trace_id": entry["trace_id"],
                "root": root.name,
                "duration_s": round(root.duration, 6),
                "threshold_s": self.slow_threshold,
                **({"shard": shard} if shard is not None else {}),
                **(
                    {"peer": entry["peer"]} if "peer" in entry else {}
                ),
                "spans": [
                    {
                        "name": s["name"],
                        "duration_s": round(s["duration"], 6),
                        **({"attrs": s["attrs"]} if s.get("attrs") else {}),
                    }
                    for s in spans
                ],
            }, default=str))
        except Exception:  # a weird attr value must never kill a request
            pass

    def cursor(self) -> int:
        """The current export cursor (sequence of the newest recorded
        span) without serializing anything — pass to :meth:`export` as
        ``since`` to drain only what happens after this point (the
        bench's per-round breakdown uses it to scope one section)."""
        with self._lock:
            return self._seq

    # -- export (the fleet collector's feed) ------------------------------

    def export(self, since: int = 0) -> dict:
        """Incremental drain: every retained span recorded after cursor
        ``since`` (0 = from the beginning), oldest first.

        Returns ``{"cursor", "dropped", "spans"}`` — pass ``cursor``
        back as the next ``since``.  ``dropped`` counts spans that were
        recorded after ``since`` but already overwritten by the bounded
        ring before this drain (a slow scraper loses the oldest spans,
        never blocks the hot path).  A ``since`` ahead of the current
        sequence means the process (or the ring) restarted: the drain
        resyncs from the beginning rather than returning nothing
        forever.  Read-only — concurrent exports with different cursors
        (several collectors) do not disturb each other."""
        with self._lock:
            seq = self._seq
            if since > seq:
                since = 0
            fresh = [s for s in self._spans if s.seq > since]
            # This reader was offered everything up to seq (overwritten
            # spans are reported via "dropped" below): later evictions
            # of these spans are not loss.
            self._drained_to = max(self._drained_to, seq)
            ring_dropped = self._ring_dropped
            slow_dropped = self._slow_dropped
        # Serialize OUTSIDE the lock (same discipline as percentile/
        # snapshot in metrics.py): a near-full-ring drain would
        # otherwise stall every concurrent record() — a span is
        # immutable once recorded, so the reference snapshot suffices.
        out = [s.to_dict() for s in fresh]
        oldest = fresh[0].seq if fresh else seq + 1
        # Gauges refresh on every drain (the record hot path never pays
        # a metrics lock): each collector scrape — and any /trace hit —
        # keeps /metrics at most one drain stale.
        from bftkv_tpu.metrics import registry as _metrics

        _metrics.gauge("trace.ring.dropped", ring_dropped)
        _metrics.gauge("trace.slow.dropped", slow_dropped)
        return {
            "cursor": seq,
            "dropped": max(0, oldest - since - 1),
            "ring_dropped": ring_dropped,
            "slow_dropped": slow_dropped,
            "spans": out,
        }

    # -- querying ---------------------------------------------------------

    def trace(self, trace_id: int) -> list[dict]:
        """Every retained span of one trace, oldest first."""
        with self._lock:
            return [
                s.to_dict() for s in self._spans if s.trace_id == trace_id
            ]

    def traces(self, limit: int = 20) -> list[dict]:
        """The most recent ``limit`` traces assembled from the ring
        (newest last), each ``{"trace_id", "root", "duration", "spans"}``.
        A trace whose root span already fell off the ring reports the
        longest retained span as its root."""
        with self._lock:
            spans = [s.to_dict() for s in self._spans]
        grouped: dict[str, list[dict]] = {}
        order: list[str] = []
        for s in spans:
            tid = s["trace"]
            if tid not in grouped:
                grouped[tid] = []
                order.append(tid)
            grouped[tid].append(s)
        out = []
        for tid in order[-limit:]:
            ss = grouped[tid]
            root = next(
                (s for s in ss if "parent" not in s),
                max(ss, key=lambda s: s["duration"]),
            )
            out.append({
                "trace_id": tid,
                "root": root["name"],
                "duration": root["duration"],
                "spans": ss,
            })
        return out

    def slow(self) -> list[dict]:
        with self._lock:
            self._slow_seen = self._slow_seq
            return list(self._slow)

    def reset(self) -> None:
        with self._lock:
            self._spans.clear()
            self._slow.clear()
            self._seq = 0  # export() resyncs stale cursors from zero
            self._ring_dropped = 0
            self._slow_dropped = 0
            self._drained_to = 0
            self._slow_seq = 0
            self._slow_seen = 0


tracer = Tracer()
