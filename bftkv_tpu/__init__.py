"""bftkv_tpu — a TPU-native Byzantine fault-tolerant distributed key-value
framework with the capabilities of yahoo/bftkv.

Capability parity with the reference (see SURVEY.md for the full map):

- b-masking Byzantine quorum systems selected from a Web-of-Trust graph
  (reference: quorum/wotqs/wotqs.go, node/graph/graph.go)
- quorum-certificate signed writes with equivocation detection,
  revoke-on-read and read-repair (reference: protocol/client.go,
  protocol/server.go)
- threshold password authentication (reference: crypto/auth/auth.go)
- threshold RSA/DSA/ECDSA signing for a decentralized CA
  (reference: crypto/threshold/)

The crypto data plane is array-oriented from the ground up: signatures,
public keys and shares live as fixed-limb uint32 arrays shaped
``(batch, limbs)`` and every verify/sign/combine is a batched JAX/Pallas
kernel (``bftkv_tpu.ops``), dispatched through a batching sidecar
(``bftkv_tpu.parallel``) and sharded over a ``jax.sharding.Mesh``.
"""

__version__ = "0.1.0"

from bftkv_tpu.errors import Error  # noqa: F401
