"""Continuous wall-clock sampling profiler (collapsed-stack output).

Attribution (:mod:`bftkv_tpu.obs.critpath`) says which *phase* owns a
slow write; the profiler says which *code* owns the phase — without
instrumenting anything: a sampler thread walks
``sys._current_frames()`` at a fixed rate (default 67 Hz — prime, so
the sampling comb never phase-locks to millisecond-periodic work) and
folds every thread's stack into the collapsed flamegraph format
(``root;child;leaf count`` lines, the ``flamegraph.pl`` /
speedscope input).

Same arming contract as the failpoint plane (PR 3): **off is free**.
``BFTKV_PROFILE`` unset means no thread, no wrapper, no per-call
anything — the profiler only exists as an idle module.  Armed, the
cost is one GIL-shared stack walk per tick (~tens of µs per thread),
bounded memory (``max_stacks`` unique stacks, deeper/rarer stacks fold
into an overflow bucket), and the perf-smoke bar is armed-vs-disarmed
within 5% (ISSUE 15 acceptance).

Surfaces: each daemon serves ``/profile?seconds=N`` (cmd/bftkv.py) —
an on-demand capture window over the continuous sampler (or a
temporary sampler when disarmed); the flight recorder snapshots
:func:`last` into every bundle.
"""

from __future__ import annotations

import sys
import threading
import time

from bftkv_tpu import flags
from bftkv_tpu.devtools.lockwatch import named_lock
from bftkv_tpu.metrics import registry as metrics

__all__ = ["Profiler", "enabled", "ensure_started", "last", "profile_for"]

#: Leaf-frame function names that mean a thread is parked, not
#: competing for the GIL — the blocking primitives (lock/CV waits,
#: socket waits, queue gets, sleeps).  A leaf NOT in this set is
#: counted as runnable by the GIL-pressure estimate below; the set errs
#: toward "runnable" because a false runnable inflates the estimate
#: (visible, self-correcting) while a false blocked hides pressure.
_BLOCKED_LEAVES = frozenset({
    "wait", "acquire", "sleep", "select", "poll", "epoll", "accept",
    "recv", "recv_into", "read", "readinto", "readline", "get",
    "join", "settimeout", "connect", "getaddrinfo",
})


def enabled() -> bool:
    """The opt-in flag (read at call time, like every switch here)."""
    return flags.enabled("BFTKV_PROFILE")


class Profiler:
    """Bounded folding sampler over ``sys._current_frames()``.

    ``hz`` is the sampling rate; ``max_stacks`` bounds distinct
    collapsed stacks (overflow folds into ``<overflow>``); ``max_depth``
    bounds frames kept per stack (deeper stacks keep the LEAF side —
    the hot code — and fold the root side into ``<deep>``)."""

    def __init__(
        self,
        hz: float | None = None,
        max_stacks: int = 4096,
        max_depth: int = 48,
    ):
        self.hz = hz or float(flags.get_int("BFTKV_PROFILE_HZ") or 67)
        self.max_stacks = max_stacks
        self.max_depth = max_depth
        self._lock = named_lock("obs.profiler")
        self._counts: dict[str, int] = {}
        self._samples = 0
        self._overflow = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- sampling ----------------------------------------------------------

    def _fold(self, frame) -> str:
        parts: list[str] = []
        depth = 0
        while frame is not None and depth < self.max_depth:
            code = frame.f_code
            mod = code.co_filename.rsplit("/", 1)[-1]
            parts.append(f"{mod}:{code.co_name}")
            frame = frame.f_back
            depth += 1
        if frame is not None:
            parts.append("<deep>")
        parts.reverse()  # collapsed format runs root -> leaf
        return ";".join(parts)

    def sample_once(self) -> int:
        """One tick over every live thread except the sampler itself.
        Returns the number of stacks folded (tests drive this
        directly)."""
        me = threading.get_ident()
        n = 0
        # _current_frames() is one C-level snapshot under the GIL; the
        # frames may keep running while we walk them — a torn co_name
        # is impossible (strings are immutable), at worst a stack is
        # one frame stale, which sampling tolerates by definition.
        runnable = 0
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue
            if frame.f_code.co_name not in _BLOCKED_LEAVES:
                runnable += 1
            stack = self._fold(frame)
            with self._lock:
                if stack in self._counts:
                    self._counts[stack] += 1
                elif len(self._counts) < self.max_stacks:
                    self._counts[stack] = 1
                else:
                    self._overflow += 1
                self._samples += 1
            n += 1
        # GIL-pressure estimate: threads whose leaf frame is NOT a
        # blocking primitive are runnable — i.e. queued on the GIL.
        # Rides the sampler tick, so it costs nothing when the profiler
        # is disarmed (no sampler, no gauge) and the capacity plane's
        # gil resource simply reports absent.
        if flags.enabled("BFTKV_GIL_SAMPLER"):
            metrics.gauge(
                "gil.runnable", float(runnable),
                labels={"resource": "gil"},
            )
        return n

    def _run(self) -> None:
        period = 1.0 / self.hz
        while not self._stop.wait(period):
            try:
                self.sample_once()
            except Exception:
                pass  # the sampler must never take the process down

    def start(self) -> "Profiler":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="bftkv-profiler", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2)
            self._thread = None

    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    # -- output ------------------------------------------------------------

    def collapsed(self) -> str:
        """The folded profile: one ``stack count`` line per unique
        stack, descending by count, plus the overflow bucket when the
        stack bound was hit."""
        with self._lock:
            items = sorted(
                self._counts.items(), key=lambda kv: -kv[1]
            )
            overflow = self._overflow
            samples = self._samples
        lines = [f"{stack} {count}" for stack, count in items]
        if overflow:
            lines.append(f"<overflow> {overflow}")
        header = (
            f"# bftkv profile: {samples} samples @ {self.hz:g} Hz "
            f"({len(items)} stacks)"
        )
        return "\n".join([header] + lines) + "\n"

    def reset(self) -> None:
        with self._lock:
            self._counts.clear()
            self._samples = 0
            self._overflow = 0


# ---------------------------------------------------------------------------
# Process singletons: the continuous sampler (armed) + the last
# captured window (the flight recorder's "what was the box doing").
# ---------------------------------------------------------------------------

_global: Profiler | None = None
_global_lock = named_lock("obs.profiler.global")
_last: str = ""


def ensure_started() -> Profiler | None:
    """Start (once) and return the continuous process sampler when
    ``BFTKV_PROFILE`` is armed; None otherwise — the disarmed path is
    one flag read, no thread, no state."""
    global _global
    if not enabled():
        return None
    with _global_lock:
        if _global is None:
            _global = Profiler()
        return _global.start()


def profile_for(seconds: float) -> str:
    """One bounded capture window, collapsed-stack text.

    Armed: snapshots the continuous sampler's delta over the window
    (reset-free — concurrent windows each see the full interval
    superset, which sampling tolerates).  Disarmed: runs a TEMPORARY
    sampler for the window, so ``/profile?seconds=N`` works on demand
    without paying the always-on cost."""
    global _last
    seconds = min(max(seconds, 0.05), 30.0)
    p = ensure_started()
    if p is not None:
        before = dict(p._counts)
        time.sleep(seconds)
        with p._lock:
            after = dict(p._counts)
            samples = p._samples
        delta = {
            k: v - before.get(k, 0)
            for k, v in after.items()
            if v > before.get(k, 0)
        }
        lines = [
            f"{k} {v}"
            for k, v in sorted(delta.items(), key=lambda kv: -kv[1])
        ]
        header = (
            f"# bftkv profile: {seconds:g}s window @ {p.hz:g} Hz "
            f"(continuous sampler, {samples} total samples)"
        )
        out = "\n".join([header] + lines) + "\n"
    else:
        tmp = Profiler()
        tmp.start()
        try:
            time.sleep(seconds)
        finally:
            tmp.stop()
        out = tmp.collapsed()
    _last = out
    return out


def last() -> str:
    """The most recent captured window ('' when none) — what the
    flight recorder folds into a bundle."""
    return _last
