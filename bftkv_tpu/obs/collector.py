"""The fleet collector: scrape → stitch → BFT-native health.

One :class:`FleetCollector` watches every member of a (possibly
sharded) fleet through :mod:`bftkv_tpu.obs.source` objects and keeps
three products current:

**f-budget per shard.**  The paper's tolerance is quantitative: a
clique of ``n`` replicas survives ``f = (n-1)//3`` faults, commits at
``2f+1`` and collects signatures at ``suff = f + (n-f)//2 + 1``
(``quorum/wotqs.py``).  The collector counts clique members that fail
their liveness probe and reports ``remaining = f - down`` — the number
of additional faults the shard can absorb before its write quorum
stalls (liveness) or its masking assumption breaks (safety).  Storage
(complement) members are tracked and alarmed but do not consume the
clique budget: the WRITE complement runs with ``f = 0`` by
construction (wotqs ``W = U − {Ci} + R``).

**SLO histograms per shard.**  Daemons export fixed-bucket latency
histograms (``metrics.BUCKETS``) precisely so this code can sum bucket
vectors across processes and estimate fleet-wide p50/p99 — per-daemon
summary quantiles cannot be merged.  Slow-trace entries (which carry
``shard``/``peer`` attribution) become exemplars: a latency regression
links directly to trace ids you can pull.

**Anomaly feed.**  A bounded ring of events derived from what already
exists: per-source counter deltas (``server.wrong_shard``,
``server.equivocation``, ``server.verify.collective_fail``,
``transport.peer.opens``, ``faults.fired``), membership transitions
(probe up→down / down→up), and — in-process — the failpoint
registry's fault trace, so an injected partition surfaces in the feed
within one scrape interval (the chaos nemesis asserts exactly this).
"""

from __future__ import annotations

import re
import threading
import time
from collections import OrderedDict, deque

from bftkv_tpu import flags
from bftkv_tpu.metrics import BUCKETS, histogram_quantile
from bftkv_tpu.obs.capacity import CapacityPlane
from bftkv_tpu.obs.critpath import ROOT_OPS, PhaseBudget, attribute
from bftkv_tpu.obs.stitch import Stitcher
from bftkv_tpu.devtools.lockwatch import named_lock

__all__ = ["FleetCollector", "parse_flat_key"]

_FLAT_KEY = re.compile(r"^([^{]+)\{(.*)\}$")

#: Counter families whose per-scrape delta becomes one anomaly event.
#: Closed list — the feed must not turn into a second metrics dump.
ANOMALY_COUNTERS = {
    "server.wrong_shard": "wrong_shard",
    "server.equivocation": "equivocation",
    "server.verify.collective_fail": "collective_verify_fail",
    "transport.peer.opens": "peer_circuit_open",
    "faults.fired": "fault_injected",
    # A committed piggybacked write whose async tail never reached a
    # verifying ``suff`` share set: the record stays commit-pending
    # until a reader certifies it — worth an operator's attention.
    "client.tail.starved": "tail_starved",
    # The repair daemon's verdict on residue that can NEVER certify
    # (the SIGN round could not mint a verifying suff): same starved-
    # tail condition, detected from the replica's seat instead of the
    # writer's — only misbehavior or >f loss can produce it.
    "sync.repair.demoted": "tail_starved",
    # Gray failure: a peer whose observed RTT jumped far above its own
    # baseline (transport/latency.py) — alive for the prober, poison
    # for tail latency.  One event per gray episode, not per RPC.
    "transport.peer.slow": "gray_member",
    # Edge gateway tier (bftkv_tpu/gateway).  Sustained shedding means
    # the front door is turning clients away — capacity, not safety.
    "gateway.shed": "gateway_shed",
    # A fill (or write-through) whose collective signature failed
    # verification against the owner quorum: someone fed the gateway a
    # record the quorum never endorsed — the Byzantine-fill signal.
    "gateway.cache.verify_fail": "gateway_poisoned_fill",
    # Epoched routing (DESIGN.md §15): a replica declined a request
    # for a bucket an epoch flip moved away from it — some client is
    # still routing on an older epoch (the route_flap fault's shape;
    # benign in small bursts around a flip, sustained means a member
    # never received the new table).
    "server.epoch_stale": "epoch_skew",
    # Shared crypto sidecar (DESIGN.md §17).  The breaker opened: the
    # service is unreachable/broken and tenants are on local crypto —
    # capacity, not safety (results were never trusted).
    "verify.remote_breaker_open": "sidecar_down",
    # A spot-check or signature self-check caught the sidecar lying
    # (wrong verdict / forged signature): the Byzantine-service
    # signal.  The tenant already fell back to local crypto.
    "crypto.sidecar.dishonest": "sidecar_dishonest",
    # Sustained sidecar shedding: the shared crypto plane is turning
    # batches away — tenants absorb them locally, at host speed.
    "sidecar.shed": "sidecar_shed",
}


def parse_flat_key(key: str) -> tuple[str, dict]:
    """``name{k=v,...}`` → ``(name, {k: v})`` (the snapshot grammar)."""
    m = _FLAT_KEY.match(key)
    if not m:
        return key, {}
    labels: dict = {}
    for part in m.group(2).split(","):
        if "=" in part:
            k, _, v = part.partition("=")
            labels[k] = v
    return m.group(1), labels


class _Member:
    __slots__ = (
        "source",
        "info",
        "info_stale",
        "status",
        "last_ok",
        "last_err",
        "scrape_s",
        "cursor",
        "prev_counters",
        "ring_dropped",
        "slow_dropped",
    )

    def __init__(self, source):
        self.source = source
        self.info: dict = {}
        #: Re-fetch /info on the next scrape (set on recovery and on
        #: the periodic refresh tick) — but keep the LAST KNOWN seat
        #: meanwhile: a down member's f-budget attribution needs it.
        self.info_stale = True
        self.status = "unknown"  # unknown | up | down
        self.last_ok = 0.0
        self.last_err = ""
        self.scrape_s = 0.0
        self.cursor = 0
        self.prev_counters: dict = {}
        #: Cumulative trace-ring overwrite counts the member self-
        #: reports on /trace — the fleet-wide under-sampling signal.
        self.ring_dropped = 0
        self.slow_dropped = 0


class FleetCollector:
    """``sources``: one per fleet member.  ``local_metrics`` /
    ``local_tracer`` / ``fp_registry``: process-wide feeds for
    in-process clusters (every loopback server shares one registry and
    tracer, so these attach once, to the collector, not per source).
    ``scrape_once()`` is synchronous and reentrant-safe;
    ``start(interval)`` runs it on a daemon thread."""

    #: Every member's /info (shard seat, clique membership) is
    #: re-fetched at this scrape cadence — and immediately after a
    #: down→up transition — so membership churn reseats the health
    #: document instead of going stale forever.
    INFO_REFRESH_SCRAPES = 30

    def __init__(
        self,
        sources: list,
        *,
        interval: float = 2.0,
        local_metrics=None,
        local_tracer=None,
        fp_registry=None,
        max_anomalies: int = 1024,
    ):
        self.members = {s.name: _Member(s) for s in sources}
        self.interval = interval
        self.local_metrics = local_metrics
        self.local_tracer = local_tracer
        self.fp_registry = fp_registry
        self.stitcher = Stitcher()
        self._lock = named_lock("obs.collector")
        self._anomalies: deque = deque(maxlen=max_anomalies)
        self._anomaly_seq = 0
        self._local_cursor = 0
        self._local_prev: dict = {}
        self._fp_seq = 0
        self._scrapes = 0
        self._slo: dict = {}  # (shard, op) -> merged bucket vector
        self._slo_sums: dict = {}  # (shard, op) -> merged latency sum
        self._exemplars: dict = {}  # shard -> deque of slow entries
        #: Critical-path attribution (DESIGN.md §18): per-(op, shard)
        #: phase budgets over the stitched traces.
        self.budget = PhaseBudget()
        #: trace id -> scrape index its root was first seen.  A trace
        #: is attributed one full scrape AFTER its root appears, so
        #: server-side fragments scraped from other daemons in between
        #: make it into the tree (bounded; overflow = oldest dropped,
        #: counted so under-sampling is visible, never silent).
        self._attr_pending: "OrderedDict[str, int]" = OrderedDict()
        self._attr_dropped = 0
        #: SLO burn-rate state: previous merged write vectors (per-
        #: scrape deltas are the burn signal — cumulative histograms
        #: stop moving once counts are large) + per-shard consecutive
        #: breach counts with hysteresis.
        self._burn_prev: dict = {}
        self._burn_count: dict = {}
        #: Capacity plane (DESIGN.md §20): per-member USE rows folded
        #: from every scraped metrics snapshot + the local feed, the
        #: bottleneck verdict, and the resource_saturated hysteresis.
        self.capacity = CapacityPlane()
        self._local_ring_dropped = 0
        self._local_slow_dropped = 0
        #: Regions currently dark (every labeled member down) — the
        #: region_down/region_up transition state (DESIGN.md §21).
        self._region_dark: set = set()
        #: Anomaly listeners (the flight recorder's feed), called
        #: OUTSIDE the collector lock — a listener may read health().
        self._listeners: list = []
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        #: Optional zero-arg callable set by an attached topology
        #: autopilot; its status rides the health document so /fleet
        #: reports the last decision next to the budgets it came from.
        self.autopilot_status = None

    # -- anomaly feed ------------------------------------------------------

    def add_anomaly_listener(self, fn) -> None:
        """``fn(anomaly_dict)`` on every fresh anomaly — the flight
        recorder's anomaly→bundle path.  Called OUTSIDE the collector
        lock (a listener may call :meth:`health`/:meth:`anomalies`); a
        raising listener never takes the scrape down."""
        with self._lock:
            self._listeners.append(fn)

    def _emit(self, kind: str, source: str, shard, detail: str, count=1):
        with self._lock:
            self._anomaly_seq += 1
            anomaly = {
                "seq": self._anomaly_seq,
                "ts": time.time(),
                "kind": kind,
                "source": source,
                "shard": shard,
                "detail": detail,
                "count": count,
            }
            self._anomalies.append(anomaly)
            listeners = list(self._listeners)
        for fn in listeners:
            try:
                fn(anomaly)
            except Exception:
                pass  # a broken black box must not break detection

    def anomalies(self, since_seq: int = 0, limit: int = 200) -> list[dict]:
        with self._lock:
            return [a for a in self._anomalies if a["seq"] > since_seq][
                -limit:
            ]

    # -- critical-path attribution (DESIGN.md §18) -------------------------

    ATTR_PENDING_MAX = 2048

    def _note_roots(self, spans: list) -> None:
        """Mark every fresh write/read root trace for attribution ONE
        scrape later — the deferral that lets server-side fragments
        from other daemons join the tree first."""
        with self._lock:
            cur = self._scrapes
            for s in spans:
                if "parent" not in s and s.get("name") in ROOT_OPS:
                    tid = s.get("trace")
                    if tid and tid not in self._attr_pending:
                        self._attr_pending[tid] = cur
                        while len(self._attr_pending) > self.ATTR_PENDING_MAX:
                            self._attr_pending.popitem(last=False)
                            self._attr_dropped += 1

    def _ingest_spans(self, who: str, texp: dict, m=None) -> None:
        """One source's trace export → stitcher + root marking + the
        member's self-reported ring-drop counters."""
        spans = texp.get("spans") or []
        self.stitcher.add(who, spans)
        self._note_roots(spans)
        if m is not None:
            m.ring_dropped = texp.get("ring_dropped", m.ring_dropped)
            m.slow_dropped = texp.get("slow_dropped", m.slow_dropped)

    def _attribute_pass(self) -> None:
        """Attribute every due trace (root seen at least one full
        scrape ago) into the per-(op, shard) phase budgets."""
        with self._lock:
            cur = self._scrapes
            due = [t for t, sc in self._attr_pending.items() if sc < cur]
            for tid in due:
                del self._attr_pending[tid]
        for tid in due:
            spans = self.stitcher.spans(tid)
            if not spans:
                continue  # evicted before its turn: under-sampled, not wrong
            breakdown = attribute(spans)
            if breakdown is not None:
                self.budget.observe(breakdown)

    # -- SLO burn rate (hysteresis; ISSUE 15 satellite) --------------------

    def _slo_burn_check(self, slo_counts: dict) -> None:
        """``slo_burn`` when a shard's PER-SCRAPE write p99 (delta of
        the merged bucket vectors — cumulative histograms stop moving
        once counts are large) exceeds ``BFTKV_SLO_WRITE_P99`` for k
        consecutive traffic-bearing scrapes.  One slow scrape never
        fires it; a clean scrape re-arms."""
        thr = flags.get_float("BFTKV_SLO_WRITE_P99")
        if thr is None:
            return
        if not slo_counts:
            return  # no merged histograms at all: nothing to judge
        k = max(flags.get_int("BFTKV_SLO_BURN_SCRAPES") or 3, 1)
        for (sh, op), vec in slo_counts.items():
            if op != "write":
                continue
            prev = self._burn_prev.get((sh, op))
            delta = [
                c - (prev[i] if prev and i < len(prev) else 0)
                for i, c in enumerate(vec)
            ]
            if sum(delta) <= 0:
                # No fresh writes this scrape (or a restart shrank the
                # merge): neither a breach nor a recovery — the burn
                # count holds, idle time can't page or un-page anyone.
                continue
            p99 = histogram_quantile(0.99, delta)
            if p99 is not None and p99 > thr:
                n = self._burn_count.get(sh, 0) + 1
                self._burn_count[sh] = n
                if n == k:  # fires ONCE per burn episode
                    self._emit(
                        "slo_burn", "collector", sh,
                        f"write p99_le {p99:g}s > slo {thr:g}s "
                        f"for {k} consecutive scrapes",
                    )
            else:
                self._burn_count[sh] = 0  # recovery re-arms
        self._burn_prev = {key: list(v) for key, v in slo_counts.items()}

    # -- scraping ----------------------------------------------------------

    def _shard_of_member(self, name: str):
        m = self.members.get(name)
        if m is None:
            return None
        return m.info.get("shard")

    def _counter_deltas(self, who: str, shard, prev: dict, snap: dict) -> dict:
        """Diff the watched counter families and emit anomalies; returns
        the new baseline (watched keys only)."""
        base: dict = {}
        for key, val in snap.items():
            if not isinstance(val, (int, float)):
                continue
            name, labels = parse_flat_key(key)
            kind = ANOMALY_COUNTERS.get(name)
            if kind is None:
                continue
            base[key] = val
            delta = val - prev.get(key, 0)
            if delta > 0:
                detail = (
                    ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
                    or name
                )
                sh = labels.get("shard")
                if sh is not None and sh.isdigit():
                    sh = int(sh)
                else:
                    sh = shard
                self._emit(kind, who, sh, detail, int(delta))
        return base

    _SLO_OPS = {
        "client.write.latency": "write",
        "client.read.latency": "read",
    }

    def _merge_slo(self, shard_counts: dict, shard_sums: dict,
                   snap: dict) -> None:
        """Fold one daemon's ``client.{write,read}.latency`` bucket and
        sum keys into the per-shard merged histograms.  Shard-labeled
        series win; unlabeled series only count when the fleet is
        unsharded (they would double-count otherwise — the client
        observes both)."""
        sharded = any(
            (m.info.get("shard_count") or 1) > 1
            for m in self.members.values()
        )
        for key, val in snap.items():
            name, labels = parse_flat_key(key)
            if name.endswith(".bucket"):
                op = self._SLO_OPS.get(name[: -len(".bucket")])
                kind = "bucket"
            elif name.endswith(".sum"):
                op = self._SLO_OPS.get(name[: -len(".sum")])
                kind = "sum"
            else:
                continue
            if op is None:
                continue
            sh = labels.get("shard")
            if sh is None:
                if sharded:
                    continue
                sh = 0
            else:
                sh = int(sh) if str(sh).isdigit() else sh
            if kind == "sum":
                shard_sums[(sh, op)] = shard_sums.get((sh, op), 0.0) + val
                continue
            le = labels.get("le")
            try:
                idx = (
                    len(BUCKETS)
                    if le == "+Inf"
                    else BUCKETS.index(float(le))
                )
            except (TypeError, ValueError):
                continue
            h = shard_counts.setdefault(
                (sh, op), [0] * (len(BUCKETS) + 1)
            )
            h[idx] += int(val)

    def _ingest_slow(self, who: str, shard, slow: list) -> None:
        for entry in slow or []:
            sh = entry.get("shard")
            if sh is None:
                sh = shard
            if sh is None:
                sh = 0  # unsharded fleets report as shard 0 throughout
            ex = {
                "trace_id": entry.get("trace_id"),
                "root": entry.get("root"),
                "duration": round(entry.get("duration", 0.0), 4),
                "source": who,
            }
            if "peer" in entry:
                ex["peer"] = entry["peer"]
            with self._lock:  # health() iterates these concurrently
                d = self._exemplars.setdefault(sh, deque(maxlen=16))
                if not any(
                    e["trace_id"] == ex["trace_id"] for e in d
                ):
                    d.append(ex)

    def _fetch(self, m: _Member) -> tuple:
        """The NETWORK phase for one member — no shared-state writes,
        so many of these run concurrently (a hung daemon then costs one
        source-timeout of wall clock per scrape, not one per hung
        member serially).  Returns
        ``(info|None, ok, snap, texp, err, elapsed_s)``."""
        t0 = time.perf_counter()
        info = None
        try:
            # Gateways and sidecars self-report their live stats on
            # /info (cache/shed; queue/occupancy), so their seat
            # document is live data, not topology — refetch every
            # scrape instead of on the 30-scrape cadence.
            if (
                m.info_stale
                or not m.info
                or m.info.get("role") in ("gateway", "sidecar")
            ):
                info = m.source.info() or {}
            if not getattr(m.source, "PROBE_BY_SCRAPE", False):
                # In-process sources: the probe is the signal (their
                # metrics feed is process-wide, always "up").
                if not m.source.probe():
                    return (info, False, None, None, "probe failed",
                            time.perf_counter() - t0)
            # HTTP sources skip the extra probe round trip: the
            # metrics fetch succeeding IS the liveness signal.
            snap = m.source.metrics()
            texp = m.source.trace_export(m.cursor)
            return info, True, snap, texp, "", time.perf_counter() - t0
        except Exception as e:
            return (info, False, None, None,
                    str(e) or type(e).__name__,
                    time.perf_counter() - t0)

    def scrape_once(self) -> dict:
        """One pass over every source + the process-wide feeds.
        Returns the fresh :meth:`health` document."""
        slo_counts: dict = {}
        slo_sums: dict = {}
        renames: list[tuple[str, str]] = []
        with self._lock:
            members = list(self.members.items())
            refresh_tick = self._scrapes % self.INFO_REFRESH_SCRAPES == 0
        if refresh_tick:
            # Topology is not static: /joining, /leaving, and
            # revocations reseat members.  Mark every seat stale on a
            # slow cadence so the health plane converges to membership
            # changes instead of grouping by a boot-time snapshot
            # forever.
            for _n, m in members:
                m.info_stale = True

        # Phase 1 — network, concurrent per member.
        results: dict = {}
        if len(members) > 1:
            def run(name, m):
                results[name] = self._fetch(m)

            threads = [
                threading.Thread(target=run, args=(n, m), daemon=True)
                for n, m in members
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        else:
            results = {n: self._fetch(m) for n, m in members}

        # Phase 2 — state, sequential (stitcher/deltas/anomalies).
        for name, m in members:
            info, ok, snap, texp, err, elapsed = results[name]
            prev_status = m.status
            if info is not None:
                m.info = info
                m.info_stale = False
                reported = info.get("name")
                if reported and reported != name:
                    # An HTTPSource starts out named host:port; the
                    # daemon's /info supplies the real member name —
                    # which must match clique-member lists for the
                    # f-budget attribution to line up.
                    renames.append((name, reported))
                    name = reported
            if ok:
                m.cursor = texp.get("cursor", m.cursor)
                self._ingest_spans(name, texp, m)
                shard = m.info.get("shard")
                self._ingest_slow(name, shard, texp.get("slow"))
                m.prev_counters = self._counter_deltas(
                    name, shard, m.prev_counters, snap
                )
                self._merge_slo(slo_counts, slo_sums, snap)
                self.capacity.observe(name, snap)
                m.status = "up"
                m.last_ok = time.time()
                m.last_err = ""
            else:
                m.status = "down"
                m.last_err = err
            m.scrape_s = elapsed
            if prev_status in ("up", "unknown") and m.status == "down":
                self._emit(
                    "member_down", name, m.info.get("shard"), m.last_err
                )
            elif prev_status == "down" and m.status == "up":
                # A restart may have come back with a different seat.
                m.info_stale = True
                self._emit("member_up", name, m.info.get("shard"), "")
        with self._lock:
            # Key mutation under the lock: /fleet handler threads read
            # the dict concurrently via _members_snapshot().
            for old, new in renames:
                if new not in self.members:
                    self.members[new] = self.members.pop(old)

        # Process-wide feeds (in-process clusters).
        if self.local_metrics is not None:
            snap = self.local_metrics.snapshot()
            self._local_prev = self._counter_deltas(
                "process", None, self._local_prev, snap
            )
            self._merge_slo(slo_counts, slo_sums, snap)
            self.capacity.observe("process", snap)
        if self.local_tracer is not None:
            texp = self.local_tracer.export(self._local_cursor)
            self._local_cursor = texp["cursor"]
            self._ingest_spans("process", texp)
            self._local_ring_dropped = texp.get("ring_dropped", 0)
            self._local_slow_dropped = texp.get("slow_dropped", 0)
            self._ingest_slow("process", None, self.local_tracer.slow())
        if self.fp_registry is not None:
            events = self.fp_registry.trace()
            if events and events[-1].seq < self._fp_seq:
                self._fp_seq = 0  # registry re-armed: sequence restarted
            for ev in events:
                if ev.seq <= self._fp_seq:
                    continue
                self._fp_seq = ev.seq
                target = ev.rule_id.split(":", 1)[1].split(":", 1)[0] \
                    if ":" in ev.rule_id else ""
                self._emit(
                    "fault",
                    target or "?",
                    self._shard_of_member(target),
                    f"{ev.point}:{ev.rule_id}:{ev.kind}",
                )

        # Region plane (DESIGN.md §21): a whole region going dark is a
        # different animal than f scattered members — judge it after
        # every member's probe verdict landed this scrape.
        self._region_check()

        # Diagnosis tier (DESIGN.md §18): attribute every trace whose
        # root has waited one full scrape, then judge the SLO burn rate
        # on this scrape's delta — both AFTER every feed was ingested.
        self._attribute_pass()
        self._slo_burn_check(slo_counts)
        # Capacity hysteresis (DESIGN.md §20): sustained per-resource
        # saturation becomes resource_saturated — same episode contract
        # as slo_burn, emitted through the feed so the flight recorder
        # snapshots capacity state with the bundle.
        for ev in self.capacity.check():
            self._emit(
                "resource_saturated",
                ev["member"],
                self._shard_of_member(ev["member"]),
                f"{ev['resource']} saturation {ev['saturation']:.2f} "
                f"(utilization {ev['utilization']:.2f})",
            )

        with self._lock:
            if slo_counts:
                self._slo = slo_counts
                self._slo_sums = slo_sums
            self._scrapes += 1
        return self.health()

    # -- health document ---------------------------------------------------

    def _members_snapshot(self) -> dict:
        """A consistent copy for reader threads — the scrape thread
        renames keys (host:port → daemon name) under the same lock."""
        with self._lock:
            return dict(self.members)

    def _shards(self, members: dict) -> dict:
        """Group members by shard seat; daemons that reported an /info
        WITHOUT a seat (unsharded storage nodes, degenerate graphs)
        fold into shard 0 so the fleet is fully accounted for.  A
        member that never answered /info at all is excluded here — its
        seat is UNKNOWN, and binning it anywhere would let the shard
        it really belongs to report a full f-budget while one of its
        clique members is dark (health() surfaces these as
        ``fleet.unseated`` instead).  Gateways (``role: gateway``) and
        the crypto sidecar (``role: sidecar``) are deliberately NOT
        shard members: neither holds a quorum seat, so they must never
        enter the clique f-budget math — they report under
        ``health()["gateways"]`` / ``health()["sidecars"]`` instead."""
        shards: dict = {}
        for name, m in members.items():
            if not m.info or m.info.get("role") in ("gateway", "sidecar"):
                continue
            sh = m.info.get("shard")
            sh = 0 if sh is None else sh
            shards.setdefault(sh, []).append((name, m))
        return shards

    def _gateways(self, members: dict, now: float) -> dict:
        """The edge tier's health rows: status + the gateway's own
        cache/shed stats as self-reported on /info."""
        out: dict = {}
        for name, m in members.items():
            if not m.info or m.info.get("role") != "gateway":
                continue
            out[name] = {
                "status": m.status,
                "scrape_s": round(m.scrape_s, 4),
                "last_ok_age_s": round(now - m.last_ok, 1)
                if m.last_ok
                else None,
                **(m.info.get("gateway") or {}),
            }
        return out

    @staticmethod
    def _region_groups(members: dict) -> dict:
        """Region label -> [(name, member)] over every member whose
        /info carried a ``region`` seat field.  Empty on loopback
        fleets (no region map installed) — the whole region plane then
        stays invisible, bit-for-bit pre-region behavior."""
        groups: dict = {}
        for name, m in members.items():
            r = (m.info or {}).get("region")
            if r is None:
                continue
            groups.setdefault(r, []).append((name, m))
        return groups

    def _region_check(self) -> None:
        """Emit ``region_down`` when EVERY member of a region fails its
        probe (``region_up`` on recovery).  The region plane has its own
        two-level budget (DESIGN.md §21): node-level, a region whose
        clique seats stay within each shard's ``f`` leaves writes alive;
        region-level, ``f_regions = (n_regions-1)//3`` whole-region
        losses are masked — which is 0 below four regions, so ANY
        whole-region outage drives the region budget negative and the
        anomaly names that arithmetic even while zero writes fail."""
        with self._lock:
            members = dict(self.members)
        groups = self._region_groups(members)
        if not groups:
            return
        f_regions = (len(groups) - 1) // 3
        for r, mem in sorted(groups.items()):
            dark = all(m.status == "down" for _n, m in mem)
            was = r in self._region_dark
            if dark and not was:
                self._region_dark.add(r)
                used = len(self._region_dark)
                self._emit(
                    "region_down", r, None,
                    f"all {len(mem)} members of region {r} dark; "
                    f"region f-budget {f_regions}-{used}="
                    f"{f_regions - used}",
                )
            elif was and not dark:
                self._region_dark.discard(r)
                self._emit(
                    "region_up", r, None,
                    f"{sum(1 for _n, m in mem if m.status == 'up')}"
                    f"/{len(mem)} members back",
                )

    def _regions(self, members: dict, now: float) -> dict:
        """The WAN plane's health rows (DESIGN.md §21): per-region
        member/up/down rollup plus the REGION-LEVEL f-budget —
        ``f_regions = (n_regions-1)//3`` whole-region outages masked,
        so three regions budget 0 and one dark region reads -1.
        Empty dict when no member carries a region seat."""
        groups = self._region_groups(members)
        if not groups:
            return {}
        f_regions = (len(groups) - 1) // 3
        dark = sorted(
            r for r, mem in groups.items()
            if all(m.status == "down" for _n, m in mem)
        )
        rows: dict = {}
        for r, mem in sorted(groups.items()):
            down = sorted(n for n, m in mem if m.status == "down")
            shards = sorted(
                {
                    m.info.get("shard")
                    for _n, m in mem
                    if m.info.get("shard") is not None
                },
                key=str,
            )
            rows[r] = {
                "members": len(mem),
                "up": len(mem) - len(down),
                "down": down,
                "dark": r in dark,
                "shards": shards,
                "gateways": sorted(
                    n for n, m in mem
                    if m.info.get("role") == "gateway"
                ),
            }
        return {
            "n": len(groups),
            "f_budget": {
                "f": f_regions,
                "used": len(dark),
                "remaining": f_regions - len(dark),
                "dark": dark,
            },
            "rows": rows,
        }

    def _sidecars(self, members: dict, now: float) -> dict:
        """The shared crypto service's health rows: status + the
        sidecar's own queue/occupancy/shed stats as self-reported on
        /info — a ``role=sidecar`` member is an optimizer, never a
        quorum seat, so it lives here instead of any f-budget."""
        out: dict = {}
        for name, m in members.items():
            if not m.info or m.info.get("role") != "sidecar":
                continue
            out[name] = {
                "status": m.status,
                "scrape_s": round(m.scrape_s, 4),
                "last_ok_age_s": round(now - m.last_ok, 1)
                if m.last_ok
                else None,
                **(m.info.get("sidecar") or {}),
            }
        return out

    def health(self) -> dict:
        shards_doc: dict = {}
        now = time.time()
        all_members = self._members_snapshot()
        budget_doc = self.budget.doc()
        with self._lock:
            slo = {k: list(v) for k, v in self._slo.items()}
            slo_sums = dict(self._slo_sums)
            exemplars = {k: list(v) for k, v in self._exemplars.items()}
            attr_pending = len(self._attr_pending)
            attr_dropped = self._attr_dropped
        for sh, members in sorted(
            self._shards(all_members).items(), key=lambda kv: str(kv[0])
        ):
            clique = next(
                (
                    m.info["clique"]
                    for _n, m in members
                    if m.info.get("clique")
                ),
                None,
            )
            cnames = set(clique["members"]) if clique else {
                n for n, _m in members
            }
            down = sorted(
                n for n, m in members if m.status == "down"
            )
            clique_down = [n for n in down if n in cnames]
            f = clique["f"] if clique else max((len(cnames) - 1) // 3, 0)
            doc = {
                "n": clique["n"] if clique else len(cnames),
                "f": f,
                "threshold": clique["threshold"] if clique else 2 * f + 1,
                "suff": clique["suff"] if clique else None,
                "members": [
                    {
                        "name": n,
                        "role": m.info.get("role")
                        or ("clique" if n in cnames else "storage"),
                        "status": m.status,
                        "scrape_s": round(m.scrape_s, 4),
                        "last_ok_age_s": round(now - m.last_ok, 1)
                        if m.last_ok
                        else None,
                        # Route-table epoch the member self-reports; a
                        # fleet mid-flip shows a mixed column here.
                        "epoch": m.info.get("epoch"),
                    }
                    for n, m in sorted(members)
                ],
                "f_budget": {
                    "f": f,
                    "used": len(clique_down),
                    "remaining": f - len(clique_down),
                    "down": clique_down,
                    "storage_down": [n for n in down if n not in cnames],
                },
            }
            slo_doc = {}
            for op in ("write", "read"):
                h = slo.get((sh, op))
                if h and sum(h):
                    slo_doc[op] = {
                        "count": sum(h),
                        "sum_s": round(slo_sums.get((sh, op), 0.0), 6),
                        "p50_le_s": histogram_quantile(0.5, h),
                        "p99_le_s": histogram_quantile(0.99, h),
                        "buckets": h,
                    }
            doc["slo"] = slo_doc
            doc["exemplars"] = exemplars.get(sh, [])
            # The phase budget of this shard's writes/reads: where the
            # wall clock went, exclusive per phase, p99 exemplar first.
            doc["budget"] = {
                op: budget_doc[op][sh]
                for op in ("write", "read")
                if sh in budget_doc.get(op, {})
            }
            shards_doc[str(sh)] = doc

        up = [n for n, m in all_members.items() if m.status == "up"]
        # Fleet-wide epoch spread: every member's self-reported
        # route-table epoch (None = never answered /info or pre-epoch
        # daemon).  min != max while a flip is propagating.
        epochs = sorted(
            {
                m.info.get("epoch")
                for m in all_members.values()
                if isinstance(m.info.get("epoch"), int)
            }
        )
        autopilot = None
        status_fn = self.autopilot_status
        if callable(status_fn):
            try:
                autopilot = status_fn()
            except Exception:
                autopilot = None
        with self._lock:
            anomalies = list(self._anomalies)[-200:]
            scrapes = self._scrapes
        return {
            "ts": now,
            "scrapes": scrapes,
            "interval_s": self.interval,
            "fleet": {
                "daemons": len(all_members),
                "up": len(up),
                "down": sorted(set(all_members) - set(up)),
                # Seat unknown (never answered /info): every f-budget
                # above is indeterminate while one of these is dark —
                # the CLI exit code treats that as unhealthy.
                "unseated": sorted(
                    n for n, m in all_members.items() if not m.info
                ),
                "route_epochs": {
                    "min": epochs[0] if epochs else None,
                    "max": epochs[-1] if epochs else None,
                    "skewed": len(epochs) > 1,
                },
                # Fleet-wide trace-ring overwrite totals: nonzero means
                # attribution/stitching under-sample — turn down traffic
                # per scrape or raise the ring (ISSUE 15 satellite).
                "trace_drops": {
                    "ring": self._local_ring_dropped + sum(
                        m.ring_dropped for m in all_members.values()
                    ),
                    "slow": self._local_slow_dropped + sum(
                        m.slow_dropped for m in all_members.values()
                    ),
                    "attr_pending": attr_pending,
                    "attr_dropped": attr_dropped,
                },
            },
            "autopilot": autopilot,
            # The full attribution document, op → shard → budget (the
            # per-shard copies above are views into this): where each
            # op's wall clock went, exclusive per phase (DESIGN.md §18).
            "write_budget_by_phase": budget_doc.get("write", {}),
            "read_budget_by_phase": budget_doc.get("read", {}),
            # Capacity plane (DESIGN.md §20): USE rows per member +
            # fleet fold + the bottleneck verdict, joined against the
            # write budget's phase shares above.
            "capacity": {
                **self.capacity.doc(),
                "verdict": self.capacity.verdict(
                    PhaseBudget.fleet_shares(budget_doc)
                ),
            },
            "shards": shards_doc,
            "regions": self._regions(all_members, now),
            "gateways": self._gateways(all_members, now),
            "sidecars": self._sidecars(all_members, now),
            "traces": {
                **self.stitcher.summary(),
                "recent": self.stitcher.traces(limit=10),
            },
            "anomalies": anomalies,
            "bucket_bounds": list(BUCKETS),
        }

    def prometheus(self) -> str:
        """The fleet document as Prometheus text — gauges with a
        ``shard`` label, counters for the anomaly feed.  Samples group
        by family with exactly ONE ``# TYPE`` line each (a repeated
        TYPE line for a name is a parse error in a real Prometheus
        server, which would reject the whole exposition on any
        multi-shard fleet)."""
        doc = self.health()
        order: list[str] = []  # family base names, first-seen order
        types: dict[str, str] = {}
        samples: dict[str, list[str]] = {}

        def add(family: str, typ: str, suffix: str, sample: str):
            base = "bftkv_fleet_" + family
            if base not in types:
                types[base] = typ
                order.append(base)
                samples[base] = []
            samples[base].append(base + suffix + " " + sample)

        add("daemons", "gauge", "", str(doc["fleet"]["daemons"]))
        add("daemons_up", "gauge", "", str(doc["fleet"]["up"]))
        add("scrapes", "gauge", "", str(doc["scrapes"]))
        repochs = doc["fleet"].get("route_epochs") or {}
        if isinstance(repochs.get("max"), int):
            add("route_epoch", "gauge", "", str(repochs["max"]))
            add("route_epoch_skewed", "gauge", "",
                "1" if repochs.get("skewed") else "0")
        gws = doc.get("gateways") or {}
        if gws:
            add("gateways", "gauge", "", str(len(gws)))
            add("gateways_up", "gauge", "",
                str(sum(1 for g in gws.values() if g["status"] == "up")))
            for name, g in sorted(gws.items()):
                lab = f'{{gateway="{name}"}}'
                for field in ("hits", "misses", "shed", "verify_fail"):
                    if isinstance(g.get(field), (int, float)):
                        add(f"gateway_{field}", "gauge", lab,
                            str(g[field]))
        regs = doc.get("regions") or {}
        if regs:
            add("regions", "gauge", "", str(regs["n"]))
            add("region_budget_remaining", "gauge", "",
                str(regs["f_budget"]["remaining"]))
            for rname, row in sorted(regs["rows"].items()):
                lab = f'{{region="{rname}"}}'
                add("region_members", "gauge", lab, str(row["members"]))
                add("region_members_up", "gauge", lab, str(row["up"]))
        scs = doc.get("sidecars") or {}
        if scs:
            add("sidecars_up", "gauge", "",
                str(sum(1 for s in scs.values() if s["status"] == "up")))
            for name, s in sorted(scs.items()):
                lab = f'{{sidecar="{name}"}}'
                q = s.get("queue") or {}
                for field in ("inflight", "waiting", "shed"):
                    if isinstance(q.get(field), (int, float)):
                        add(f"sidecar_{field}", "gauge", lab,
                            str(q[field]))
        # Capacity plane: ONE gauge family per USE axis, labeled
        # (member, resource) — resource names are the closed
        # capacity.RESOURCES enum, so cardinality is members x |enum|.
        cap = doc.get("capacity") or {}
        for member, rows in sorted((cap.get("members") or {}).items()):
            for res, row in sorted(rows.items()):
                lab = f'{{member="{member}",resource="{res}"}}'
                for field in ("utilization", "saturation", "errors"):
                    add(f"resource_{field}", "gauge", lab,
                        str(row.get(field, 0)))
        top = (cap.get("verdict") or {}).get("top")
        if top:
            add("resource_verdict_score", "gauge",
                f'{{member="{top["member"]}",resource="{top["resource"]}"}}',
                str(top["score"]))
        add("traces_stitched", "gauge", "",
            str(doc["traces"]["stitched"]))
        drops = doc["fleet"].get("trace_drops") or {}
        add("trace_ring_dropped", "gauge", "", str(drops.get("ring", 0)))
        add("trace_slow_dropped", "gauge", "", str(drops.get("slow", 0)))
        add("anomalies_total", "counter", "", str(self._anomaly_seq))
        for sh, sd in sorted(doc["shards"].items()):
            lab = f'{{shard="{sh}"}}'
            for field in ("n", "f", "threshold"):
                if sd[field] is not None:
                    add(f"shard_{field}", "gauge", lab, str(sd[field]))
            fb = sd["f_budget"]
            add("f_budget_remaining", "gauge", lab, str(fb["remaining"]))
            add("members_down", "gauge", lab,
                str(len(fb["down"]) + len(fb["storage_down"])))
            for op, s in sd["slo"].items():
                fam = f"{op}_latency"
                acc = 0
                for i, c in enumerate(s["buckets"]):
                    acc += c
                    le = BUCKETS[i] if i < len(BUCKETS) else "+Inf"
                    add(fam, "histogram",
                        f'_bucket{{shard="{sh}",le="{le}"}}', str(acc))
                add(fam, "histogram", "_sum" + lab, str(s["sum_s"]))
                add(fam, "histogram", "_count" + lab, str(s["count"]))
        # Critical-path attribution: ONE histogram family labeled by
        # (shard, op, phase) — ``bftkv_fleet_phase_seconds`` is the
        # per-phase exclusive-time distribution (DESIGN.md §18).
        # Emitted from the top-level attribution doc, so a budget
        # survives even when no member's /info seated its shard.
        for op in ("write", "read"):
            for sh, b in sorted(
                doc.get(f"{op}_budget_by_phase", {}).items(),
                key=lambda kv: str(kv[0]),
            ):
                for phase, pd in sorted(b.get("phases", {}).items()):
                    plab = f'shard="{sh}",op="{op}",phase="{phase}"'
                    acc = 0
                    for i, c in enumerate(pd["buckets"]):
                        acc += c
                        le = BUCKETS[i] if i < len(BUCKETS) else "+Inf"
                        add("phase_seconds", "histogram",
                            f'_bucket{{{plab},le="{le}"}}', str(acc))
                    add("phase_seconds", "histogram",
                        "_sum{" + plab + "}", str(pd["sum_s"]))
                    add("phase_seconds", "histogram",
                        "_count{" + plab + "}", str(b["count"]))

        lines: list[str] = []
        for base in order:
            lines.append(f"# TYPE {base} {types[base]}")
            lines.extend(samples[base])
        return "\n".join(lines) + "\n"

    # -- background loop ---------------------------------------------------

    def start(self, interval: float | None = None) -> "FleetCollector":
        if interval is not None:
            self.interval = interval
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                try:
                    self.scrape_once()
                except Exception:  # scraping must never die
                    pass
                self._stop.wait(self.interval)

        self._thread = threading.Thread(
            target=loop, name="fleet-collector", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5)
            self._thread = None
