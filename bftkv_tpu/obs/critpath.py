"""Critical-path attribution: who owns a write's wall clock.

PR 8 collapsed the write to one round and PR 13 moved the crypto into
a shared sidecar — and with every such move, "the write is slow"
became harder to localize: the latency can hide in the presession
lease, the WRITE_SIGN fan-out machinery, the slowest peer's wire time,
the server's admission + verify, the batching dispatcher's queue, the
sidecar round trip, or the collective combine ("The Latency Price of
Threshold Cryptosystems" frames exactly this: threshold systems pay
their latency price in stragglers and pipelining gaps, not means).
``round_p50_s`` (PR 8's bench breakdown) reports per-phase medians of
*independent* spans; it cannot say what fraction of one p99 write each
phase owned.

This module decomposes a stitched trace tree (the PR 7
:class:`~bftkv_tpu.obs.stitch.Stitcher` output, or any span-dict list)
into an **exclusive-time budget** over the closed
:data:`bftkv_tpu.trace.PHASES` enum:

- each span's *self time* is its duration minus the interval UNION of
  its children (overlapping children — parallel RPCs — are counted
  once, never summed past wall clock);
- time covered by several overlapping siblings is attributed to the
  LAST-ENDING one — the straggler owns the overlap, because the
  straggler is what the caller actually waited on;
- children are clipped to their parent's interval, so an async tail
  that outlives the root (back-fill after early commit) never inflates
  the budget past the root's duration — by construction the per-phase
  exclusive times sum to exactly the root span's duration.

:class:`PhaseBudget` aggregates budgets per (op, shard) into
fixed-bucket histograms on the fleet-wide ``metrics.BUCKETS`` ladder —
mergeable across collectors by bucket-vector summation, same design as
the SLO histograms (DESIGN.md §11.2) — and retains the slowest traces
as exemplars so ``/fleet`` reports the phase breakdown of the **p99
exemplar**, not the mean.  Design: docs/DESIGN.md §18.
"""

from __future__ import annotations

import heapq

from bftkv_tpu.metrics import BUCKETS, _bucket_index, histogram_quantile
from bftkv_tpu.trace import PHASES, phase_of
from bftkv_tpu.devtools.lockwatch import named_lock

__all__ = ["PhaseBudget", "ROOT_OPS", "attribute"]

#: Root span names the attribution plane decomposes, and the op each
#: reports under.  Closed on purpose: write_many/read_many roots have
#: batch semantics (N items amortize one round) that would pollute the
#: single-op budget.
ROOT_OPS = {
    "client.write": "write",
    "client.read": "read",
    "client.read_certified": "read",
}

# ---------------------------------------------------------------------------
# Interval algebra.  An interval set is a sorted, disjoint tuple of
# (start, end) pairs; all helpers preserve that invariant.
# ---------------------------------------------------------------------------


def _clip(iv: tuple, lo: float, hi: float) -> tuple:
    out = []
    for s, e in iv:
        s2, e2 = max(s, lo), min(e, hi)
        if e2 > s2:
            out.append((s2, e2))
    return tuple(out)


def _subtract(iv: tuple, minus: tuple) -> tuple:
    """``iv − minus`` (both interval sets)."""
    out = []
    for s, e in iv:
        segs = [(s, e)]
        for ms, me in minus:
            nxt = []
            for ss, se in segs:
                if me <= ss or ms >= se:
                    nxt.append((ss, se))
                    continue
                if ms > ss:
                    nxt.append((ss, ms))
                if me < se:
                    nxt.append((me, se))
            segs = nxt
            if not segs:
                break
        out.extend(segs)
    return tuple(sorted(out))


def _measure(iv: tuple) -> float:
    return sum(e - s for s, e in iv)


# ---------------------------------------------------------------------------
# One-trace attribution.
# ---------------------------------------------------------------------------


def _span_interval(s: dict) -> tuple[float, float]:
    start = float(s.get("start", 0.0))
    return start, start + max(float(s.get("duration", 0.0)), 0.0)


def attribute(spans: list[dict]) -> dict | None:
    """Decompose one trace's root span into the per-phase exclusive-
    time budget.  ``spans`` is any list of span dicts (one trace) in
    ``Span.to_dict`` / stitcher form.  Returns ``None`` when the trace
    has no :data:`ROOT_OPS` root; otherwise::

        {"op", "shard", "trace_id", "root_s",
         "phases": {phase: seconds},   # sums to root_s exactly
         "attributed_s"}               # root_s minus clock-skew loss

    Cross-process clock skew can push a stitched child outside its
    parent's wall-clock window; such children are clipped (possibly to
    nothing) and their time stays with the parent's phase — the budget
    degrades toward coarser attribution, never toward double counting.
    """
    root = None
    for s in spans:
        if "parent" not in s and s.get("name") in ROOT_OPS:
            root = s
            break
    if root is None:
        return None
    children: dict[str, list[dict]] = {}
    for s in spans:
        p = s.get("parent")
        if p is not None and s.get("span") != root.get("span"):
            children.setdefault(p, []).append(s)

    budget = dict.fromkeys(PHASES, 0.0)
    r0, r1 = _span_interval(root)

    def walk(span: dict, owned: tuple, depth: int = 0) -> None:
        if not owned or depth > 64:  # defensive: hostile/cyclic input
            return
        kids = children.get(span.get("span"), ())
        claimed: tuple = ()
        # Straggler-first: the last-ending sibling claims its full
        # interval; earlier-ending overlappers claim what is left.  The
        # overlap therefore lands on the span the caller waited on.
        for kid in sorted(
            kids, key=lambda k: _span_interval(k)[1], reverse=True
        ):
            ks, ke = _span_interval(kid)
            own = _subtract(_clip(owned, ks, ke), claimed)
            if own:
                walk(kid, own, depth + 1)
                claimed = tuple(sorted(claimed + own))
        phase = span.get("phase") or phase_of(span.get("name", ""))
        if phase not in budget:
            phase = "other"
        budget[phase] += _measure(_subtract(owned, claimed))

    walk(root, ((r0, r1),) if r1 > r0 else ())
    attributed = sum(budget.values())
    shard = (root.get("attrs") or {}).get("shard")
    return {
        "op": ROOT_OPS[root["name"]],
        "shard": shard if isinstance(shard, int) else None,
        "trace_id": root.get("trace"),
        "root_s": max(r1 - r0, 0.0),
        "phases": budget,
        "attributed_s": attributed,
    }


# ---------------------------------------------------------------------------
# Aggregation: mergeable per-phase histograms + p99 exemplars.
# ---------------------------------------------------------------------------


class PhaseBudget:
    """Per-(op, shard) phase budgets as fixed-bucket histograms.

    Bucket vectors ride the fleet-wide ``metrics.BUCKETS`` ladder, so
    two PhaseBudgets (two collectors, two bench runs) merge by vector
    summation — the same property the SLO plane leans on.  The slowest
    ``max_exemplars`` traces per (op, shard) are retained with their
    full breakdown; :meth:`doc` reports the one sitting at the merged
    p99 (smallest retained root ≥ the histogram's p99 estimate, else
    the slowest) — stragglers are the point, means hide them."""

    def __init__(self, max_exemplars: int = 8):
        self.max_exemplars = max_exemplars
        self._lock = named_lock("obs.critpath")
        #: (op, shard, phase) -> [bucket counts] (len(BUCKETS)+1)
        self._phase_hist: dict[tuple, list[int]] = {}
        #: (op, shard, phase) -> cumulative seconds
        self._phase_sum: dict[tuple, float] = {}
        #: (op, shard) -> [bucket counts] of root durations
        self._root_hist: dict[tuple, list[int]] = {}
        self._root_count: dict[tuple, int] = {}
        self._root_sum: dict[tuple, float] = {}
        #: (op, shard) -> min-heap of (root_s, seq, breakdown)
        self._exemplars: dict[tuple, list] = {}
        self._seq = 0

    # Same ladder, same bucketing as the SLO histograms — the merge
    # property depends on it, so share the helper instead of forking.
    _bucket = staticmethod(_bucket_index)

    def observe(self, breakdown: dict) -> None:
        """Fold one :func:`attribute` result in."""
        op = breakdown["op"]
        shard = breakdown["shard"] or 0
        key = (op, shard)
        with self._lock:
            for phase, secs in breakdown["phases"].items():
                pk = (op, shard, phase)
                h = self._phase_hist.get(pk)
                if h is None:
                    h = self._phase_hist[pk] = [0] * (len(BUCKETS) + 1)
                h[self._bucket(secs)] += 1
                self._phase_sum[pk] = self._phase_sum.get(pk, 0.0) + secs
            rh = self._root_hist.get(key)
            if rh is None:
                rh = self._root_hist[key] = [0] * (len(BUCKETS) + 1)
            rh[self._bucket(breakdown["root_s"])] += 1
            self._root_count[key] = self._root_count.get(key, 0) + 1
            self._root_sum[key] = (
                self._root_sum.get(key, 0.0) + breakdown["root_s"]
            )
            heap = self._exemplars.setdefault(key, [])
            self._seq += 1
            item = (breakdown["root_s"], self._seq, breakdown)
            if len(heap) < self.max_exemplars:
                heapq.heappush(heap, item)
            elif item[0] > heap[0][0]:
                heapq.heapreplace(heap, item)

    def merge(self, other: "PhaseBudget") -> None:
        """Fold ``other`` in (bucket-vector summation; exemplars
        re-ranked by root duration).  The cross-member merge property
        the fixed ladder buys."""
        with other._lock:
            ph = {k: list(v) for k, v in other._phase_hist.items()}
            ps = dict(other._phase_sum)
            rh = {k: list(v) for k, v in other._root_hist.items()}
            rc = dict(other._root_count)
            rs = dict(other._root_sum)
            ex = {k: list(v) for k, v in other._exemplars.items()}
        with self._lock:
            for k, v in ph.items():
                mine = self._phase_hist.setdefault(
                    k, [0] * (len(BUCKETS) + 1)
                )
                for i, c in enumerate(v):
                    mine[i] += c
            for k, v in ps.items():
                self._phase_sum[k] = self._phase_sum.get(k, 0.0) + v
            for k, v in rh.items():
                mine = self._root_hist.setdefault(
                    k, [0] * (len(BUCKETS) + 1)
                )
                for i, c in enumerate(v):
                    mine[i] += c
            for k, v in rc.items():
                self._root_count[k] = self._root_count.get(k, 0) + v
            for k, v in rs.items():
                self._root_sum[k] = self._root_sum.get(k, 0.0) + v
            for k, items in ex.items():
                heap = self._exemplars.setdefault(k, [])
                for item in items:
                    self._seq += 1
                    item = (item[0], self._seq, item[2])
                    if len(heap) < self.max_exemplars:
                        heapq.heappush(heap, item)
                    elif item[0] > heap[0][0]:
                        heapq.heapreplace(heap, item)

    def _p99_exemplar(self, key: tuple) -> dict | None:
        """The retained trace nearest the merged p99 from above."""
        heap = self._exemplars.get(key)
        if not heap:
            return None
        p99 = histogram_quantile(0.99, self._root_hist.get(key, ()))
        candidates = sorted(heap, key=lambda it: it[0])
        for root_s, _seq, breakdown in candidates:
            if p99 is None or root_s >= p99 or root_s >= BUCKETS[-1]:
                return breakdown
        return candidates[-1][2]  # merged p99 above every retained root

    def doc(self) -> dict:
        """``{op: {shard: {"count", "root_sum_s", "phases": {phase:
        {"sum_s", "share", "buckets"}}, "p99_exemplar": {...}}}}`` —
        the ``/fleet`` ``write_budget_by_phase`` surface.  Bucket
        vectors ride along so any consumer can merge further."""
        with self._lock:
            keys = sorted(self._root_count)
            out: dict = {}
            for op, shard in keys:
                total = self._root_sum.get((op, shard), 0.0)
                phases = {}
                for phase in PHASES:
                    pk = (op, shard, phase)
                    if pk not in self._phase_hist:
                        continue
                    s = self._phase_sum.get(pk, 0.0)
                    phases[phase] = {
                        "sum_s": round(s, 6),
                        "share": round(s / total, 4) if total else 0.0,
                        "buckets": list(self._phase_hist[pk]),
                    }
                ex = self._p99_exemplar((op, shard))
                out.setdefault(op, {})[shard] = {
                    "count": self._root_count[(op, shard)],
                    "root_sum_s": round(total, 6),
                    "root_p99_le_s": histogram_quantile(
                        0.99, self._root_hist.get((op, shard), ())
                    ),
                    "phases": phases,
                    "p99_exemplar": (
                        {
                            "trace_id": ex["trace_id"],
                            "root_s": round(ex["root_s"], 6),
                            "phases": {
                                p: round(v, 6)
                                for p, v in ex["phases"].items()
                                if v > 0.0
                            },
                        }
                        if ex
                        else None
                    ),
                }
            return out

    @staticmethod
    def fleet_shares(budget_doc: dict, op: str = "write") -> dict:
        """Fleet-wide ``{phase: share}`` over one op, each shard's
        shares weighted by the wall clock that shard's roots actually
        spent (``root_sum_s``) — the verdict join's input (§20): a
        phase dominating a busy shard outweighs the same phase idling
        on a quiet one.  Empty before any trace was attributed."""
        agg: dict[str, float] = {}
        total = 0.0
        for sh_doc in budget_doc.get(op, {}).values():
            w = sh_doc.get("root_sum_s") or 0.0
            if w <= 0:
                continue
            total += w
            for ph, pd in (sh_doc.get("phases") or {}).items():
                agg[ph] = agg.get(ph, 0.0) + w * (pd.get("share") or 0.0)
        return {ph: v / total for ph, v in agg.items()} if total else {}

    def reset(self) -> None:
        with self._lock:
            self._phase_hist.clear()
            self._phase_sum.clear()
            self._root_hist.clear()
            self._root_count.clear()
            self._root_sum.clear()
            self._exemplars.clear()
