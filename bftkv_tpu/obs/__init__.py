"""Fleet health plane: cross-process observability for a sharded fleet.

PR 2 gave each daemon its own ``/metrics`` and ``/trace`` rings; PR 6
split the fleet into disjoint quorum cliques.  After that, no single
component could answer the question the paper's safety story makes
quantitative: *how close is shard k to losing liveness or safety right
now* — a quorum survives only while failures stay under
``f = (n-1)/3``, and that margin was invisible.

This package is the aggregation side (the shape Thetacrypt proves out:
a co-located service multiplexing many replicas is only operable with
a shared observability plane):

- :mod:`bftkv_tpu.obs.source` — where fleet state comes from: one
  :class:`~bftkv_tpu.obs.source.HTTPSource` per daemon API (scrapes
  ``/info`` + ``/metrics`` + ``/trace?since=``), or
  :class:`~bftkv_tpu.obs.source.LocalSource` for in-process clusters
  (the chaos harness);
- :mod:`bftkv_tpu.obs.stitch` — joins every process's exported spans
  into one tree per trace id, so a single client write reads as one
  story across client, quorum, and storage processes;
- :mod:`bftkv_tpu.obs.collector` — the
  :class:`~bftkv_tpu.obs.collector.FleetCollector`: per-shard
  **f-budget** against the ``quorum/wotqs.py`` thresholds, merged
  fixed-bucket SLO histograms with slow-trace exemplars, and an
  anomaly feed (counter deltas, membership transitions, failpoint
  events);
- :mod:`bftkv_tpu.obs.http` — ``/fleet`` as JSON and Prometheus text;
- :mod:`bftkv_tpu.obs.critpath` — exclusive-time decomposition of each
  stitched write/read trace over the closed ``trace.PHASES`` enum,
  aggregated into mergeable per-shard phase histograms with a p99
  exemplar (``/fleet`` ``write_budget_by_phase``, DESIGN.md §18);
- :mod:`bftkv_tpu.obs.profiler` — opt-in wall-clock sampling profiler
  (collapsed flamegraph stacks, ``/profile?seconds=N`` per daemon);
- :mod:`bftkv_tpu.obs.recorder` — the flight recorder: anomaly-driven,
  rate-limited, size-capped black-box bundles of every diagnostic ring;
- :mod:`bftkv_tpu.obs.capacity` — the USE-method capacity plane over
  the closed resource vocabulary + the bottleneck-verdict engine
  (``/fleet`` ``capacity``, ``cmd.fleet --capacity``, DESIGN.md §20).

Entry points: ``python -m bftkv_tpu.cmd.fleet`` (one-shot, ``--watch``,
``--listen``, ``--budget``, ``--capacity``, ``--bundle``) and
``run_cluster --fleet``.  Design: docs/DESIGN.md §11 (health plane) +
§18 (diagnosis tier) + §20 (capacity plane).
"""

from bftkv_tpu.obs.capacity import CapacityPlane
from bftkv_tpu.obs.collector import FleetCollector
from bftkv_tpu.obs.critpath import PhaseBudget, attribute
from bftkv_tpu.obs.recorder import FlightRecorder
from bftkv_tpu.obs.source import HTTPSource, LocalSource
from bftkv_tpu.obs.stitch import Stitcher

__all__ = [
    "CapacityPlane",
    "FleetCollector",
    "FlightRecorder",
    "HTTPSource",
    "LocalSource",
    "PhaseBudget",
    "Stitcher",
    "attribute",
]
