"""Cross-process trace stitching.

Each process's :class:`~bftkv_tpu.trace.Tracer` retains only its own
spans: the client write's root lives in the process that issued the
write, the ``server.*`` spans live in every replica that served it,
joined only by the trace id that rode inside the encrypted payload
(``packet.wrap_trace``).  The stitcher is where those fragments become
one tree again: feed it every source's span export and it groups by
trace id, de-duplicates (a collector may re-scrape overlapping
windows), tags each span with the source it came from, and assembles
parent→child trees on demand.

Bounded like everything else in the metrics/trace plane: at most
``max_traces`` traces and ``max_spans_per_trace`` spans each, evicting
oldest-inserted first — sustained fleet traffic cannot grow the
collector without bound.
"""

from __future__ import annotations

from collections import OrderedDict
from bftkv_tpu.devtools.lockwatch import named_lock

__all__ = ["Stitcher"]


class Stitcher:
    def __init__(self, max_traces: int = 256, max_spans_per_trace: int = 512):
        self.max_traces = max_traces
        self.max_spans_per_trace = max_spans_per_trace
        self._lock = named_lock("obs.stitch")
        #: trace id (hex) -> {"spans": {span id: span dict}, "sources": set}
        self._traces: "OrderedDict[str, dict]" = OrderedDict()

    def add(self, source: str, spans: list[dict]) -> int:
        """Ingest one source's exported spans; returns how many were
        new (not seen from any source before)."""
        added = 0
        with self._lock:
            for s in spans:
                tid = s.get("trace")
                sid = s.get("span")
                if not tid or not sid:
                    continue
                t = self._traces.get(tid)
                if t is None:
                    t = self._traces[tid] = {"spans": {}, "sources": set()}
                    while len(self._traces) > self.max_traces:
                        # Newest insertion sits last; eviction takes the
                        # oldest, so ``t`` survives this loop.
                        self._traces.popitem(last=False)
                if sid not in t["spans"]:
                    if len(t["spans"]) >= self.max_spans_per_trace:
                        continue
                    t["spans"][sid] = dict(s, src=source)
                    added += 1
                t["sources"].add(source)
        return added

    # -- views -------------------------------------------------------------

    def summary(self) -> dict:
        with self._lock:
            total = len(self._traces)
            stitched = sum(
                1 for t in self._traces.values() if len(t["sources"]) > 1
            )
        return {"traces": total, "stitched": stitched}

    def traces(self, limit: int = 20, stitched_only: bool = False) -> list[dict]:
        """Newest-inserted last.  Each entry: trace id, root name +
        duration (the longest parentless span, or the longest span when
        every root fragment is missing), span/source counts, and
        ``stitched`` (spans from more than one process)."""
        with self._lock:
            items = [
                (tid, list(t["spans"].values()), sorted(t["sources"]))
                for tid, t in self._traces.items()
            ]
        out = []
        for tid, spans, sources in items:
            if stitched_only and len(sources) <= 1:
                continue
            roots = [s for s in spans if "parent" not in s]
            root = max(
                roots or spans, key=lambda s: s.get("duration", 0.0)
            )
            out.append(
                {
                    "trace_id": tid,
                    "root": root.get("name", "?"),
                    "duration": root.get("duration", 0.0),
                    "spans": len(spans),
                    "sources": sources,
                    "stitched": len(sources) > 1,
                }
            )
        return out[-limit:]

    def spans(self, trace_id: str) -> list[dict] | None:
        """One trace's retained spans as a flat list (the critical-path
        attribution input), or None when the trace was never seen or
        already evicted."""
        with self._lock:
            t = self._traces.get(trace_id)
            return list(t["spans"].values()) if t else None

    def tree(self, trace_id: str) -> dict | None:
        """One trace as a nested tree: ``{"name", "src", "duration",
        "attrs", "children": [...]}``.  Orphan fragments (parent span
        not retained/exported) attach under the synthetic root so
        nothing silently disappears."""
        with self._lock:
            t = self._traces.get(trace_id)
            spans = list(t["spans"].values()) if t else None
        if spans is None:
            return None
        spans.sort(key=lambda s: s.get("start", 0.0))
        nodes = {
            s["span"]: {
                "name": s.get("name", "?"),
                "src": s.get("src", "?"),
                "duration": s.get("duration", 0.0),
                "attrs": s.get("attrs", {}),
                "children": [],
            }
            for s in spans
        }
        root = {"name": "trace", "trace_id": trace_id, "children": []}
        for s in spans:
            node = nodes[s["span"]]
            parent = nodes.get(s.get("parent"))
            (parent["children"] if parent else root["children"]).append(node)
        return root

    def reset(self) -> None:
        with self._lock:
            self._traces.clear()
