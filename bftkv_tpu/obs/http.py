"""HTTP surface of the fleet collector: ``/fleet`` JSON + Prometheus.

Same stance as the daemon API (cmd/bftkv.py): stdlib-only threading
HTTP server, content negotiation on one path — scrapers asking for
text (or ``?format=prometheus``) get the exposition, everyone else the
full JSON health document.  ``/fleet/trace/<id>`` serves one stitched
trace as a nested tree; ``/fleet/capacity`` serves just the capacity
section (USE rows + bottleneck verdict, DESIGN.md §20) for dashboards
that poll only the planning signal.
"""

from __future__ import annotations

import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

__all__ = ["serve_fleet"]


class _FleetHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *a):
        pass

    def _reply(self, code: int, body: bytes, ctype: str):
        self.send_response(code)
        self.send_header("content-type", ctype)
        self.send_header("content-length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):
        collector = self.server.collector
        try:
            if self.path == "/fleet/bundle" or self.path.startswith(
                "/fleet/bundle?"
            ):
                # Demand flight-recorder snapshot (cmd.fleet --bundle
                # against a listening collector): POST-only — it writes
                # to disk.
                rec = getattr(collector, "recorder", None)
                if rec is None:
                    self._reply(
                        404,
                        b"no flight recorder attached "
                        b"(cmd.fleet --recorder DIR)\n",
                        "text/plain",
                    )
                    return
                bundle = rec.snapshot(reason="demand")
                self._reply(
                    200,
                    json.dumps({"bundle": bundle}).encode() + b"\n",
                    "application/json",
                )
            else:
                self._reply(404, b"unknown endpoint\n", "text/plain")
        except Exception as e:  # operator surface: never die
            self._reply(500, (str(e) + "\n").encode(), "text/plain")

    def do_GET(self):
        collector = self.server.collector
        path = self.path
        try:
            if path.startswith("/fleet/trace/"):
                tid = urllib.parse.unquote(path[len("/fleet/trace/"):])
                tree = collector.stitcher.tree(tid.split("?", 1)[0])
                if tree is None:
                    self._reply(404, b"unknown trace\n", "text/plain")
                    return
                self._reply(
                    200,
                    json.dumps(tree, sort_keys=True, default=str).encode(),
                    "application/json",
                )
            elif path == "/fleet/capacity" or path.startswith(
                "/fleet/capacity?"
            ):
                # Just the capacity section — the health document is
                # large; a saturation dashboard needs only this.
                self._reply(
                    200,
                    json.dumps(
                        collector.health().get("capacity") or {},
                        sort_keys=True,
                        default=str,
                    ).encode(),
                    "application/json",
                )
            elif path == "/fleet" or path.startswith("/fleet?"):
                q = urllib.parse.parse_qs(
                    urllib.parse.urlparse(path).query
                )
                accept = self.headers.get("accept") or ""
                want_prom = q.get("format", [""])[0] == "prometheus" or (
                    "application/json" not in accept
                    and (
                        "text/plain" in accept or "openmetrics" in accept
                    )
                )
                if want_prom:
                    self._reply(
                        200,
                        collector.prometheus().encode(),
                        "text/plain; version=0.0.4; charset=utf-8",
                    )
                else:
                    self._reply(
                        200,
                        json.dumps(
                            collector.health(),
                            sort_keys=True,
                            default=str,
                        ).encode(),
                        "application/json",
                    )
            elif path == "/metrics" or path.startswith("/metrics?"):
                # Scraper convenience: the collector exposes ITS fleet
                # rollup here, so one Prometheus job covers the plane.
                self._reply(
                    200,
                    collector.prometheus().encode(),
                    "text/plain; version=0.0.4; charset=utf-8",
                )
            elif path == "/healthz":
                self._reply(200, b"ok\n", "text/plain")
            else:
                self._reply(404, b"unknown endpoint\n", "text/plain")
        except Exception as e:  # operator surface: never die
            self._reply(500, (str(e) + "\n").encode(), "text/plain")


def serve_fleet(collector, addr: str) -> ThreadingHTTPServer:
    """Serve ``/fleet`` for ``collector`` on ``host:port``; returns the
    started server (daemon threads — call ``.shutdown()`` to stop)."""
    host, _, port = addr.rpartition(":")
    httpd = ThreadingHTTPServer((host or "127.0.0.1", int(port)),
                                _FleetHandler)
    httpd.daemon_threads = True
    httpd.collector = collector
    threading.Thread(
        target=httpd.serve_forever, name="fleet-http", daemon=True
    ).start()
    return httpd
