"""USE-method capacity plane + the fleet bottleneck-verdict engine
(DESIGN.md §20).

The diagnosis tier so far answers "why was THIS request slow" (§18:
attribution, profiler, recorder).  This module answers the planning
question — **what limits throughput right now** — by reading every
bounded resource in the box through one closed vocabulary and the USE
method: *Utilization* (how full is the resource), *Saturation* (how
much work is queued/shed/throttled behind it, normalized to [0, 1]),
*Errors* (work the resource refused this scrape).

The resource vocabulary is CLOSED, exactly like ``metrics.LABEL_KEYS``
and ``trace.PHASES`` — the ``resource`` metric label, the
``bftkv_fleet_resource_*`` Prometheus family, the ``/fleet`` capacity
section, and the verdict join all key off :data:`RESOURCES`.  Adding a
resource is a deliberate schema change (declare the name here, map its
phases, document the signals in §20).

The **verdict** joins per-resource saturation with §18's phase budgets:
a saturated resource only limits throughput to the extent the write
path actually *spends time* in the phases that resource backs, so each
(member, resource) is scored ``saturation x phase-share`` (share floor
0.05 — a fully saturated resource in a currently-unattributed phase
still ranks above quiet ones; the GIL is cross-cutting and carries a
flat floor instead of a phase).  The ranked list, per member and
fleet-wide, is what ``cmd.fleet --capacity`` prints under the verdict
line.

Sustained saturation becomes the ``resource_saturated`` anomaly with
``slo_burn``'s exact hysteresis contract: ``BFTKV_SAT_THRESHOLD``
breached for ``BFTKV_SAT_SCRAPES`` consecutive traffic-bearing scrapes
fires ONCE per episode; idle scrapes hold the count; a healthy scrape
re-arms.  The collector emits it through the anomaly feed, so the
flight recorder snapshots capacity state automatically.
"""

from __future__ import annotations

import time

from bftkv_tpu import flags

__all__ = [
    "RESOURCES",
    "RESOURCE_PHASES",
    "CapacityPlane",
    "compute_member",
]

#: The closed resource vocabulary (same cardinality rule as
#: ``metrics.LABEL_KEYS``): every bounded resource the box can queue
#: behind, one canonical name each.
RESOURCES = (
    "admission",     # AdmissionQueue slots (gateway + sidecar tiers)
    "dispatch",      # device batch plane (sign/verify/modexp launches)
    "fanout_pool",   # transport._DaemonPool multicast workers
    "conn_pool",     # per-peer keep-alive HTTP connection pool
    "log_commit",    # log-engine group-commit fsync barrier
    "compact_io",    # compaction copy bandwidth (governed)
    "sync_lag",      # repair daemon scan-cursor backlog
    "gil",           # interpreter: runnable (GIL-queued) threads
)

#: Verdict join: which §18 phases each resource backs.  A resource's
#: weight is the write budget's share-sum over these phases (floor
#: applied in :meth:`CapacityPlane.verdict`).  ``gil`` is cross-cutting
#: — it maps to no phase and scores on a flat floor.
RESOURCE_PHASES: dict[str, tuple[str, ...]] = {
    "admission": ("server", "sidecar"),
    "dispatch": ("dispatch",),
    "fanout_pool": ("fanout",),
    "conn_pool": ("rpc",),
    "log_commit": ("server",),
    "compact_io": ("server",),
    "sync_lag": ("backfill",),
    "gil": (),
}

#: Cross-cutting / unattributed weight floor (see module doc).
_SHARE_FLOOR = 0.05
_GIL_WEIGHT = 0.25

#: Saturation scale constants: pool submits queued per scrape that
#: count as "fully saturated", and runnable threads past the one the
#: GIL can run that count the same.
_POOL_SAT_REF = 8.0
_GIL_SAT_REF = 4.0


def _index(snap: dict) -> dict:
    """Flat snapshot → ``name -> [(labels, value)]`` (one parse pass)."""
    from bftkv_tpu.obs.collector import parse_flat_key

    idx: dict[str, list[tuple[dict, float]]] = {}
    for k, v in snap.items():
        if not isinstance(v, (int, float)):
            continue
        name, labels = parse_flat_key(k)
        idx.setdefault(name, []).append((labels, float(v)))
    return idx


def _first(idx: dict, name: str, **want) -> float | None:
    for labels, v in idx.get(name, ()):
        if all(labels.get(k) == w for k, w in want.items()):
            return v
    return None


def _sum(idx: dict, name: str, **want) -> float:
    return sum(
        v
        for labels, v in idx.get(name, ())
        if all(labels.get(k) == w for k, w in want.items())
    )


def _delta(idx: dict, prev: dict, name: str, **want) -> float:
    """Per-scrape counter delta (floored at 0 — a member restart resets
    its counters; a negative delta is a reboot, not negative traffic)."""
    return max(0.0, _sum(idx, name, **want) - _sum(prev, name, **want))


def compute_member(
    idx: dict, prev: dict, dt: float, *, wait_ref: float | None = None
) -> dict:
    """USE rows for one member from an indexed snapshot (``_index``)
    plus the previous scrape's index (counter-delta baseline; ``{}``
    on the first scrape makes deltas equal totals, which is the honest
    first reading).  Returns ``{resource: row}`` with only the
    resources the member actually exposes; each row carries
    ``utilization`` / ``saturation`` in [0, 1], ``errors`` (count this
    scrape), a private ``_traffic`` bool for the hysteresis, and
    resource-specific extras (occupancy breakdowns, rates)."""
    if wait_ref is None:
        wait_ref = flags.get_float("BFTKV_SAT_WAIT_REF") or 0.25
    dt = max(dt, 1e-9)
    rows: dict[str, dict] = {}

    # -- admission ---------------------------------------------------------
    tiers = {}
    for tier in ("gateway", "sidecar"):
        limit = _first(idx, "admission.limit", resource=tier)
        if limit is None:
            continue
        inflight = _first(idx, "admission.inflight", resource=tier) or 0.0
        waiting = _first(idx, "admission.waiting", resource=tier) or 0.0
        qlimit = _first(idx, "admission.queue_limit", resource=tier) or 1.0
        shed = _delta(idx, prev, f"{tier}.shed")
        wait_p99 = _first(idx, "admission.wait.p99", resource=tier) or 0.0
        tiers[tier] = {
            "inflight": inflight,
            "waiting": waiting,
            "limit": limit,
            "shed": shed,
            "wait_p99_s": round(wait_p99, 6),
            "utilization": min(1.0, inflight / max(1.0, limit)),
            "saturation": max(
                min(1.0, waiting / max(1.0, qlimit)),
                min(1.0, wait_p99 / wait_ref),
                1.0 if shed > 0 else 0.0,
            ),
        }
    if tiers:
        rows["admission"] = {
            "utilization": max(t["utilization"] for t in tiers.values()),
            "saturation": max(t["saturation"] for t in tiers.values()),
            "errors": sum(t["shed"] for t in tiers.values()),
            "_traffic": any(
                _delta(idx, prev, "admission.wait.count", resource=t) > 0
                or tiers[t]["shed"] > 0
                for t in tiers
            ),
            "tiers": tiers,
        }

    # -- dispatch ----------------------------------------------------------
    disps = {}
    for name in ("dispatch", "signdispatch", "modexpdispatch"):
        widths = {
            labels.get("width", "all"): v
            for labels, v in idx.get(f"{name}.device_occupancy", ())
        }
        flushes = _delta(idx, prev, f"{name}.flushes")
        items = _delta(idx, prev, f"{name}.items")
        if not widths and not flushes:
            continue
        wait_p99 = _first(idx, f"{name}.wait.p99") or 0.0
        disps[name] = {
            "device_occupancy": widths,
            "items_per_launch": round(items / flushes, 2) if flushes else None,
            "wait_p99_s": round(wait_p99, 6),
            "flushes": flushes,
        }
    if disps:
        # Device-plane signals (r11): observed launch RTT (the online
        # recalibration EWMA) and per-width staging buffer-ring
        # saturation.  A full ring means flushes are allocating fresh
        # staging arrays behind a busy device — the buffer rings are the
        # wall, which folds into the row's saturation alongside caller
        # wait: either one pushing up is the dispatch plane telling the
        # fleet it cannot absorb more offered load.
        launch_rtt = _first(idx, "dispatch.launch_rtt")
        rings = {
            labels.get("width", "all"): v
            for labels, v in idx.get("devbuf.saturation", ())
        }
        ring_sat = max(rings.values(), default=0.0)
        row = {
            "utilization": max(
                (
                    occ
                    for d in disps.values()
                    for occ in d["device_occupancy"].values()
                ),
                default=0.0,
            ),
            "saturation": max(
                min(
                    1.0,
                    max(d["wait_p99_s"] for d in disps.values()) / wait_ref,
                ),
                min(1.0, ring_sat),
            ),
            "errors": 0.0,
            "_traffic": any(d["flushes"] > 0 for d in disps.values()),
            "dispatchers": disps,
        }
        if launch_rtt is not None:
            row["launch_rtt_s"] = round(launch_rtt, 6)
        if rings:
            row["buffer_rings"] = {
                w: round(v, 4) for w, v in sorted(rings.items())
            }
        rows["dispatch"] = row

    # -- fanout_pool -------------------------------------------------------
    cap = _first(idx, "transport.pool.cap", resource="fanout_pool")
    if cap:
        busy = _first(idx, "transport.pool.busy", resource="fanout_pool") or 0.0
        queued = _delta(idx, prev, "transport.pool.saturated")
        overflow = _delta(idx, prev, "transport.pool.nested_overflow")
        rows["fanout_pool"] = {
            "utilization": min(1.0, busy / cap),
            "saturation": min(1.0, queued / _POOL_SAT_REF),
            "errors": overflow,
            "_traffic": True,  # gauge presence == fan-out happened
            "busy": busy,
            "cap": cap,
            "queued_submits": queued,
        }

    # -- conn_pool ---------------------------------------------------------
    dialed = _delta(idx, prev, "transport.conn.dialed")
    reused = _delta(idx, prev, "transport.conn.reused")
    if dialed or reused or idx.get("transport.conn.idle"):
        total = dialed + reused
        miss = dialed / total if total else 0.0
        rows["conn_pool"] = {
            "utilization": round(miss, 4),
            "saturation": round(miss if total else 0.0, 4),
            "errors": 0.0,
            "_traffic": total > 0,
            "dialed": dialed,
            "reused": reused,
            "idle": _first(idx, "transport.conn.idle", resource="conn_pool")
            or 0.0,
        }

    # -- log_commit --------------------------------------------------------
    commits = _delta(idx, prev, "storage.log.commit_wait.count")
    if commits or idx.get("storage.log.linger_ms"):
        linger_s = (_first(idx, "storage.log.linger_ms") or 0.0) / 1000.0
        p99 = _first(idx, "storage.log.commit_wait.p99") or 0.0
        fsyncs = _delta(idx, prev, "storage.log.fsync")
        bsum = _delta(idx, prev, "storage.log.batch.sum")
        bcount = _delta(idx, prev, "storage.log.batch.count")
        rows["log_commit"] = {
            # Linger occupancy: fraction of the scrape the fsync leader
            # spent inside a linger window.
            "utilization": min(1.0, fsyncs * linger_s / dt),
            "saturation": min(
                1.0, p99 / max(4.0 * linger_s, wait_ref)
            ),
            "errors": _delta(idx, prev, "storage.log.torn_truncated")
            + _delta(idx, prev, "storage.log.sealed_tear"),
            "_traffic": commits > 0,
            "fsync_per_s": round(fsyncs / dt, 2),
            "batch_fill": round(bsum / bcount, 2) if bcount else None,
            "commit_wait_p99_s": round(p99, 6),
            "linger_ms": round(linger_s * 1000.0, 3),
        }

    # -- compact_io --------------------------------------------------------
    moved = _delta(idx, prev, "storage.compact.read_bytes") + _delta(
        idx, prev, "storage.compact.written_bytes"
    )
    if moved or idx.get("storage.compact.mbps"):
        governor = flags.get_float("BFTKV_LOG_COMPACT_MBPS") or 0.0
        mbps = moved / dt / (1024 * 1024)
        throttle = _delta(idx, prev, "storage.compact.throttle.sum")
        rows["compact_io"] = {
            "utilization": min(1.0, mbps / governor)
            if governor
            else (1.0 if moved else 0.0),
            "saturation": min(1.0, throttle / dt),
            "errors": _delta(idx, prev, "storage.log.compact_failed"),
            "_traffic": moved > 0,
            "mbps": round(mbps, 3),
            "throttle_s": round(throttle, 4),
        }

    # -- sync_lag ----------------------------------------------------------
    lag = _first(idx, "sync.repair.cursor_lag")
    if lag is not None:
        rows["sync_lag"] = {
            "utilization": min(1.0, lag),
            "saturation": min(1.0, lag),
            "errors": _delta(idx, prev, "sync.repair.demoted"),
            "_traffic": True,
            "backlog": _first(idx, "sync.repair.backlog") or 0.0,
        }

    # -- gil ---------------------------------------------------------------
    runnable = _first(idx, "gil.runnable", resource="gil")
    if runnable is not None:
        rows["gil"] = {
            "utilization": min(1.0, runnable / (1.0 + _GIL_SAT_REF)),
            # >1 runnable thread means someone is queued on the GIL.
            "saturation": min(1.0, max(0.0, runnable - 1.0) / _GIL_SAT_REF),
            "errors": 0.0,
            "_traffic": True,
            "runnable": runnable,
        }

    return rows


class CapacityPlane:
    """Per-member USE state + the verdict engine + the
    ``resource_saturated`` hysteresis.  One instance per collector (and
    one inside the bench harness); ``observe`` folds a member's metrics
    snapshot each scrape, ``doc``/``verdict`` render, ``check`` runs
    the anomaly hysteresis and returns newly-fired episodes."""

    def __init__(self) -> None:
        self._prev: dict[str, dict] = {}     # member -> last index
        self._last_ts: dict[str, float] = {}  # member -> last observe ts
        self._rows: dict[str, dict] = {}     # member -> resource rows
        self._sat_count: dict[tuple[str, str], int] = {}

    # -- fold --------------------------------------------------------------

    def observe(self, member: str, snap: dict, now: float | None = None) -> dict:
        """Fold one member scrape; returns the member's USE rows."""
        if now is None:
            now = time.monotonic()
        idx = _index(snap)
        prev = self._prev.get(member, {})
        dt = now - self._last_ts.get(member, now - 1.0)
        rows = compute_member(idx, prev, dt)
        self._prev[member] = idx
        self._last_ts[member] = now
        self._rows[member] = rows
        return rows

    def forget(self, member: str) -> None:
        self._prev.pop(member, None)
        self._last_ts.pop(member, None)
        self._rows.pop(member, None)
        for key in [k for k in self._sat_count if k[0] == member]:
            del self._sat_count[key]

    # -- render ------------------------------------------------------------

    def doc(self) -> dict:
        """``{member: {resource: row}}`` with the private keys dropped
        and a fleet-wide per-resource max fold."""
        members = {
            m: {
                res: {k: v for k, v in row.items() if not k.startswith("_")}
                for res, row in rows.items()
            }
            for m, rows in self._rows.items()
        }
        fleet: dict[str, dict] = {}
        for rows in self._rows.values():
            for res, row in rows.items():
                agg = fleet.setdefault(
                    res, {"utilization": 0.0, "saturation": 0.0, "errors": 0.0}
                )
                agg["utilization"] = max(agg["utilization"], row["utilization"])
                agg["saturation"] = max(agg["saturation"], row["saturation"])
                agg["errors"] += row["errors"]
        return {"members": members, "fleet": fleet}

    def verdict(self, phase_shares: dict | None = None) -> dict:
        """Rank (member, resource) by ``saturation x phase-weight``.

        ``phase_shares`` is ``{phase: share}`` from the write budget
        (shares sum to ~1 across ``trace.PHASES``); None or empty —
        e.g. before any trace has been attributed — degrades to pure
        saturation ranking (weight 1.0), which is still a verdict, just
        an unjoined one."""
        shares = phase_shares or {}
        ranked = []
        for member, rows in self._rows.items():
            for res, row in rows.items():
                sat = row["saturation"]
                if sat <= 0 and row["errors"] <= 0:
                    continue
                if not shares:
                    weight = 1.0
                elif res == "gil":
                    weight = _GIL_WEIGHT
                else:
                    weight = max(
                        sum(
                            shares.get(p, 0.0)
                            for p in RESOURCE_PHASES.get(res, ())
                        ),
                        _SHARE_FLOOR,
                    )
                ranked.append(
                    {
                        "member": member,
                        "resource": res,
                        "saturation": round(sat, 4),
                        "utilization": round(row["utilization"], 4),
                        "phase_weight": round(weight, 4),
                        "score": round(sat * weight, 4),
                    }
                )
        ranked.sort(key=lambda r: (-r["score"], -r["saturation"]))
        top = ranked[0] if ranked else None
        if top is not None:
            summary = (
                f"{top['resource']} on {top['member']} limits throughput "
                f"(saturation {top['saturation']:.2f} x phase weight "
                f"{top['phase_weight']:.2f})"
            )
        else:
            # Nothing saturated: report the fullest resource instead —
            # "you are not queueing anywhere; here is the next wall".
            best = None
            for member, rows in self._rows.items():
                for res, row in rows.items():
                    if best is None or row["utilization"] > best[2]:
                        best = (member, res, row["utilization"])
            summary = (
                "no saturated resource"
                + (
                    f"; highest utilization {best[1]} on {best[0]} "
                    f"({best[2]:.2f})"
                    if best
                    else ""
                )
            )
        return {"ranked": ranked, "top": top, "summary": summary}

    # -- anomaly hysteresis ------------------------------------------------

    def check(self) -> list[dict]:
        """The ``resource_saturated`` hysteresis, slo_burn's contract:
        saturation >= BFTKV_SAT_THRESHOLD on a traffic-bearing scrape
        advances the (member, resource) counter; BFTKV_SAT_SCRAPES
        consecutive ones fire ONCE; idle holds; healthy re-arms.
        Returns the episodes fired by the LATEST observed scrapes."""
        thr = flags.get_float("BFTKV_SAT_THRESHOLD")
        if not thr:
            return []
        k = max(flags.get_int("BFTKV_SAT_SCRAPES") or 3, 1)
        fired = []
        for member, rows in self._rows.items():
            for res, row in rows.items():
                key = (member, res)
                if row["saturation"] >= thr and row.get("_traffic", True):
                    n = self._sat_count.get(key, 0) + 1
                    self._sat_count[key] = n
                    if n == k:
                        fired.append(
                            {
                                "member": member,
                                "resource": res,
                                "saturation": row["saturation"],
                                "utilization": row["utilization"],
                            }
                        )
                elif row.get("_traffic", True):
                    self._sat_count[key] = 0
                # idle scrape: hold the count (idle can neither
                # saturate nor recover a resource).
        return fired
