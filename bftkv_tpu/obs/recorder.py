"""Flight recorder: a bounded on-disk black box for anomaly windows.

Every diagnostic surface this tree grew is a *ring*: the trace rings
overwrite in seconds under load, metrics are cumulative (the delta
that mattered is gone), the anomaly feed is bounded, the failpoint
trace caps out.  By the time a human looks at a 3 a.m. page, the
evidence has been overwritten.  The recorder closes that gap: on any
anomaly-feed event (and on ``cmd.fleet --bundle`` demand) it snapshots
the rings THAT INSTANT into one timestamped bundle directory::

    <dir>/bundle-<utcstamp>-<reason>/
        manifest.json     # ts, reason, anomalies, file inventory+sizes
        traces.json       # recent + slow tracer rings
        metrics.json      # flat metrics snapshot
        health.json       # fleet health document (budgets, epochs)
        anomalies.json    # the collector's anomaly ring
        failpoints.json   # fault-injection event log
        lockwatch.json    # lock sanitizer report (when armed)
        profile.txt       # last captured profile window (when any)

Disk discipline, because a flapping anomaly must not fill the volume:

- **coalescing** — anomalies inside ``min_interval_s`` of the last
  bundle AMEND that bundle's manifest instead of minting a new one
  (one fault window → one bundle, the nemesis oracle's shape);
  :meth:`mark_window` opens a fresh coalescing epoch so back-to-back
  windows never share a bundle;
- **size cap** — total bytes across bundles ≤ ``max_bytes`` and at
  most ``max_bundles`` directories; oldest bundles are evicted first
  (the black box keeps the *recent* past, like its aviation namesake);
- bundles are plain JSON + text, readable with no live fleet and no
  bftkv import.

Design: docs/DESIGN.md §18.
"""

from __future__ import annotations

import json
import os
import shutil
import time

from bftkv_tpu import flags
from bftkv_tpu.devtools.lockwatch import named_lock

__all__ = ["FlightRecorder", "default_dir", "read_manifest"]


def default_dir() -> str:
    """``BFTKV_RECORDER_DIR`` or ``<tmp>/bftkv-blackbox``."""
    d = flags.raw("BFTKV_RECORDER_DIR")
    if d:
        return d
    import tempfile

    return os.path.join(tempfile.gettempdir(), "bftkv-blackbox")


def read_manifest(bundle_dir: str) -> dict:
    """One bundle's manifest — stdlib-only on purpose (a bundle must
    open on a laptop with nothing installed)."""
    with open(os.path.join(bundle_dir, "manifest.json")) as f:
        return json.load(f)


class FlightRecorder:
    """``dir``: bundle root (created on first use).  The feed objects
    are all optional — a recorder wired to nothing still writes valid
    (if sparse) bundles, which is what the no-live-fleet tests prove.

    Thread-safe; :meth:`on_anomaly` is shaped to hang directly off
    ``FleetCollector.add_anomaly_listener``."""

    def __init__(
        self,
        dir: str | None = None,
        *,
        collector=None,
        tracer=None,
        metrics=None,
        fp_registry=None,
        min_interval_s: float | None = None,
        max_bundles: int = 16,
        max_bytes: int | None = None,
    ):
        self.dir = dir or default_dir()
        self.collector = collector
        self.tracer = tracer
        self.metrics = metrics
        self.fp_registry = fp_registry
        self.min_interval_s = (
            min_interval_s
            if min_interval_s is not None
            else (flags.get_float("BFTKV_RECORDER_MIN_INTERVAL") or 5.0)
        )
        self.max_bundles = max_bundles
        self.max_bytes = (
            max_bytes
            if max_bytes is not None
            else (flags.get_int("BFTKV_RECORDER_MAX_MB") or 64) * 1048576
        )
        self._lock = named_lock("obs.recorder")
        self._last_bundle: str | None = None
        self._last_ts = 0.0
        self._epoch = 0  # bumped by mark_window: never coalesce across
        self._last_epoch = -1
        self.bundle_count = 0  # bundles CREATED by this recorder
        self.coalesced = 0
        self.suppressed = 0

    # -- the anomaly→bundle path -------------------------------------------

    def add_to(self, collector) -> "FlightRecorder":
        """Subscribe to a collector's anomaly feed (and adopt it as the
        health/anomaly source when none was given).  The collector also
        learns about the recorder so its ``/fleet/bundle`` endpoint can
        serve demand snapshots."""
        if self.collector is None:
            self.collector = collector
        collector.recorder = self
        collector.add_anomaly_listener(self.on_anomaly)
        return self

    def on_anomaly(self, anomaly: dict) -> None:
        """One anomaly event → one bundle, coalesced: follow-up events
        amend the window's bundle instead of minting new snapshots.
        With :meth:`mark_window` in use (epoch > 0, the nemesis) the
        window boundary IS the coalescing boundary — every same-epoch
        event amends; without it, ``min_interval_s`` rate-limits."""
        with self._lock:
            same_epoch = self._last_epoch == self._epoch
            recent = (time.time() - self._last_ts) < self.min_interval_s
            coalesce = self._last_bundle is not None and same_epoch and (
                recent or self._epoch > 0
            )
            if coalesce:
                self._amend_locked(anomaly)
                self.coalesced += 1
                return
        try:
            self.snapshot(
                reason=str(anomaly.get("kind", "anomaly")),
                anomalies=[anomaly],
            )
        except OSError:
            with self._lock:
                self.suppressed += 1  # a full disk must not kill scrapes

    def mark_window(self) -> None:
        """Open a new coalescing epoch: the NEXT anomaly mints a fresh
        bundle even if the previous one is recent.  The nemesis calls
        this at each fault-window boundary so one window maps to one
        bundle deterministically."""
        with self._lock:
            self._epoch += 1

    def _amend_locked(self, anomaly: dict) -> None:
        path = os.path.join(self._last_bundle, "manifest.json")
        try:
            with open(path) as f:
                manifest = json.load(f)
            manifest.setdefault("anomalies", []).append(anomaly)
            manifest["amended_ts"] = time.time()
            tmp = path + "~"
            with open(tmp, "w") as f:
                json.dump(manifest, f, indent=1, default=repr)
            os.replace(tmp, path)
        except OSError:
            self.suppressed += 1

    # -- snapshotting ------------------------------------------------------

    def _feeds(self) -> dict:
        """name → JSON-able payload, best effort per feed (one broken
        feed must not cost the bundle the others)."""
        out: dict = {}
        tracer = self.tracer
        if tracer is None:
            from bftkv_tpu import trace as trmod

            tracer = trmod.tracer
        metrics = self.metrics
        if metrics is None:
            from bftkv_tpu.metrics import registry as metrics
        feeds = {
            "traces.json": lambda: {
                "recent": tracer.traces(limit=50),
                "slow": tracer.slow(),
            },
            "metrics.json": metrics.snapshot,
        }
        if self.collector is not None:
            feeds["health.json"] = self.collector.health
            feeds["anomalies.json"] = self.collector.anomalies
            # The capacity plane standalone (it also rides health.json):
            # a resource_saturated bundle must answer "what was full"
            # on a laptop without digging through the health document.
            cap = getattr(self.collector, "capacity", None)
            if cap is not None:
                feeds["capacity.json"] = lambda: {
                    **cap.doc(),
                    "verdict": cap.verdict(),
                }
        fp_registry = self.fp_registry
        if fp_registry is None:
            from bftkv_tpu.faults import failpoint as fp

            fp_registry = fp._active
        feeds["failpoints.json"] = lambda: [
            list(e) for e in fp_registry.trace()[-500:]
        ]

        def lockwatch_doc():
            from bftkv_tpu.devtools import lockwatch

            return lockwatch.report() if lockwatch.enabled() else None

        feeds["lockwatch.json"] = lockwatch_doc
        for name, fn in feeds.items():
            try:
                out[name] = fn()
            except Exception as e:
                out[name] = {"feed_error": repr(e)}
        return out

    def snapshot(
        self,
        reason: str = "demand",
        anomalies: list | None = None,
    ) -> str:
        """Write one bundle NOW (the ``cmd.fleet --bundle`` demand
        path, and the first event of each anomaly window).  Returns the
        bundle directory path."""
        feeds = self._feeds()  # outside the lock: feeds take their own
        from bftkv_tpu.obs import profiler

        profile = profiler.last()
        with self._lock:
            # One clock read for both halves: seconds and milliseconds
            # sampled separately can straddle a second boundary and
            # mint "57.999" AFTER "57.001" — and bundles() name-sort
            # IS the eviction order.
            now = time.time()
            stamp = time.strftime(
                "%Y%m%dT%H%M%S", time.gmtime(now)
            ) + f".{int(now * 1000) % 1000:03d}"
            safe = "".join(
                c if c.isalnum() or c in "-_" else "_" for c in reason
            )[:48]
            bundle = os.path.join(self.dir, f"bundle-{stamp}-{safe}")
            os.makedirs(bundle, exist_ok=True)
            files: dict[str, int] = {}
            for name, payload in feeds.items():
                p = os.path.join(bundle, name)
                with open(p, "w") as f:
                    json.dump(payload, f, indent=1, default=repr)
                files[name] = os.path.getsize(p)
            if profile:
                p = os.path.join(bundle, "profile.txt")
                with open(p, "w") as f:
                    f.write(profile)
                files["profile.txt"] = os.path.getsize(p)
            manifest = {
                "ts": time.time(),
                "reason": reason,
                "anomalies": list(anomalies or []),
                "files": files,
                "bytes": sum(files.values()),
            }
            mp = os.path.join(bundle, "manifest.json")
            with open(mp, "w") as f:
                json.dump(manifest, f, indent=1, default=repr)
            self._last_bundle = bundle
            self._last_ts = time.time()
            self._last_epoch = self._epoch
            self.bundle_count += 1
            self._enforce_caps_locked(keep=bundle)
        return bundle

    # -- disk bounds -------------------------------------------------------

    def bundles(self) -> list[str]:
        """Bundle directories on disk, oldest first (the stamp sorts)."""
        if not os.path.isdir(self.dir):
            return []
        return sorted(
            os.path.join(self.dir, n)
            for n in os.listdir(self.dir)
            if n.startswith("bundle-")
            and os.path.isdir(os.path.join(self.dir, n))
        )

    @staticmethod
    def _du(path: str) -> int:
        total = 0
        for dirpath, _dirs, files in os.walk(path):
            for fn in files:
                try:
                    total += os.path.getsize(os.path.join(dirpath, fn))
                except OSError:
                    pass
        return total

    def _enforce_caps_locked(self, keep: str) -> None:
        """Evict oldest bundles past either cap.  ``keep`` (the bundle
        just written) survives even when it alone busts the byte cap —
        an empty black box is worse than an oversized one."""
        bundles = self.bundles()
        sizes = {b: self._du(b) for b in bundles}
        while bundles and (
            len(bundles) > self.max_bundles
            or sum(sizes[b] for b in bundles) > self.max_bytes
        ):
            victim = bundles[0] if bundles[0] != keep else (
                bundles[1] if len(bundles) > 1 else None
            )
            if victim is None:
                break
            shutil.rmtree(victim, ignore_errors=True)
            bundles.remove(victim)
