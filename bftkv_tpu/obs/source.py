"""Scrape sources for the fleet collector.

A *source* is one fleet member's observability surface.  The collector
only needs four operations, so both deployment shapes fit one duck
type:

- :meth:`info` — identity + shard seat + clique thresholds (the
  daemon's ``/info``, computed from ``quorum/wotqs.py`` state);
- :meth:`metrics` — the flat JSON metrics snapshot (includes the
  fixed-bucket histogram keys);
- :meth:`trace_export` — incremental span drain from a cursor
  (:meth:`bftkv_tpu.trace.Tracer.export`);
- :meth:`probe` — cheap liveness check, the f-budget's input.

:class:`HTTPSource` talks to a real daemon API over localhost/LAN.
:class:`LocalSource` wraps an in-process server (the chaos harness and
the loopback tests): liveness comes from the loopback transport's
registration state — ``crash()`` unregisters, so a crashed replica
fails the probe exactly like a dead daemon fails a scrape.  In-process
clusters share ONE metrics registry and tracer per process, so
process-wide feeds (metrics/trace) are attached to the collector once,
not per LocalSource (see ``FleetCollector(local_metrics=...)``).
"""

from __future__ import annotations

import json
import urllib.request

__all__ = ["HTTPSource", "LocalSource", "seat_document"]


def seat_document(qs, node_id: int) -> dict:
    """The seat half of an ``/info`` document — defaults merged with
    :meth:`bftkv_tpu.quorum.wotqs.WotQS.seat_info` when the quorum
    system supports it.  ONE implementation for every deployment
    shape: the daemon endpoint (cmd/bftkv.py) and the in-process
    :class:`LocalSource` both call this, so the HTTP and chaos planes
    cannot drift apart field by field."""
    out = {
        "shard": None,
        "shard_count": 1,
        "role": None,
        "clique": None,
        "region": None,
        "owned_buckets": 256,
    }
    seat_info = getattr(qs, "seat_info", None)
    if seat_info is not None:
        try:
            out.update(seat_info(node_id))
        except Exception:
            pass  # introspection must never take a surface down
    return out


class HTTPSource:
    """One daemon API endpoint (``host:port`` of ``bftkv --api``).

    ``PROBE_BY_SCRAPE``: the collector treats the metrics fetch itself
    as the liveness probe — a separate ``probe()`` round trip per
    member per scrape would just double the request load for no new
    information."""

    PROBE_BY_SCRAPE = True

    def __init__(self, base: str, name: str = "", timeout: float = 3.0):
        if "://" not in base:
            base = "http://" + base
        self.base = base.rstrip("/")
        self.name = name or base.split("://", 1)[1]
        self.timeout = timeout

    def _get_json(self, path: str):
        with urllib.request.urlopen(
            self.base + path, timeout=self.timeout
        ) as res:
            return json.loads(res.read())

    def info(self) -> dict:
        info = self._get_json("/info")
        if info.get("name"):
            self.name = info["name"]
        return info

    def metrics(self) -> dict:
        return self._get_json("/metrics?format=json")

    def trace_export(self, cursor: int) -> dict:
        return self._get_json(f"/trace?since={cursor}")

    def profile(self, seconds: float = 2.0) -> str:
        """On-demand collapsed-stack profile window from the daemon's
        ``/profile`` endpoint (``cmd.fleet --profile``; the request
        blocks for the window, so the timeout stretches to cover it)."""
        with urllib.request.urlopen(
            self.base + f"/profile?seconds={seconds:g}",
            timeout=self.timeout + seconds + 5.0,
        ) as res:
            return res.read().decode()

    def probe(self) -> bool:
        try:
            self._get_json("/info")
            return True
        except Exception:
            return False

    def __repr__(self) -> str:  # pragma: no cover
        return f"HTTPSource({self.name} @ {self.base})"


class LocalSource:
    """One in-process server (loopback transports).

    ``server_fn`` returns the CURRENT server object for this member —
    a callable, not a reference, because the chaos harness's
    crash-restart replaces the ``Server`` instance on the same storage
    (``ChaosCluster.restart``), and health must follow the member, not
    a dead object."""

    def __init__(self, name: str, server_fn):
        self.name = name
        self.server_fn = server_fn

    def info(self) -> dict:
        srv = self.server_fn()
        # A member that builds its own /info document (the edge
        # Gateway: role=gateway + cache stats) is the authority — a
        # seat document derived from its qs would misfile it as a
        # quorum principal.
        own = getattr(srv, "info", None)
        if callable(own):
            doc = dict(own())
            doc.setdefault("name", self.name)
            return doc
        g = srv.self_node
        out = {
            "name": self.name,
            "id": f"{g.get_self_id():016x}",
            "addr": getattr(g, "address", ""),
        }
        out.update(seat_document(srv.qs, g.get_self_id()))
        return out

    def metrics(self) -> dict:
        # One shared registry per process: per-member counters are not
        # attributable in-process.  The collector reads the process
        # registry once per scrape via its ``local_metrics`` feed.
        return {}

    def trace_export(self, cursor: int) -> dict:
        return {"cursor": cursor, "dropped": 0, "spans": [], "slow": []}

    def probe(self) -> bool:
        try:
            tr = self.server_fn().tr
        except Exception:
            return False
        addr = getattr(tr, "_addr", None)
        if addr is None:
            return False  # tr.stop() ran: the member is dark
        net = getattr(tr, "net", None)
        if net is not None:
            return net.servers.get(addr) is not None
        return True

    def __repr__(self) -> str:  # pragma: no cover
        return f"LocalSource({self.name})"
