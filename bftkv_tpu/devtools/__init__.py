"""Development-time correctness tooling that ships inside the package
(so deployments can arm it) but stays zero-overhead when disarmed:

- :mod:`bftkv_tpu.devtools.lockwatch` — the opt-in runtime lock
  sanitizer behind ``BFTKV_LOCKWATCH=1`` (DESIGN.md §16).

The static half of the correctness plane lives in ``tools/bftlint``.
"""
