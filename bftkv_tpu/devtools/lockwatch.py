"""Opt-in runtime lock sanitizer (``BFTKV_LOCKWATCH=1``).

The project's locking rules were enforced by prose until this module:
DESIGN.md said "I/O moved outside the store lock" (PR 4) and "the
``_DaemonPool`` nested-overflow deadlock" (PR 4) in words, and nothing
machine-checked either.  Lockwatch turns both into runtime checks:

- **Lock-order graph.**  Every lock created through :func:`named_lock`
  is a node named by its *class* (``storage.plain``, ``metrics``,
  ``transport.pool`` — one node per name, lockdep-style, so an
  ordering violation between any two instances of two classes is
  caught even when the two runs never touch the same instances).
  Acquiring B while holding A records the edge A→B with the first
  acquire site; a cycle in the directed graph is a potential deadlock
  (:func:`report` lists each cycle once).
- **Blocking calls under a watched lock.**  Arming patches a small set
  of blocking choke points (``builtins.open``, ``os.listdir``,
  ``os.fsync``, ``socket.create_connection``,
  ``http.client.HTTPConnection.request``/``getresponse``,
  ``time.sleep``); a patched call executed while the thread holds a
  lock whose name matches :data:`WATCHED_PREFIXES` (storage / metrics
  / route-table / quorum classes) is the PR 4 "I/O under the store
  lock" bug class and is recorded as a finding.

**Zero overhead disarmed** is a hard contract, like the failpoint
plane's: :func:`named_lock` returns a *plain* ``threading.Lock`` /
``RLock`` when the flag is off — no wrapper, no indirection, nothing
patched — so the steady-state hot path is bit-for-bit the pre-lockwatch
build (tests/test_lockwatch.py holds a perf-parity smoke over it).

Known-benign findings are waived in code, where the next reader needs
them: either a ``with lockwatch.waiver("reason"):`` region (suppresses
recording on this thread — e.g. PlainStorage's one-time index rebuild,
which must hold the lock across its first ``listdir``) or a declared
:func:`waive_order` pair for a benign A→B/B→A report.  Waivers carry
their reason into :func:`report` so the soak log shows WHAT was waived.

Wired into tier-1 via a conftest session gate and into the nightly
``nemesis`` soak (exit non-zero on any cycle or under-lock blocking
call); see DESIGN.md §16.
"""

from __future__ import annotations

import threading
from typing import Any

from bftkv_tpu import flags

__all__ = [
    "ARMED",
    "WATCHED_PREFIXES",
    "arm",
    "disarm",
    "enabled",
    "named_lock",
    "report",
    "reset",
    "waive_order",
    "waiver",
]

#: Lock-name prefixes whose holders must never block (the invariant
#: classes from PR 4/6/11: storage stores, the metrics registry, the
#: route table / quorum caches, the trust-graph generation guard).
WATCHED_PREFIXES = ("storage.", "metrics", "quorum.", "graph.")

#: Module-level arm flag, failpoint-style: cheap to read, and
#: :func:`named_lock` consults it once per lock CONSTRUCTION (not per
#: acquire), so disarmed cost is literally zero.
ARMED = False

_state_lock = threading.Lock()
#: (holder_name, acquired_name) -> first-seen acquire site "file:line".
_edges: dict[tuple[str, str], str] = {}
#: Waived directed orders with reasons.
_waived_orders: dict[tuple[str, str], str] = {}
#: Blocking-call findings: (lock_name, func, site) -> count.
_blocking: dict[tuple[str, str, str], int] = {}
_tls = threading.local()

_patched: list[tuple[Any, str, Any]] = []


def enabled() -> bool:
    return ARMED


def _held() -> list:
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
    return h


def _waiver_depth() -> int:
    return getattr(_tls, "waive", 0)


class waiver:
    """Suppress lockwatch recording on this thread inside the block.

    Use for a known-benign region, with the reason in the source:
    ``with lockwatch.waiver("first-use index rebuild holds the lock"):``
    """

    def __init__(self, reason: str):
        self.reason = reason

    def __enter__(self):
        _tls.waive = _waiver_depth() + 1
        return self

    def __exit__(self, *exc):
        _tls.waive = _waiver_depth() - 1
        return False


def _acquire_site() -> str:
    import sys

    # Caller of the lock proxy: skip lockwatch frames.
    f = sys._getframe(2)
    while f is not None and "lockwatch" in f.f_code.co_filename:
        f = f.f_back
    if f is None:  # pragma: no cover
        return "?"
    return f"{f.f_code.co_filename}:{f.f_lineno}"


def _note_acquired(name: str) -> None:
    held = _held()
    if _waiver_depth() == 0:
        for h in held:
            if h == name:
                continue  # reentrant same-class hold: not an order edge
            edge = (h, name)
            if edge not in _edges:
                site = _acquire_site()
                with _state_lock:
                    _edges.setdefault(edge, site)
    held.append(name)


def _note_released(name: str) -> None:
    held = _held()
    # Out-of-order release is legal; drop the most recent hold of name.
    for i in range(len(held) - 1, -1, -1):
        if held[i] == name:
            del held[i]
            return


class _WatchedLock:
    """Proxy recording acquisition order; duck-compatible with
    ``threading.Lock``/``RLock`` (incl. ``threading.Condition(lock)``,
    which only needs acquire/release and falls back to its own
    ``_is_owned`` emulation for foreign lock objects)."""

    __slots__ = ("_lock", "name")

    def __init__(self, name: str, *, rlock: bool = False):
        self._lock = threading.RLock() if rlock else threading.Lock()
        self.name = name

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            _note_acquired(self.name)
        return ok

    def release(self) -> None:
        self._lock.release()
        _note_released(self.name)

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:  # pragma: no cover
        return f"<lockwatch {self.name} {self._lock!r}>"


def named_lock(name: str, *, rlock: bool = False):
    """The project-wide lock seam: every ``threading.Lock()`` in
    ``bftkv_tpu/`` is created through here with a stable class name.
    Disarmed (the default) this returns the plain stdlib lock object —
    zero wrapper, zero overhead."""
    if not ARMED:
        return threading.RLock() if rlock else threading.Lock()
    return _WatchedLock(name, rlock=rlock)


# ---------------------------------------------------------------------------
# Blocking-call choke points (patched only while armed).
# ---------------------------------------------------------------------------


def _watched_holds() -> list:
    held = getattr(_tls, "held", None)
    if not held:
        return []
    return [
        h for h in held if any(h.startswith(p) for p in WATCHED_PREFIXES)
    ]


def _note_blocking(func: str) -> None:
    if _waiver_depth():
        return
    for h in _watched_holds():
        site = _acquire_site()
        key = (h, func, site)
        with _state_lock:
            _blocking[key] = _blocking.get(key, 0) + 1


def _wrap_callable(owner: Any, attr: str, label: str) -> None:
    orig = getattr(owner, attr)

    def wrapper(*a, **kw):
        _note_blocking(label)
        return orig(*a, **kw)

    wrapper.__name__ = getattr(orig, "__name__", attr)
    wrapper.__lockwatch_orig__ = orig
    setattr(owner, attr, wrapper)
    _patched.append((owner, attr, orig))


def _patch_blocking() -> None:
    import builtins
    import http.client
    import os
    import socket
    import time

    _wrap_callable(builtins, "open", "open")
    _wrap_callable(os, "listdir", "os.listdir")
    _wrap_callable(os, "fsync", "os.fsync")
    _wrap_callable(socket, "create_connection", "socket.connect")
    _wrap_callable(http.client.HTTPConnection, "request", "http.request")
    _wrap_callable(
        http.client.HTTPConnection, "getresponse", "http.response"
    )
    _wrap_callable(time, "sleep", "time.sleep")


def _unpatch_blocking() -> None:
    while _patched:
        owner, attr, orig = _patched.pop()
        setattr(owner, attr, orig)


# ---------------------------------------------------------------------------
# Lifecycle + reporting.
# ---------------------------------------------------------------------------


def arm() -> None:
    """Arm the sanitizer: locks created from now on through
    :func:`named_lock` are watched, and the blocking choke points are
    patched.  Locks created before arming stay plain (arm at process
    start — the ``BFTKV_LOCKWATCH=1`` path — to watch everything)."""
    global ARMED
    if ARMED:
        return
    reset()
    _patch_blocking()
    ARMED = True


def disarm() -> None:
    global ARMED
    ARMED = False
    _unpatch_blocking()


def reset() -> None:
    """Clear recorded edges/findings (waived orders persist — they are
    code-declared facts, not run state)."""
    with _state_lock:
        _edges.clear()
        _blocking.clear()


def waive_order(first: str, then: str, reason: str) -> None:
    """Declare the directed order ``first`` held while acquiring
    ``then`` as known-benign; edges matching it are excluded from
    cycle analysis and listed under ``waived`` in :func:`report`."""
    with _state_lock:
        _waived_orders[(first, then)] = reason


def _find_cycles(adj: dict[str, set]) -> list[list[str]]:
    """Each elementary cycle once (rooted at its smallest node)."""
    cycles: list[list[str]] = []
    seen: set = set()
    nodes = sorted(adj)
    for root in nodes:
        stack = [(root, [root])]
        while stack:
            node, path = stack.pop()
            for nxt in sorted(adj.get(node, ())):
                if nxt == root and len(path) > 1:
                    key = frozenset(path)
                    if key not in seen:
                        seen.add(key)
                        cycles.append(path + [root])
                elif nxt not in path and nxt > root:
                    stack.append((nxt, path + [nxt]))
        # Self-loops cannot occur: reentrant holds are filtered at
        # record time.
    return cycles


def report() -> dict:
    """Machine-readable findings:

    ``{"cycles": [[a, b, a], ...], "blocking": [{lock, func, site,
    count}], "edges": {...}, "waived": [...]}`` — the pytest gate and
    the nemesis soak fail on non-empty ``cycles`` or ``blocking``."""
    with _state_lock:
        edges = dict(_edges)
        blocking = dict(_blocking)
        waived = dict(_waived_orders)
    adj: dict[str, set] = {}
    waived_hits = []
    for (a, b), site in edges.items():
        if (a, b) in waived:
            waived_hits.append(
                {"order": [a, b], "site": site, "reason": waived[(a, b)]}
            )
            continue
        adj.setdefault(a, set()).add(b)
    return {
        "cycles": _find_cycles(adj),
        "blocking": [
            {"lock": lk, "func": fn, "site": site, "count": n}
            for (lk, fn, site), n in sorted(blocking.items())
        ],
        "edges": {f"{a}->{b}": site for (a, b), site in sorted(edges.items())},
        "waived": waived_hits,
    }


def fail_message() -> str | None:
    """None when clean; else a human-readable findings summary (the
    string the conftest gate asserts on and nemesis prints)."""
    rep = report()
    if not rep["cycles"] and not rep["blocking"]:
        return None
    lines = ["lockwatch findings:"]
    for cyc in rep["cycles"]:
        lines.append("  lock-order cycle: " + " -> ".join(cyc))
    for b in rep["blocking"]:
        lines.append(
            f"  blocking call under lock: {b['func']} while holding "
            f"{b['lock']} at {b['site']} (x{b['count']})"
        )
    return "\n".join(lines)


# Arm at import when the flag is set: lock construction happens at
# module import / object init all over the package, so the decision
# must be made before anything else imports.
if flags.enabled("BFTKV_LOCKWATCH"):  # pragma: no cover - env-dependent
    arm()
