"""Anti-entropy: Byzantine-safe replica state-sync.

The reference protocol repairs stale replicas only opportunistically —
a client pushes the winning packet back during a quorum read
(protocol/client.go:281-302) — so a replica that was down during a
write window stays stale until some client happens to read that exact
key through it.  This package is the explicit state-recovery plane
(the Thetacrypt lesson, PAPERS.md), kept OFF the hot path: background
digest exchange + record pull whose verification cost rides the
existing batched device pipeline.

- :mod:`bftkv_tpu.sync.digest` — prefix-bucketed rolling hashes over
  ``<variable, t, value-hash>`` triples, computed incrementally from
  storage (``keys()``/``versions()``/``read()`` contract);
- :mod:`bftkv_tpu.sync.daemon` — the :class:`SyncDaemon` round driver
  and :func:`admit_records`, the full local admission path every pulled
  record must survive (collective-signature sufficiency verified as one
  device batch, then timestamp/TOFU/equivocation checks);
- wire: ``SYNC_DIGEST`` / ``SYNC_PULL`` commands
  (:mod:`bftkv_tpu.transport`), codecs in :mod:`bftkv_tpu.packet`,
  handlers in :class:`bftkv_tpu.protocol.server.Server`.

Peers are never trusted: a Byzantine peer can waste bandwidth but can
never poison state, because admission is the same code path a client
write faces.
"""

from __future__ import annotations

from bftkv_tpu.sync.daemon import SyncDaemon, admit_records
from bftkv_tpu.sync.digest import DigestTree, bucket_of, record_hash

__all__ = [
    "SyncDaemon",
    "admit_records",
    "DigestTree",
    "bucket_of",
    "record_hash",
]
