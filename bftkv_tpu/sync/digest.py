"""Keyspace digest tree: prefix-bucketed rolling hashes over the
replica's completed records.

The tree summarizes what a replica would serve: for every variable, the
*latest completed* version (``ss`` present and completed — in-progress
sign records and bare auth records are invisible, exactly as they are to
a quorum read).  Each variable lands in one of 256 buckets by the first
byte of ``sha256(variable)``; a bucket's hash is the XOR-fold of its
record hashes ``sha256(len(x) | x | t | sha256(v))`` — XOR is
commutative, so bucket membership needs no ordering and a single
record's change re-derives from the bucket's variables alone.

Incrementality: the first build walks ``storage.keys()`` once; after
that, every server-side persist marks the written VARIABLE dirty and
the next digest request re-reads only the dirty variables — each
bucket hash is patched by XOR-ing the variable's cached old
contribution out and its fresh one in, so a digest round after N
changed records costs O(N) storage reads regardless of keyspace size
(the §19 log engine's bound; it holds for every backend).  The tree
caches one integer per variable, never record bytes — storage stays
the single source of truth, so a crash/restart simply rebuilds.

Two replicas with equal trees serve identical completed state; a
divergent bucket names the (at most 1/256th) slice of the keyspace to
pull.  The reference has no analog — its only repair plane is client
read-repair (protocol/client.go:281-302).

Sharding interplay: a digest bucket is exactly one *routing* bucket
(``quorum.wotqs.route_bucket`` uses the same ``sha256(x)[0]``), so
shard ownership partitions the tree cleanly.  The tree itself stays
shard-blind on purpose — it summarizes what the replica HAS, including
buckets a routing-generation change just took away, which is how a new
owner pulls migrated state (the old owner serves it; the pull filter
and the admission gate live on the *consuming* side, sync/daemon.py).
"""

from __future__ import annotations

import hashlib
import struct

from bftkv_tpu import packet as pkt
from bftkv_tpu.errors import ERR_NOT_FOUND

# Variables holding threshold-CA shares are replica-local secrets and
# never sync — the ONE sentinel the server defines, not a copy that
# could silently diverge from it.
from bftkv_tpu.protocol.server import HIDDEN_PREFIX
from bftkv_tpu.devtools.lockwatch import named_lock

__all__ = [
    "DigestTree",
    "bucket_of",
    "record_hash",
    "latest_completed",
    "HIDDEN_PREFIX",
]

_EMPTY = bytes(pkt.DIGEST_HASH_LEN)


def bucket_of(variable: bytes) -> int:
    return hashlib.sha256(variable).digest()[0]


def record_hash(variable: bytes, t: int, value: bytes | None) -> bytes:
    h = hashlib.sha256()
    h.update(struct.pack(">Q", len(variable)))
    h.update(variable)
    h.update(struct.pack(">Q", t))
    h.update(hashlib.sha256(value or b"").digest())
    return h.digest()


def latest_completed(
    storage, variable: bytes
) -> tuple[int, bytes, pkt.Packet] | None:
    """(t, raw record bytes, parsed packet) of the newest stored
    version whose collective signature is completed, or None.  Scans
    versions descending — the same walk the server read path does past
    in-progress sign records.  The parsed packet rides along so
    digest/admission callers never re-parse multi-MB records.

    TPA-protected records (stored ``auth`` params) are invisible to the
    sync plane entirely: the read path serves their values only behind
    a cryptographically verified auth proof, and the sync peer gate is
    weaker than that (keyring membership — which open Join enrollment
    can satisfy).  Excluding them from BOTH digest and pull keeps the
    trees consistent; protected variables keep the reference's
    read-repair-only recovery."""
    try:
        versions = sorted(storage.versions(variable), reverse=True)
    except Exception:
        return None
    for t in versions:
        try:
            raw = storage.read(variable, t)
        except ERR_NOT_FOUND:
            continue
        try:
            p = pkt.parse(raw)
        except Exception:
            # Undecodable stored bytes: the digest skips them —
            # hostile storage must not kill the sync round.
            continue
        if p.auth is not None:
            return None  # protected variable: not syncable at all
        if p.ss is not None and p.ss.completed:
            return t, raw, p
    return None


class DigestTree:
    """Per-storage digest with dirty-VARIABLE invalidation: a digest
    round costs O(records changed since the last round), not O(dirty
    buckets × bucket population)."""

    def __init__(self, storage):
        self.storage = storage
        self._lock = named_lock("sync.digest")
        self._vars: dict[int, set[bytes]] = {}
        #: variable -> its current XOR contribution to its bucket (as
        #: an int; 0 = contributes nothing).  The cache that buys
        #: O(changed): patching a bucket is old-out/new-in, no walk.
        self._contrib: dict[bytes, int] = {}
        self._hash_int: dict[int, int] = {}
        self._dirty: dict[int, set[bytes]] = {}
        self._built = False

    # -- write-path hook ---------------------------------------------------

    def mark(self, variable: bytes) -> None:
        """Invalidate the written variable (cheap dict ops only; called
        from every server persist).  Recording even before the first
        build means a write landing DURING the build's keyspace scan
        cannot be lost — the merge in :meth:`_ensure_built` keeps it."""
        if variable.startswith(HIDDEN_PREFIX):
            return
        b = bucket_of(variable)
        with self._lock:
            self._vars.setdefault(b, set()).add(variable)
            self._dirty.setdefault(b, set()).add(variable)

    # -- digest ------------------------------------------------------------

    def _ensure_built(self) -> None:
        """One-time keyspace enumeration, with the storage walk OUTSIDE
        the tree lock — ``mark()`` sits on the foreground write path
        and must never wait behind a 100k-variable listdir."""
        with self._lock:
            if self._built:
                return
        keys = self.storage.keys()
        with self._lock:
            if self._built:
                return  # another thread's scan won; marks kept us fresh
            for var in keys:
                if var.startswith(HIDDEN_PREFIX):
                    continue
                b = bucket_of(var)
                self._vars.setdefault(b, set()).add(var)
                self._dirty.setdefault(b, set()).add(var)
            self._built = True

    def buckets(self) -> dict[int, bytes]:
        """Non-empty bucket hashes, re-reading only DIRTY variables.

        The per-record storage reads happen OUTSIDE the tree lock:
        ``mark()`` sits on every server persist, so holding the lock
        through the reads would stall the foreground write path behind
        a background digest request.  A variable marked dirty again
        mid-recompute lands in the next round's dirty set and refreshes
        then — staleness is bounded by one round either way."""
        self._ensure_built()
        with self._lock:
            dirty = self._dirty
            self._dirty = {}
            todo = [
                (b, var) for b, vs in dirty.items() for var in sorted(vs)
            ]
        fresh: list[tuple[int, bytes, int]] = []
        for b, var in todo:
            rec = latest_completed(self.storage, var)
            if rec is None:
                new = 0
            else:
                t, _raw, p = rec
                new = int.from_bytes(record_hash(var, t, p.value), "big")
            fresh.append((b, var, new))
        with self._lock:
            for b, var, new in fresh:
                old = self._contrib.get(var, 0)
                if new == old:
                    continue
                acc = self._hash_int.get(b, 0) ^ old ^ new
                if acc:
                    self._hash_int[b] = acc
                else:
                    self._hash_int.pop(b, None)
                if new:
                    self._contrib[var] = new
                else:
                    self._contrib.pop(var, None)
            return {
                b: acc.to_bytes(pkt.DIGEST_HASH_LEN, "big")
                for b, acc in self._hash_int.items()
            }

    def bucket_variables(self, b: int) -> list[bytes]:
        """Variables currently assigned to bucket ``b`` (serving side
        of SYNC_PULL)."""
        self._ensure_built()
        with self._lock:
            return sorted(self._vars.get(b, ()))

    def root(self) -> bytes:
        """One hash over the whole tree (convergence checks/tests)."""
        h = hashlib.sha256()
        for b, digest in sorted(self.buckets().items()):
            h.update(bytes([b]))
            h.update(digest)
        return h.digest()

    def serialize(self) -> bytes:
        return pkt.serialize_digest(self.buckets())
