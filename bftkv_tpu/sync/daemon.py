"""Anti-entropy daemon: background replica convergence without trust.

A restarted, rejoined, or lagging replica converges by pulling from its
peers instead of waiting for a client to read the exact keys it missed
(the reference's only repair plane, protocol/client.go:281-302 — which
silently erodes the ``3f+1`` margin for any key nobody re-reads).

Each (jitter-scheduled) round:

1. ``SYNC_DIGEST`` to the peer set — a digest is ≤ 8 KB, so polling is
   the cheap half — and compare each peer's bucket hashes against the
   local :class:`~bftkv_tpu.sync.digest.DigestTree`;
2. ``SYNC_PULL`` the divergent buckets from up to ``f+1`` *distinct*
   divergent peers: with at most ``f`` Byzantine replicas, at least one
   pulled peer is honest, which is all liveness needs — safety needs
   none;
3. feed every pulled record through :func:`admit_records` — the FULL
   local admission path.

Admission re-runs exactly what the write handler runs: collective-
signature sufficiency against the local AUTH quorum and keyring (all
pulled signatures verify as ONE device batch through the installed
``ops.dispatch`` verify dispatcher via ``collective.verify_many``),
then timestamp monotonicity / equivocation / TOFU via the server's
``_write_storage_checks``.  A Byzantine peer can therefore waste
bandwidth but can never poison state: forged, replayed, cert-stripped,
or re-keyed records all die in admission with ``sync.rejected``
incremented and local state untouched.

Metrics: ``sync.rounds``, ``sync.pull.records`` (admitted),
``sync.rejected``, ``sync.pull.stale`` (honest-but-old), and
``sync.pull.verify_batch`` (device batch size per pull).
"""

from __future__ import annotations

import logging
import random
import threading
import time

from bftkv_tpu import packet as pkt
from bftkv_tpu import quorum as qm
from bftkv_tpu import trace
from bftkv_tpu import transport as tp
from bftkv_tpu.faults import failpoint as fp
from bftkv_tpu.metrics import registry as metrics
from bftkv_tpu.sync.digest import HIDDEN_PREFIX, latest_completed
from bftkv_tpu import flags

__all__ = ["SyncDaemon", "admit_records", "repair_enabled"]

log = logging.getLogger("bftkv_tpu.sync")


def repair_enabled() -> bool:
    """``BFTKV_REPAIR`` — the pending-residue repair plane (default
    on).  ``BFTKV_REPAIR_AFTER`` sets the grace window in seconds."""
    return flags.raw("BFTKV_REPAIR", "on").lower() not in (
        "off", "0", "false",
    )

#: Upper bounds on one pull response: record count AND total bytes.
#: The transport has already buffered the body by the time these apply
#: (that bound is transport-wide), so what they cap is the parse +
#: admission amplification a hostile peer can force per round.
MAX_PULL_RECORDS = 8192
#: Strictly above the worst case a conforming server can send (its
#: 32 MiB budget may be overshot by one ≤32 MiB record plus list
#: framing) — only a NON-conforming peer trips this, where discarding
#: is correct and cannot livelock convergence.
MAX_PULL_BYTES = 80 << 20


def admit_records(server, records: list[bytes]) -> dict:
    """Run pulled records through the full local admission path.

    Returns counters: ``admitted`` / ``rejected`` / ``stale``.  Never
    raises on record content — a hostile record is a counter bump, not
    a daemon crash.
    """
    stats = {"admitted": 0, "rejected": 0, "stale": 0}
    parsed: list[tuple[bytes, object, bytes] | None] = []
    jobs: list[tuple[bytes, object]] = []
    owns = getattr(server.qs, "owns", None)
    for raw in records[:MAX_PULL_RECORDS]:
        try:
            p = pkt.parse(raw)
            variable = p.variable or b""
            if variable.startswith(HIDDEN_PREFIX):
                raise ValueError("hidden variable")
            if owns is not None and not owns(variable):
                # Sharded namespace: records of foreign shards never
                # enter local state (the same gate the write handler
                # applies) — a peer cannot use the sync plane to park
                # another shard's history here.
                raise ValueError("wrong shard")
            if p.sig is None or p.ss is None or not p.ss.completed:
                raise ValueError("not a completed record")
            if p.auth is not None:
                # TPA-protected state never rides the sync plane
                # (sync/digest.py latest_completed explains why).
                raise ValueError("protected record")
            local = latest_completed(server.storage, variable)
            if local is not None:
                lt, _lraw, lp = local
                if lt > p.t:
                    stats["stale"] += 1  # honest-but-old: not Byzantine
                    parsed.append(None)
                    continue
                if lt == p.t and lp.value == p.value:
                    parsed.append(None)  # already converged on this key
                    continue
            tbss = pkt.tbss(raw)
        except Exception:
            stats["rejected"] += 1
            parsed.append(None)
            continue
        parsed.append((raw, p, tbss))
        jobs.append((tbss, p.ss))

    # ONE device batch for every pulled collective signature: verify_many
    # routes through the installed ops.dispatch verify dispatcher, so a
    # whole pull costs one kernel launch, not per-record host checks.
    if jobs:
        # Keyed to the OWNER quorum, exactly like the write handler:
        # every surviving record passed the owns() gate above, so they
        # all share this replica's shard and one keyed quorum covers
        # the batch.  The unkeyed quorum would accept a foreign
        # clique's threshold (is_sufficient is any-QC), letting a
        # Byzantine peer launder another shard's signatures through
        # the sync plane.
        first_var = next(
            e[1].variable or b"" for e in parsed if e is not None
        )
        qa = qm.choose_quorum_for(server.qs, first_var, qm.AUTH)
        metrics.observe("sync.pull.verify_batch", len(jobs))
        with trace.span(
            "server.verify_batch",
            attrs={"batch_size": len(jobs), "kind": "sync_pull"},
        ):
            verrs = server.crypt.collective.verify_many(
                jobs, qa, server.crypt.keyring
            )
        # Dual-epoch migration window (DESIGN.md §15): records the OLD
        # owner clique certified while it owned the bucket must be
        # admissible at the NEW owner — that pull IS the pre-copy.
        # Failures retry per-record against the dual quorum the route
        # table names for that record's bucket; outside a window
        # alt_quorums_for is empty and nothing changes.
        if any(e is not None for e in verrs):
            alt_of = getattr(server.qs, "alt_quorums_for", None)
            if alt_of is not None:
                live = [e for e in parsed if e is not None]
                for j, err in enumerate(verrs):
                    if err is None:
                        continue
                    raw_j, p_j, tbss_j = live[j]
                    for alt in alt_of(p_j.variable or b"", qm.AUTH):
                        try:
                            server.crypt.collective.verify(
                                tbss_j, p_j.ss, alt, server.crypt.keyring
                            )
                            verrs[j] = None
                            metrics.incr("sync.pull.dual_verified")
                            break
                        except Exception:
                            # Try the next dual-window quorum; verrs[j]
                            # stays set when none verifies.
                            continue
    else:
        verrs = []

    vi = 0
    persists: list[tuple[bytes, int, bytes]] = []
    seen_vars: set[bytes] = set()
    for entry in parsed:
        if entry is None:
            continue
        raw, p, _tbss = entry
        err = verrs[vi]
        vi += 1
        if err is not None:
            stats["rejected"] += 1
            continue
        variable = p.variable or b""
        if variable in seen_vars and persists:
            # One variable twice in a pull (hostile peers can): the
            # second record's admission gates must see the first's
            # stored state — flush the deferred batch first.
            server._persist_many(persists)
            persists = []
        seen_vars.add(variable)
        try:
            # Timestamp monotonicity, equivocation, and TOFU against the
            # locally stored record — the same checks ``_write`` runs.
            out = server._write_storage_checks(
                variable, p.value, p.t, p.sig, p.ss, raw
            )
        except Exception:
            stats["rejected"] += 1
            continue
        persists.append((variable, p.t, out))
        stats["admitted"] += 1
    # ONE durability barrier for the whole admitted pull — the §19
    # group-commit seam (falls back to per-record writes elsewhere).
    server._persist_many(persists)

    metrics.incr("sync.pull.records", stats["admitted"])
    metrics.incr("sync.rejected", stats["rejected"])
    metrics.incr("sync.pull.stale", stats["stale"])
    return stats


class SyncDaemon:
    """Background anti-entropy driver for one server."""

    #: Bound on the pending-residue scan per repair round.
    REPAIR_SCAN_MAX = 4096

    def __init__(
        self,
        server,
        interval: float = 30.0,
        jitter: float = 0.5,
        rng: random.Random | None = None,
        repair_after: float | None = None,
    ):
        self.server = server
        self.interval = interval
        self.jitter = jitter
        if repair_after is None:
            repair_after = float(
                flags.raw("BFTKV_REPAIR_AFTER", "5") or 5
            )
        #: Grace window: a pending record younger than this (measured
        #: from when THIS daemon first observed it — storage records
        #: carry no wall clock) is presumed to be a live write's tail
        #: still in flight and left alone.
        self.repair_after = repair_after
        self._rng = rng or random.Random()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # variable -> monotonic time first seen pending.
        self._pending_seen: dict[bytes, float] = {}
        # (variable, t) this daemon demoted: never-certifiable residue
        # is tried once, surfaced once, and not retried every round.
        self._demoted: set[tuple[bytes, int]] = set()
        self._backfills = None  # lazy _BackfillCoalescer(server)
        # Windowed-scan cursor (None = start of keyspace) and the
        # variables seen pending so far in the current scan CYCLE —
        # watch-list eviction is only sound once a cycle covered the
        # whole keyspace.
        self._scan_cursor: bytes | None = None
        self._cycle_live: set[bytes] = set()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "SyncDaemon":
        if self._thread is None:
            self._stop = threading.Event()  # a prior stop() left it set
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="bftkv-sync"
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _loop(self) -> None:
        # Local ref: a wedged thread abandoned by a timed-out stop()
        # must keep honoring the OLD event, never a successor start()'s
        # (the dispatch workers' discipline, ops/dispatch.py).
        stop = self._stop
        while not stop.is_set():
            # Jittered so a fleet restarted together does not stampede.
            delay = self.interval * (
                1.0 + self.jitter * (2.0 * self._rng.random() - 1.0)
            )
            if stop.wait(max(0.1, delay)):
                return
            try:
                self.run_round()
            except Exception:
                log.exception("anti-entropy round failed")
            try:
                self.repair_round()
            except Exception:
                log.exception("repair round failed")

    # -- one round ---------------------------------------------------------

    def _peers(self) -> list:
        peers = [
            n
            for n in self.server.self_node.get_peers()
            if getattr(n, "address", "") and getattr(n, "active", True)
        ]
        # Sharded namespace: only same-shard peers can hold records we
        # own (every replica applies the wrong-shard admission gate), so
        # polling foreign shards is pure waste.  Peers without a shard
        # assignment are kept — fail open, admission stays the shield.
        qs = getattr(self.server, "qs", None)
        idx_of = getattr(qs, "shard_index_of", None)
        if idx_of is None:
            return peers
        mine = idx_of(self.server.self_node.get_self_id())
        if mine is None:
            return peers
        # Epoched migration (DESIGN.md §15): during a pre-copy / dual
        # window the new owner of a moving bucket must pull from the
        # OLD owner's shard (and the old owner from the new, so its
        # in-flight tails converge before it goes inert) — the dual
        # shard set widens the poll set for exactly that window.
        keep = {mine} | getattr(qs, "dual_pull_shards", lambda: set())()
        return [
            n for n in peers if idx_of(n.id) is None or idx_of(n.id) in keep
        ]

    def _ask(self, cmd: int, peer, payload: bytes) -> bytes | None:
        """Point-to-point request over the encrypted transport;
        ``tp.multicast`` blocks until the single callback ran."""
        box: dict = {}

        def cb(res: tp.MulticastResponse) -> bool:
            box["res"] = res
            return True

        self.server.tr.multicast(cmd, [peer], payload, cb)
        res = box.get("res")
        if res is None or res.err is not None:
            return None
        return res.data

    def run_round(self) -> dict:
        """One anti-entropy round: digest-poll the peer set (cheap — a
        digest is ≤ 8 KB), then pull divergent buckets from up to
        ``f+1`` distinct divergent peers.  With at most ``f`` Byzantine
        replicas among them, at least one pulled peer is honest, so a
        round reaches every record some honest divergent peer serves;
        safety never depends on the count — admission re-verifies
        everything.  Returns aggregate counters."""
        if fp.ARMED:
            # ``sync.round`` failpoint: the round aborts before any
            # digest poll — a daemon wedged or killed mid-schedule.
            act = fp.fire(
                "sync.round",
                node=getattr(self.server.self_node, "name", ""),
            )
            if act is not None and act.kind == "abort":
                metrics.incr("sync.aborted")
                return {"peers": 0, "pulled_peers": 0, "admitted": 0,
                        "rejected": 0, "stale": 0, "aborted": 1}
        with trace.span("sync.round") as sp:
            stats = self._run_round_inner()
            sp.attrs.update(stats)
        return stats

    def _run_round_inner(self) -> dict:
        stats = {"peers": 0, "pulled_peers": 0, "admitted": 0,
                 "rejected": 0, "stale": 0}
        peers = self._peers()
        if not peers:
            return stats
        # get_peers() excludes self, so the replica count is
        # len(peers)+1 and the fault bound is f = (n-1)//3 = peers//3 —
        # computing it off the peer list directly would undercount by
        # one for every n = 3f+1 cluster and let a single Byzantine
        # peer absorb the whole round's pull budget.
        f = len(peers) // 3
        local = self.server._sync_tree()
        # Shard-aware digest comparison: only buckets this replica's
        # shard owns are worth pulling — a foreign shard's buckets
        # diverge forever by design (their records die in our
        # admission), and without the filter every round would re-pull
        # them just to reject them.
        owned = None
        get_owned = getattr(getattr(self.server, "qs", None),
                            "owned_buckets", None)
        if get_owned is not None:
            owned = get_owned()
        divergent_peers: list[tuple[object, list[int]]] = []
        for peer in peers:
            stats["peers"] += 1
            data = self._ask(tp.SYNC_DIGEST, peer, b"")
            if data is None:
                continue
            try:
                theirs = pkt.parse_digest(data)
            except Exception:
                metrics.incr("sync.rejected")
                stats["rejected"] += 1
                continue
            mine = local.buckets()
            divergent = [
                b
                for b, h in sorted(theirs.items())
                if mine.get(b) != h and (owned is None or b in owned)
            ]
            if divergent:
                divergent_peers.append((peer, divergent))
        self._rng.shuffle(divergent_peers)
        for peer, divergent in divergent_peers[: f + 1]:
            stats["pulled_peers"] += 1
            raw = self._ask(
                tp.SYNC_PULL, peer, pkt.serialize_bucket_ids(divergent)
            )
            if raw is None:
                continue
            if len(raw) > MAX_PULL_BYTES:
                metrics.incr("sync.rejected")
                stats["rejected"] += 1
                continue
            try:
                records = pkt.parse_list(raw)
            except Exception:
                metrics.incr("sync.rejected")
                stats["rejected"] += 1
                continue
            got = admit_records(self.server, records)
            for k in ("admitted", "rejected", "stale"):
                stats[k] += got[k]
        metrics.incr("sync.rounds")
        return stats

    # -- pending-residue repair (DESIGN.md §13.1) --------------------------
    #
    # A writer that crashes after the 2f+1 commit but before its async
    # back-fill leaves commit-PENDING residue on the quorum: a record
    # the plane has accepted but that carries no verifying collective
    # signature yet.  Before this plane, such a record was certified
    # only if some client happened to READ the variable (certify-on-
    # read) — anti-entropy never ships pending records, so convergence
    # depended on client liveness.  The repair round closes that: each
    # replica scans ITS OWN store for pending residue past the grace
    # window, runs the same idempotent SIGN round the read path uses to
    # mint a verifying collective signature, back-fills the certified
    # record plane-wide through the back-fill coalescer, and demotes
    # residue that cannot reach ``suff`` (``sync.repair.demoted``
    # feeds the fleet feed's ``tail_starved`` anomaly).  Safety: the
    # SIGN round re-collects shares for the EXACT stored <x, v, t,
    # sig> (honest replicas already signed it — re-signing the exact
    # stored pair is the one re-sign the equivocation rule permits),
    # and the back-fill rides the same certified-beats-residue /
    # upgrade-in-place admission rules every write already obeys, so
    # concurrent repairs from several replicas are idempotent races.

    def repair_once(self) -> dict:
        """One repair pass ignoring the grace window (tests, CLI)."""
        return self.repair_round(force=True)

    def repair_round(self, *, force: bool = False) -> dict:
        stats = {"scanned": 0, "certified": 0, "demoted": 0,
                 "waiting": 0, "retrying": 0}
        if not repair_enabled():
            return stats
        srv = self.server
        now = time.monotonic()
        # Windowed scan: at most REPAIR_SCAN_MAX keys read+parsed per
        # round, resuming where the last round stopped — a big fully-
        # certified store costs one bounded slice per round, never a
        # full sweep.
        pending, self._scan_cursor = srv.pending_variables(
            limit=self.REPAIR_SCAN_MAX,
            after=self._scan_cursor,
            scan_window=self.REPAIR_SCAN_MAX,
        )
        cycle_done = self._scan_cursor is None
        # Cursor lag for the capacity plane: 1.0 = this round's scan
        # window came back full (the cursor cannot cover the keyspace
        # in one round — repair is running behind residue accrual);
        # 0.0 = the cycle completed inside the window.
        metrics.gauge(
            "sync.repair.cursor_lag",
            0.0 if cycle_done else min(
                1.0, len(pending) / max(1, self.REPAIR_SCAN_MAX)
            ),
        )
        metrics.gauge("sync.repair.backlog", float(len(pending)))
        due: list[tuple[bytes, int, bytes, object]] = []
        for variable, t, raw, p in pending:
            self._cycle_live.add(variable)
            if (variable, t) in self._demoted:
                continue
            stats["scanned"] += 1
            first = self._pending_seen.setdefault(variable, now)
            if force or now - first >= self.repair_after:
                due.append((variable, t, raw, p))
            else:
                stats["waiting"] += 1
        # Residue that resolved on its own (back-fill landed, a newer
        # write certified) leaves the watch list — judged only once a
        # scan CYCLE has covered the whole keyspace (absence from one
        # window just means "not in this window").
        if cycle_done:
            for v in list(self._pending_seen):
                if v not in self._cycle_live:
                    del self._pending_seen[v]
            self._cycle_live = set()
        if not due:
            return stats
        certified: list[tuple[bytes, bytes]] = []
        with trace.span("sync.repair", attrs={"due": len(due)}):
            for variable, t, raw, p in due:
                verdict, rec = self._certify_record(variable, t, raw, p)
                if verdict == "certified":
                    stats["certified"] += 1
                    metrics.incr("sync.repair.certified")
                    certified.append((variable, rec))
                    self._pending_seen.pop(variable, None)
                elif verdict == "refused":
                    # The quorum ANSWERED and would not endorse the
                    # record (bad writer signature, conflicting value):
                    # only misbehavior can produce this — surface it
                    # exactly once and stop burning quorum signs on it.
                    # The record stays gated client-side (resolve
                    # demotes uncertifiable pending buckets), so
                    # nothing unbacked is ever served off it.
                    stats["demoted"] += 1
                    metrics.incr("sync.repair.demoted")
                    self._demoted.add((variable, t))
                    self._pending_seen.pop(variable, None)
                    log.warning(
                        "repair: demoted uncertifiable pending "
                        "residue %r (t=%d)", variable, t,
                    )
                else:
                    # Quorum UNREACHABLE (timeouts, partition, circuit
                    # open): that is an outage, not a verdict — a
                    # transient blip must not permanently demote
                    # healthy residue or raise a false misbehavior
                    # anomaly.  Leave the watch entry; the next round
                    # retries after the partition heals.
                    stats["retrying"] += 1
                    metrics.incr("sync.repair.retry")
        if certified:
            self._backfill(certified)
        return stats

    #: Transport-level failure messages: an outage, never a verdict.
    _OUTAGE_ERRS = frozenset(
        e.message
        for e in (
            tp.ERR_UNREACHABLE,
            tp.ERR_RPC_TIMEOUT,
            tp.ERR_SERVER_ERROR,
            tp.ERR_PEER_OPEN,
        )
    )

    def _certify_record(
        self, variable: bytes, t: int, raw: bytes, p
    ):
        """Mint a verifying collective signature for one pending record
        via the idempotent SIGN round (the certify-on-read recipe, run
        from the replica's seat) and persist the certified bytes
        locally through the full write-path checks.  Returns
        ``("certified", record)`` on success, ``("refused", None)``
        when some quorum member ANSWERED and would not endorse the
        record (demotable misbehavior), or ``("outage", None)`` when
        the round failed on transport errors alone — a partition or
        timeout blip that the caller must retry, never demote."""
        srv = self.server
        # Plain AUTH: the owner clique from the replica's own seat (the
        # client-shaped AUTH|PEER view is empty on a server — same
        # quorum flags admit_records verifies with).
        qa = qm.choose_quorum_for(srv.qs, variable, qm.AUTH)
        # Residue whose bucket migrated AWAY (epoch flip): the owner is
        # now a foreign clique, and this seat's trust weight into it is
        # zero — the low-weight veto would zero ``suff`` and the round
        # could never combine.  Judge sufficiency in verify view: the
        # shares are still cryptographically checked against the owner
        # clique the shared certificate graph defines (DESIGN.md §15).
        qs = srv.qs
        shard_of = getattr(qs, "shard_of", None)
        my_shard = getattr(qs, "my_shard", None)
        qfs = getattr(qs, "quorum_for_shard", None)
        if shard_of is not None and my_shard is not None and qfs is not None:
            owner = shard_of(variable)
            mine = my_shard()
            if owner is not None and mine is not None and owner != mine:
                qa = qfs(owner, qm.AUTH, True)
        req = pkt.serialize(variable, p.value, t, p.sig, None)
        tbss = pkt.tbss(raw)
        ss = None
        done_flag = [False]
        failure: list = []
        refused = [0]

        def cb(res: tp.MulticastResponse) -> bool:
            nonlocal ss
            if res.err is None and res.data is not None:
                try:
                    share = pkt.parse_signature(res.data)
                    ss, done = srv.crypt.collective.combine(
                        ss, share, qa, srv.crypt.keyring
                    )
                    done_flag[0] = done
                    return done
                except Exception:
                    # An unusable share IS an answer from a reachable
                    # peer — the refusal class, not an outage.
                    refused[0] += 1
            elif (
                getattr(res.err, "message", None)
                not in self._OUTAGE_ERRS
            ):
                # Interned protocol error (equivocation, invalid
                # signature, bad timestamp, ...): the peer answered
                # and said no.
                refused[0] += 1
            failure.append(res.peer)
            return qa.reject(failure)

        with trace.span("sync.repair.sign", attrs={"t": t}):
            srv.tr.multicast(tp.SIGN, qa.nodes(), req, cb)
            try:
                srv.crypt.collective.verify(
                    tbss, ss, qa, srv.crypt.keyring
                )
            except Exception:
                return ("refused" if refused[0] else "outage", None)
        ss.completed = True
        rec = pkt.serialize(variable, p.value, t, p.sig, ss)
        try:
            # Local admission first (timestamp / equivocation / TOFU /
            # upgrade-in-place — exactly what the write handler runs);
            # local state may have legitimately moved past this record,
            # in which case the no-op answer is the correct one.
            out = srv._write_storage_checks(
                variable, p.value, t, p.sig, ss, rec
            )
            if out is not None:
                srv._persist(variable, t, out)
        except Exception:
            log.exception("repair: local admission of %r failed", variable)
        return "certified", rec

    def recertify_buckets(self, buckets: set[int] | None = None) -> dict:
        """Migration drain sweep (DESIGN.md §15.3): re-certify every
        completed record in ``buckets`` (default: all owned) whose
        collective signature does NOT verify against this replica's
        owner quorum — i.e. records pre-copied from the clique that
        owned the bucket in an earlier epoch.  The same idempotent SIGN
        round the repair plane uses mints a fresh owner-clique
        signature over the EXACT stored ``<x, v, t, sig>`` (the one
        re-sign the equivocation rule permits), so after the sweep the
        bucket's history verifies against its new owner alone and the
        dual-epoch verification window can close."""
        from bftkv_tpu.quorum.wotqs import route_bucket

        srv = self.server
        stats = {"scanned": 0, "recertified": 0, "failed": 0}
        owned = None
        get_owned = getattr(srv.qs, "owned_buckets", None)
        if get_owned is not None:
            owned = get_owned()
        certified: list[tuple[bytes, bytes]] = []
        for variable in sorted(srv.storage.keys()):
            if variable.startswith(HIDDEN_PREFIX):
                continue
            b = route_bucket(variable)
            if buckets is not None and b not in buckets:
                continue
            if owned is not None and b not in owned:
                continue
            rec = latest_completed(srv.storage, variable)
            if rec is None:
                continue
            t, raw, p = rec
            if p.auth is not None:
                continue  # TPA-protected: needs the client's proof
            stats["scanned"] += 1
            qa = qm.choose_quorum_for(srv.qs, variable, qm.AUTH)
            try:
                srv.crypt.collective.verify(
                    pkt.tbss(raw), p.ss, qa, srv.crypt.keyring
                )
                continue  # already vouched for by the owner quorum
            except Exception:
                pass  # not certified as-is: certify-or-demote below
            verdict, out = self._certify_record(variable, t, raw, p)
            if verdict == "certified":
                stats["recertified"] += 1
                metrics.incr("sync.recertified")
                certified.append((variable, out))
            else:
                stats["failed"] += 1
                metrics.incr("sync.recertify_failed")
        if certified:
            self._backfill(certified)
        return stats

    def _backfill(self, items: list[tuple[bytes, bytes]]) -> None:
        """Push certified records plane-wide through the same back-fill
        coalescer the collapsed write's async tail uses (one batched
        single-shard BATCH_WRITE round per group); bounded-blocking so
        a repair round leaves a settled plane behind it."""
        from bftkv_tpu.protocol.client import _BackfillCoalescer

        if self._backfills is None:
            # The coalescer only touches .qs and .tr — a Server
            # satisfies that surface exactly like a Client.
            self._backfills = _BackfillCoalescer(self.server)
        for variable, rec in items:
            self._backfills.submit(variable, rec)
        self._backfills.drain(timeout=15.0)
        # The coalescer covers the WRITE plane; the sign quorum's
        # members hold the pending residue too (and the repair SIGN
        # round just re-marked it in-progress there), so the certified
        # bytes must reach them as well or a clique member outside the
        # write plane would keep residue until some client read it.
        # Grouped per owning shard, exactly like the coalescer: a
        # BATCH_WRITE frame is verified against ONE owner quorum
        # server-side (a sharded replica's store only holds owned
        # variables, so this is one group in practice — the grouping
        # guards duck-typed quorum systems without that invariant).
        srv = self.server
        shard_of = getattr(srv.qs, "shard_of", None)
        groups: dict[object, list[tuple[bytes, bytes]]] = {}
        for variable, rec in items:
            key = shard_of(variable) if shard_of is not None else None
            groups.setdefault(key, []).append((variable, rec))
        for group in groups.values():
            qa = qm.choose_quorum_for(srv.qs, group[0][0], qm.AUTH)
            with trace.span(
                "sync.repair.backfill", attrs={"batch": len(group)}
            ):
                srv.tr.multicast(
                    tp.BATCH_WRITE,
                    qa.nodes(),
                    pkt.serialize_list([rec for _v, rec in group]),
                    None,
                )
