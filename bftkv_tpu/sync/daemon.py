"""Anti-entropy daemon: background replica convergence without trust.

A restarted, rejoined, or lagging replica converges by pulling from its
peers instead of waiting for a client to read the exact keys it missed
(the reference's only repair plane, protocol/client.go:281-302 — which
silently erodes the ``3f+1`` margin for any key nobody re-reads).

Each (jitter-scheduled) round:

1. ``SYNC_DIGEST`` to the peer set — a digest is ≤ 8 KB, so polling is
   the cheap half — and compare each peer's bucket hashes against the
   local :class:`~bftkv_tpu.sync.digest.DigestTree`;
2. ``SYNC_PULL`` the divergent buckets from up to ``f+1`` *distinct*
   divergent peers: with at most ``f`` Byzantine replicas, at least one
   pulled peer is honest, which is all liveness needs — safety needs
   none;
3. feed every pulled record through :func:`admit_records` — the FULL
   local admission path.

Admission re-runs exactly what the write handler runs: collective-
signature sufficiency against the local AUTH quorum and keyring (all
pulled signatures verify as ONE device batch through the installed
``ops.dispatch`` verify dispatcher via ``collective.verify_many``),
then timestamp monotonicity / equivocation / TOFU via the server's
``_write_storage_checks``.  A Byzantine peer can therefore waste
bandwidth but can never poison state: forged, replayed, cert-stripped,
or re-keyed records all die in admission with ``sync.rejected``
incremented and local state untouched.

Metrics: ``sync.rounds``, ``sync.pull.records`` (admitted),
``sync.rejected``, ``sync.pull.stale`` (honest-but-old), and
``sync.pull.verify_batch`` (device batch size per pull).
"""

from __future__ import annotations

import logging
import random
import threading

from bftkv_tpu import packet as pkt
from bftkv_tpu import quorum as qm
from bftkv_tpu import trace
from bftkv_tpu import transport as tp
from bftkv_tpu.faults import failpoint as fp
from bftkv_tpu.metrics import registry as metrics
from bftkv_tpu.sync.digest import HIDDEN_PREFIX, latest_completed

__all__ = ["SyncDaemon", "admit_records"]

log = logging.getLogger("bftkv_tpu.sync")

#: Upper bounds on one pull response: record count AND total bytes.
#: The transport has already buffered the body by the time these apply
#: (that bound is transport-wide), so what they cap is the parse +
#: admission amplification a hostile peer can force per round.
MAX_PULL_RECORDS = 8192
#: Strictly above the worst case a conforming server can send (its
#: 32 MiB budget may be overshot by one ≤32 MiB record plus list
#: framing) — only a NON-conforming peer trips this, where discarding
#: is correct and cannot livelock convergence.
MAX_PULL_BYTES = 80 << 20


def admit_records(server, records: list[bytes]) -> dict:
    """Run pulled records through the full local admission path.

    Returns counters: ``admitted`` / ``rejected`` / ``stale``.  Never
    raises on record content — a hostile record is a counter bump, not
    a daemon crash.
    """
    stats = {"admitted": 0, "rejected": 0, "stale": 0}
    parsed: list[tuple[bytes, object, bytes] | None] = []
    jobs: list[tuple[bytes, object]] = []
    owns = getattr(server.qs, "owns", None)
    for raw in records[:MAX_PULL_RECORDS]:
        try:
            p = pkt.parse(raw)
            variable = p.variable or b""
            if variable.startswith(HIDDEN_PREFIX):
                raise ValueError("hidden variable")
            if owns is not None and not owns(variable):
                # Sharded namespace: records of foreign shards never
                # enter local state (the same gate the write handler
                # applies) — a peer cannot use the sync plane to park
                # another shard's history here.
                raise ValueError("wrong shard")
            if p.sig is None or p.ss is None or not p.ss.completed:
                raise ValueError("not a completed record")
            if p.auth is not None:
                # TPA-protected state never rides the sync plane
                # (sync/digest.py latest_completed explains why).
                raise ValueError("protected record")
            local = latest_completed(server.storage, variable)
            if local is not None:
                lt, _lraw, lp = local
                if lt > p.t:
                    stats["stale"] += 1  # honest-but-old: not Byzantine
                    parsed.append(None)
                    continue
                if lt == p.t and lp.value == p.value:
                    parsed.append(None)  # already converged on this key
                    continue
            tbss = pkt.tbss(raw)
        except Exception:
            stats["rejected"] += 1
            parsed.append(None)
            continue
        parsed.append((raw, p, tbss))
        jobs.append((tbss, p.ss))

    # ONE device batch for every pulled collective signature: verify_many
    # routes through the installed ops.dispatch verify dispatcher, so a
    # whole pull costs one kernel launch, not per-record host checks.
    if jobs:
        # Keyed to the OWNER quorum, exactly like the write handler:
        # every surviving record passed the owns() gate above, so they
        # all share this replica's shard and one keyed quorum covers
        # the batch.  The unkeyed quorum would accept a foreign
        # clique's threshold (is_sufficient is any-QC), letting a
        # Byzantine peer launder another shard's signatures through
        # the sync plane.
        first_var = next(
            e[1].variable or b"" for e in parsed if e is not None
        )
        qa = qm.choose_quorum_for(server.qs, first_var, qm.AUTH)
        metrics.observe("sync.pull.verify_batch", len(jobs))
        with trace.span(
            "server.verify_batch",
            attrs={"batch_size": len(jobs), "kind": "sync_pull"},
        ):
            verrs = server.crypt.collective.verify_many(
                jobs, qa, server.crypt.keyring
            )
    else:
        verrs = []

    vi = 0
    for entry in parsed:
        if entry is None:
            continue
        raw, p, _tbss = entry
        err = verrs[vi]
        vi += 1
        if err is not None:
            stats["rejected"] += 1
            continue
        variable = p.variable or b""
        try:
            # Timestamp monotonicity, equivocation, and TOFU against the
            # locally stored record — the same checks ``_write`` runs.
            out = server._write_storage_checks(
                variable, p.value, p.t, p.sig, p.ss, raw
            )
        except Exception:
            stats["rejected"] += 1
            continue
        server._persist(variable, p.t, out)
        stats["admitted"] += 1

    metrics.incr("sync.pull.records", stats["admitted"])
    metrics.incr("sync.rejected", stats["rejected"])
    metrics.incr("sync.pull.stale", stats["stale"])
    return stats


class SyncDaemon:
    """Background anti-entropy driver for one server."""

    def __init__(
        self,
        server,
        interval: float = 30.0,
        jitter: float = 0.5,
        rng: random.Random | None = None,
    ):
        self.server = server
        self.interval = interval
        self.jitter = jitter
        self._rng = rng or random.Random()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "SyncDaemon":
        if self._thread is None:
            self._stop = threading.Event()  # a prior stop() left it set
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="bftkv-sync"
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _loop(self) -> None:
        # Local ref: a wedged thread abandoned by a timed-out stop()
        # must keep honoring the OLD event, never a successor start()'s
        # (the dispatch workers' discipline, ops/dispatch.py).
        stop = self._stop
        while not stop.is_set():
            # Jittered so a fleet restarted together does not stampede.
            delay = self.interval * (
                1.0 + self.jitter * (2.0 * self._rng.random() - 1.0)
            )
            if stop.wait(max(0.1, delay)):
                return
            try:
                self.run_round()
            except Exception:
                log.exception("anti-entropy round failed")

    # -- one round ---------------------------------------------------------

    def _peers(self) -> list:
        peers = [
            n
            for n in self.server.self_node.get_peers()
            if getattr(n, "address", "") and getattr(n, "active", True)
        ]
        # Sharded namespace: only same-shard peers can hold records we
        # own (every replica applies the wrong-shard admission gate), so
        # polling foreign shards is pure waste.  Peers without a shard
        # assignment are kept — fail open, admission stays the shield.
        qs = getattr(self.server, "qs", None)
        idx_of = getattr(qs, "shard_index_of", None)
        if idx_of is None:
            return peers
        mine = idx_of(self.server.self_node.get_self_id())
        if mine is None:
            return peers
        return [
            n for n in peers if idx_of(n.id) is None or idx_of(n.id) == mine
        ]

    def _ask(self, cmd: int, peer, payload: bytes) -> bytes | None:
        """Point-to-point request over the encrypted transport;
        ``tp.multicast`` blocks until the single callback ran."""
        box: dict = {}

        def cb(res: tp.MulticastResponse) -> bool:
            box["res"] = res
            return True

        self.server.tr.multicast(cmd, [peer], payload, cb)
        res = box.get("res")
        if res is None or res.err is not None:
            return None
        return res.data

    def run_round(self) -> dict:
        """One anti-entropy round: digest-poll the peer set (cheap — a
        digest is ≤ 8 KB), then pull divergent buckets from up to
        ``f+1`` distinct divergent peers.  With at most ``f`` Byzantine
        replicas among them, at least one pulled peer is honest, so a
        round reaches every record some honest divergent peer serves;
        safety never depends on the count — admission re-verifies
        everything.  Returns aggregate counters."""
        if fp.ARMED:
            # ``sync.round`` failpoint: the round aborts before any
            # digest poll — a daemon wedged or killed mid-schedule.
            act = fp.fire(
                "sync.round",
                node=getattr(self.server.self_node, "name", ""),
            )
            if act is not None and act.kind == "abort":
                metrics.incr("sync.aborted")
                return {"peers": 0, "pulled_peers": 0, "admitted": 0,
                        "rejected": 0, "stale": 0, "aborted": 1}
        with trace.span("sync.round") as sp:
            stats = self._run_round_inner()
            sp.attrs.update(stats)
        return stats

    def _run_round_inner(self) -> dict:
        stats = {"peers": 0, "pulled_peers": 0, "admitted": 0,
                 "rejected": 0, "stale": 0}
        peers = self._peers()
        if not peers:
            return stats
        # get_peers() excludes self, so the replica count is
        # len(peers)+1 and the fault bound is f = (n-1)//3 = peers//3 —
        # computing it off the peer list directly would undercount by
        # one for every n = 3f+1 cluster and let a single Byzantine
        # peer absorb the whole round's pull budget.
        f = len(peers) // 3
        local = self.server._sync_tree()
        # Shard-aware digest comparison: only buckets this replica's
        # shard owns are worth pulling — a foreign shard's buckets
        # diverge forever by design (their records die in our
        # admission), and without the filter every round would re-pull
        # them just to reject them.
        owned = None
        get_owned = getattr(getattr(self.server, "qs", None),
                            "owned_buckets", None)
        if get_owned is not None:
            owned = get_owned()
        divergent_peers: list[tuple[object, list[int]]] = []
        for peer in peers:
            stats["peers"] += 1
            data = self._ask(tp.SYNC_DIGEST, peer, b"")
            if data is None:
                continue
            try:
                theirs = pkt.parse_digest(data)
            except Exception:
                metrics.incr("sync.rejected")
                stats["rejected"] += 1
                continue
            mine = local.buckets()
            divergent = [
                b
                for b, h in sorted(theirs.items())
                if mine.get(b) != h and (owned is None or b in owned)
            ]
            if divergent:
                divergent_peers.append((peer, divergent))
        self._rng.shuffle(divergent_peers)
        for peer, divergent in divergent_peers[: f + 1]:
            stats["pulled_peers"] += 1
            raw = self._ask(
                tp.SYNC_PULL, peer, pkt.serialize_bucket_ids(divergent)
            )
            if raw is None:
                continue
            if len(raw) > MAX_PULL_BYTES:
                metrics.incr("sync.rejected")
                stats["rejected"] += 1
                continue
            try:
                records = pkt.parse_list(raw)
            except Exception:
                metrics.incr("sync.rejected")
                stats["rejected"] += 1
                continue
            got = admit_records(self.server, records)
            for k in ("admitted", "rejected", "stale"):
                stats[k] += got[k]
        metrics.incr("sync.rounds")
        return stats
