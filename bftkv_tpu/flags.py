"""Declarative registry of every ``BFTKV_*`` environment flag.

The framework grew ~50 tuning and kill-switch flags across ten PRs,
each read ad hoc via ``os.environ.get`` next to the code it steers —
and the documentation drifted to cover a third of them.  This module
is the single source of truth: every flag is declared ONCE here with
its default, value kind and one doc line, and

- every runtime read goes through the seam below (:func:`raw`,
  :func:`get`, :func:`enabled`, :func:`get_int`, :func:`get_float`) —
  reading an undeclared ``BFTKV_*`` name raises immediately, so a new
  flag cannot ship undocumented;
- the README "Environment flags" table is GENERATED from this registry
  (``python -m bftkv_tpu.flags --readme``) and ``tools/bftlint``
  diff-checks it, so the docs cannot drift again;
- ``tools/bftlint``'s ``env-flag`` rule statically rejects any direct
  ``os.environ`` read of a ``BFTKV_*`` literal outside this module.

The seam deliberately does NOT cache: flags keep their original
read-at-call-site (often import-time) timing, so test monkeypatching
and per-process overrides behave exactly as before.

Value kinds: ``switch`` flags use the project-wide convention — any
value whose lowercase form is not ``off``/``0``/``false`` counts as
on (:func:`enabled`); ``str``/``int``/``float`` flags parse their raw
value at the call site's discretion.  A default of ``None`` means
"unset": the call site supplies a context-dependent fallback (the
``doc`` line says what that is).
"""

from __future__ import annotations

import os
from typing import NamedTuple

__all__ = [
    "Flag",
    "FLAGS",
    "declared",
    "enabled",
    "get",
    "get_float",
    "get_int",
    "raw",
    "readme_table",
]


class Flag(NamedTuple):
    name: str
    default: str | None  # None = unset (site-specific fallback)
    kind: str  # "switch" | "str" | "int" | "float"
    doc: str
    section: str


FLAGS: dict[str, Flag] = {}


def _flag(name: str, default: str | None, kind: str, doc: str) -> None:
    assert name.startswith("BFTKV_") and name not in FLAGS, name
    FLAGS[name] = Flag(name, default, kind, doc, _section)


_section = ""


def _begin(section: str) -> None:
    global _section
    _section = section


# ---------------------------------------------------------------------------
# The registry.  Grouped by subsystem; order is the README table order.
# ---------------------------------------------------------------------------

_begin("Write path & protocol")
_flag("BFTKV_PIGGYBACK", "on", "switch",
      "Round-collapsed writes: one WRITE_SIGN fan-out with signature "
      "shares riding the acks; `off` restores classic time/sign/write "
      "rounds (DESIGN.md §12).")
_flag("BFTKV_PRESESSION", "on", "switch",
      "Background session pump + per-client timestamp leases (skips "
      "the TIME round on steady-state writes).")
_flag("BFTKV_SIGN_FANOUT", "staged", "str",
      "`staged` asks a minimal sufficient prefix first and expands on "
      "shortfall; `full` restores the ask-everyone fan-out.")
_flag("BFTKV_WRITE_PIPELINE", "2", "int",
      "write_many: chunk write-rounds in flight behind the caller's "
      "time+sign rounds (1 disables pipelining).")
_flag("BFTKV_WRITE_CHUNK", "256", "int",
      "write_many chunk floor — batches at or below this stay "
      "monolithic so device launches amortize.")

_begin("Recovery & self-healing")
_flag("BFTKV_REPAIR", "on", "switch",
      "Pending-residue repair plane: each replica certifies-or-demotes "
      "its own commit-pending residue (DESIGN.md §13).")
_flag("BFTKV_REPAIR_AFTER", "5", "float",
      "Grace window in seconds before a pending record becomes "
      "repair-eligible.")
_flag("BFTKV_ADAPTIVE_TIMEOUT", "on", "switch",
      "Per-peer EWMA/p99 RPC deadlines in place of the fixed "
      "BFTKV_RPC_TIMEOUT (which stays the ceiling).")
_flag("BFTKV_ADAPTIVE_FLOOR", "1.0", "float",
      "Lower bound in seconds on an adaptive per-peer deadline.")
_flag("BFTKV_HEDGE", "on", "switch",
      "Hedged staged fan-outs: a stalled wave launches the next wave "
      "early after a p99-derived delay.")
_flag("BFTKV_HEDGE_MIN", "0.02", "float",
      "Lower clamp in seconds on the hedge delay.")
_flag("BFTKV_HEDGE_CAP", "0.5", "float",
      "Upper clamp in seconds on the hedge delay.")

_begin("Topology & sharding")
_flag("BFTKV_AUTOPILOT", "on", "switch",
      "Automatic topology decisions (hot-shard split, clique "
      "retirement); `off` disables deciding only — forced executes "
      "stay available (DESIGN.md §15).")
_flag("BFTKV_SHARD", "auto", "str",
      "Device-mesh sharding of sign/verify flushes over local "
      "accelerator devices; `off` pins single-device.")

_begin("Transport")
_flag("BFTKV_RPC_TIMEOUT", None, "float",
      "Fixed per-RPC response deadline ceiling in seconds (unset: "
      "falls back to BFTKV_HTTP_TIMEOUT, then 10).")
_flag("BFTKV_HTTP_TIMEOUT", None, "float",
      "Legacy alias for BFTKV_RPC_TIMEOUT, read only when that is "
      "unset.")
_flag("BFTKV_RPC_RETRIES", "0", "int",
      "Bounded jittered-backoff retries on transient transport errors "
      "(0 disables).")
_flag("BFTKV_RPC_BACKOFF", "0.05", "float",
      "Base backoff in seconds between transport retries.")
_flag("BFTKV_PEER_CB", "", "switch",
      "Per-peer circuit breaker in multicast (`1` enables; default "
      "off).")
_flag("BFTKV_PEER_CB_THRESHOLD", "3", "int",
      "Consecutive failures before a peer's breaker opens.")
_flag("BFTKV_PEER_CB_OPEN_SECS", "5", "float",
      "Seconds an open breaker skips a peer before the half-open "
      "probe.")
_flag("BFTKV_HTTP_POOL", "4", "int",
      "Idle keep-alive connections kept per (host, port).")
_flag("BFTKV_FANOUT_WORKERS", "256", "int",
      "Bound on the shared multicast fan-out worker pool.")
_flag("BFTKV_INLINE_FANOUT", "auto", "str",
      "`auto` runs loopback multicast inline when calibration says "
      "all-host; `off`/`on` force the threaded/inline path.")

_begin("Multi-region WAN")
_flag("BFTKV_REGION", None, "str",
      "This process's own region label, overriding the installed "
      "region map (a gateway box pinned to its serving region; "
      "unset: the identity's label from the universe's regions "
      "file).")
_flag("BFTKV_REGION_RANK", "on", "switch",
      "Locality-aware quorum staging: staged waves order candidates "
      "same-region-first (then by RTT matrix distance) so the minimal "
      "sufficient prefix is the near one and cross-region members are "
      "hedges, not the first ask.  Never changes which sets satisfy "
      "is_threshold/is_sufficient (DESIGN.md §21).")
_flag("BFTKV_REGION_LEASE_S", "0", "float",
      "Gateway freshness lease in seconds: while the last sync-"
      "invalidation round completed this recently, TTL-expired cache "
      "entries may still be served same-region (staleness bounded by "
      "lease + poll interval; 0 disables — DESIGN.md §21).")
_flag("BFTKV_WAN_RTT_MATRIX", None, "str",
      "Named geo-topology (wan2, wan3) or raw ms spec (e.g. "
      "20/80/150) compiled onto the link plane as quiet background "
      "delay rules — the deterministic WAN environment for benches "
      "and chaos soaks.")
_flag("BFTKV_WAN_JITTER", "0", "float",
      "Fractional jitter on WAN link delays: each one-way delay "
      "stretches uniformly (seeded per-rule draw) up to "
      "delay x (1 + jitter).")

_begin("Crypto & verification")
_flag("BFTKV_VERIFY_CACHE", "1", "switch",
      "Process-global verified-signature memo (`0` disables).")
_flag("BFTKV_VERIFY_CACHE_MAX", "65536", "int",
      "Bound on the verified-signature memo (entries).")
_flag("BFTKV_NATIVE_MODEXP", "auto", "str",
      "GIL-free Montgomery CRT modexp via native/montmodexp.c; `off` "
      "falls back to pow().")
_flag("BFTKV_NATIVE_CODEC", "auto", "str",
      "Native packet codec built on import; `off` keeps the pure-"
      "Python codec.")
_flag("BFTKV_OS_RNG", "", "switch",
      "`1` restores os.urandom for every secret draw (default: "
      "per-thread SHA-256 hash-DRBG reseeded from os.urandom).")
_flag("BFTKV_SIGN_BACKEND", "rns", "str",
      "RSA sign backend: `rns` windowed modexp (default), `bigint`, "
      "`host`.")
_flag("BFTKV_VERIFY_BACKEND", "rns", "str",
      "RSA verify backend: `rns` (default), `bigint`, `host`.")
_flag("BFTKV_HOST_SIGN_THRESHOLD", None, "int",
      "Batch size below which signs stay on host (unset: measured "
      "crossover from dispatcher calibration).")
_flag("BFTKV_HOST_VERIFY_THRESHOLD", None, "int",
      "Batch size below which verifies stay on host (unset: measured "
      "crossover from dispatcher calibration).")
_flag("BFTKV_EC_BACKEND", "auto", "str",
      "EC scalar-mul backend: `auto`, `device`, `host`.")
_flag("BFTKV_EC_SIGN_THRESHOLD", None, "int",
      "EC sign host/device crossover batch size (unset: built-in "
      "crossover constant).")
_flag("BFTKV_EC_VERIFY_THRESHOLD", None, "int",
      "EC verify host/device crossover batch size (unset: built-in "
      "crossover constant).")

_begin("Shared crypto sidecar")
_flag("BFTKV_SIDECAR_SIGN", "on", "switch",
      "Clients remote their RSA signing to the shared sidecar when the "
      "channel can carry keys (unix socket or HMAC secret); `off` keeps "
      "signing in-process (verification still remotes).")
_flag("BFTKV_SIDECAR_SPOT_RATE", "0.05", "float",
      "Fraction of remote verify batches whose verdicts are re-checked "
      "locally on one sampled item; a mismatch opens the sidecar "
      "breaker and raises the sidecar_dishonest anomaly (DESIGN.md "
      "§17.3).")
_flag("BFTKV_SIDECAR_BREAKER", "30", "float",
      "Seconds the sidecar breaker skips the service after a transport "
      "failure or a dishonest result before retrying.")
_flag("BFTKV_SIDECAR_MAX_INFLIGHT", "4", "int",
      "Sidecar admission: crypto batches served concurrently; more "
      "wait, then shed (sidecar.shed).")
_flag("BFTKV_SIDECAR_MAX_QUEUE", "64", "int",
      "Sidecar admission: batches allowed to WAIT for a service slot "
      "before instant shedding.")
_flag("BFTKV_SIDECAR_MAX_WAIT", "0.5", "float",
      "Sidecar admission: longest a batch may wait for a service slot "
      "before it is shed.")
_flag("BFTKV_SIDECAR_MAX_KEYS", "64", "int",
      "Sign-key handles one sidecar connection may register (bounds "
      "hostile registration floods).")

_begin("Device kernels & dispatch")
_flag("BFTKV_DISPATCH_CALIBRATE", "1", "switch",
      "Install-time host-vs-device crossover calibration (`0` "
      "disables; CPU backends then still pin always-host).")
_flag("BFTKV_DISPATCH_PIPELINE", None, "int",
      "Flushes in flight at once in the batching dispatcher (unset: "
      "backend-dependent default).")
_flag("BFTKV_DISPATCH_ASYNC", "on", "switch",
      "Async mega-batch dispatch: flush workers hand non-blocking "
      "device launches to a completion-drain thread, so flush N+1's "
      "host assembly overlaps flush N's device execution; `off` "
      "restores fully synchronous flushes (the pre-r11 behavior).")
_flag("BFTKV_DISPATCH_CROSSOVER", None, "int",
      "Operator override for the host/device verify crossover batch "
      "size (0 or negative pins always-host; unset: measured by "
      "dispatch calibration and re-measured online from launch RTTs).")
_flag("BFTKV_DISPATCH_RECAL_S", "60", "float",
      "Sidecar online-recalibration period in seconds: the boot-time "
      "crossover pin is re-measured from observed launch RTTs, so an "
      "attached accelerator engages without a restart (0 disables).")
_flag("BFTKV_DISPATCH_DEVBUF", "on", "switch",
      "Persistent per-limb-width staging buffer rings for device "
      "launches: flushes write batches into pre-allocated slot arrays "
      "(pad rows broadcast, never re-converted); `off` re-allocates "
      "per launch.")
_flag("BFTKV_DISPATCH_DEVBUF_RING", "4", "int",
      "Slots per width-class buffer ring; with every slot in flight "
      "the next flush allocates fresh arrays (devbuf.overflow) "
      "instead of blocking behind the device.")
_flag("BFTKV_TPU_MIN_MODEXP_BATCH", "4", "int",
      "Smallest batch worth a device modexp launch.")
_flag("BFTKV_RNS_POW_BACKEND", "auto", "str",
      "`pallas` forces the Pallas RNS pow kernel, `xla` the lowered "
      "one; `auto` proves Pallas on TPU first.")
_flag("BFTKV_RNS_VERIFY_BACKEND", "auto", "str",
      "Same switch for the RNS verify kernel.")
_flag("BFTKV_PALLAS_TILE_POW", "256", "int",
      "Pallas pow kernel batch tile (power of two ≥ 8).")
_flag("BFTKV_PALLAS_TILE_VERIFY", "128", "int",
      "Pallas verify kernel batch tile (power of two ≥ 8).")
_flag("BFTKV_COMPILE_CACHE", None, "str",
      "XLA compile-cache directory (unset: ~/.cache/jax_bftkv; empty "
      "value disables).")

_begin("Storage")
_flag("BFTKV_PLAIN_FSYNC", None, "switch",
      "Per-write fsync pair (file + directory) in PlainStorage; "
      "unset: library off / daemon on (durability is a deployment "
      "policy).")
_flag("BFTKV_PLAIN_CACHE", "1024", "int",
      "PlainStorage write-through record cache (entries; 0 disables).")
_flag("BFTKV_STORAGE", None, "str",
      "Default `--storage` engine for the daemon/cluster CLIs "
      "(plain|log|native|mem; unset: plain for the daemon, log for "
      "run_cluster).")
_flag("BFTKV_LOG_SEGMENT_MB", "64", "int",
      "LogStorage segment size: the active segment seals past this "
      "and becomes a shippable snapshot unit (DESIGN.md §19).")
_flag("BFTKV_LOG_GROUP_COMMIT_MS", "2", "float",
      "LogStorage group-commit linger: how long the fsync leader "
      "waits for concurrent writers to join its barrier (0 = fsync "
      "immediately, still shared by the losers of the leader race).")
_flag("BFTKV_LOG_COMPACT_TRIGGER", "0.5", "float",
      "LogStorage background compaction trigger: sealed dead-byte "
      "ratio past which a compaction pass starts (0 disables).")
_flag("BFTKV_LOG_COMPACT_MBPS", None, "float",
      "Compaction IO governor: sustained copy-rate cap in MB/s "
      "(token-bucket sleep between record copies; unset/0 = "
      "ungoverned).  Throttle time surfaces as compact_io saturation "
      "in the capacity plane.")

_begin("Observability & tooling")
_flag("BFTKV_TRACE", "on", "switch",
      "Trace-id/span plane; `off` disables tracing entirely.")
_flag("BFTKV_SLOW_TRACE_SECONDS", "1.0", "float",
      "Slow-trace threshold: requests above it land in the slow ring "
      "and the one-JSON-line slow log.")
_flag("BFTKV_LOCKWATCH", "", "switch",
      "Opt-in runtime lock sanitizer: records the lock acquisition-"
      "order graph, reports lock-order cycles and blocking calls "
      "under storage/metrics/route locks (DESIGN.md §16).")
_flag("BFTKV_PROFILE", "", "switch",
      "Opt-in continuous wall-clock sampling profiler (collapsed "
      "flamegraph stacks served on /profile; DESIGN.md §18).  Off = "
      "no sampler thread, zero overhead.")
_flag("BFTKV_PROFILE_HZ", "67", "int",
      "Sampling rate of the continuous profiler (prime default so the "
      "comb never phase-locks to periodic work).")
_flag("BFTKV_SLO_WRITE_P99", None, "float",
      "Write-latency SLO in seconds: a shard whose per-scrape write "
      "p99 exceeds it for BFTKV_SLO_BURN_SCRAPES consecutive scrapes "
      "raises the slo_burn anomaly (unset: disabled).")
_flag("BFTKV_SLO_BURN_SCRAPES", "3", "int",
      "Consecutive breaching scrapes before slo_burn fires — the "
      "hysteresis that keeps one slow scrape from paging anyone.")
_flag("BFTKV_FLIGHT_RECORDER", "", "switch",
      "Arm the flight recorder in the chaos nemesis: every fault "
      "window must yield exactly one black-box bundle naming the "
      "detected anomaly, enforced via the nemesis exit code.")
_flag("BFTKV_RECORDER_DIR", None, "str",
      "Flight-recorder bundle directory (unset: <tmpdir>/"
      "bftkv-blackbox).")
_flag("BFTKV_RECORDER_MIN_INTERVAL", "5", "float",
      "Seconds within which anomaly events coalesce into (amend) the "
      "previous bundle instead of minting a new one — the flapping-"
      "anomaly disk bound.")
_flag("BFTKV_RECORDER_MAX_MB", "64", "int",
      "Total on-disk cap across flight-recorder bundles; oldest "
      "bundles are evicted first.")
_flag("BFTKV_SAT_THRESHOLD", "0.8", "float",
      "Capacity plane: per-resource saturation at or above this for "
      "BFTKV_SAT_SCRAPES consecutive traffic-bearing scrapes raises "
      "the resource_saturated anomaly (0 disables).")
_flag("BFTKV_SAT_SCRAPES", "3", "int",
      "Consecutive saturated scrapes before resource_saturated fires "
      "— same hysteresis contract as slo_burn (one episode, one "
      "anomaly; a clean scrape re-arms).")
_flag("BFTKV_SAT_WAIT_REF", "0.25", "float",
      "Capacity plane: queue-wait p99 (seconds) that maps to "
      "saturation 1.0 for wait-derived resources (admission, "
      "dispatch; the log commit path uses max(4x linger, this)).")
_flag("BFTKV_GIL_SAMPLER", "1", "switch",
      "GIL-pressure estimate (runnable-thread gauge) riding the "
      "profiler tick; costs nothing while the profiler is disarmed.")

_begin("Workload engine")
_flag("BFTKV_WORKLOAD", None, "str",
      "Workload spec `preset[,k=v,...]` (bftkv_tpu/workload/spec.py) "
      "for spec-shaped traffic: the chaos nemesis `--workload` default "
      "(unset: coverage traffic only).")
_flag("BFTKV_WORKLOAD_SEED", None, "int",
      "Seed override for workload-driven bench sections; one seed "
      "replays one op stream bit-for-bit (unset: section default).")
_flag("BFTKV_WORKLOAD_RATE", None, "float",
      "Offered-load override in ops/s for bench cluster_workload and "
      "cluster_shards (unset: section defaults).")
_flag("BFTKV_WORKLOAD_DURATION", None, "float",
      "Per-preset schedule duration override in seconds for bench "
      "cluster_workload (unset: section default).")
_flag("BFTKV_WORKLOAD_PROCS", None, "int",
      "Worker-process count for the multi-process driver pair in bench "
      "cluster_workload (unset: 2).")

# ---------------------------------------------------------------------------
# The read seam.
# ---------------------------------------------------------------------------


def _check(name: str) -> Flag:
    f = FLAGS.get(name)
    if f is None:
        raise KeyError(
            f"undeclared BFTKV flag {name!r}: declare it in "
            "bftkv_tpu/flags.py (default + doc line) before reading it"
        )
    return f


def declared() -> dict[str, Flag]:
    """Name → :class:`Flag` for every declared flag (insertion order)."""
    return dict(FLAGS)


def raw(name: str, default: str | None = None) -> str | None:
    """The raw environment value, or ``default`` when unset.

    This is the compatibility seam: it keeps each call site's exact
    historical semantics (site-specific defaults, ``== "1"`` vs
    ``!= "0"`` comparisons) while enforcing that the name is declared.
    New call sites should prefer the typed helpers below."""
    _check(name)
    v = os.environ.get(name)
    return default if v is None else v


def get(name: str) -> str | None:
    """Environment value, falling back to the registry default."""
    f = _check(name)
    v = os.environ.get(name)
    return f.default if v is None else v


def enabled(name: str, default: str | None = None) -> bool:
    """Project-wide switch semantics, exactly as every historical
    switch site implemented them: a SET value is on unless it
    lowercases to ``off``/``0``/``false`` (so an explicitly-set empty
    string counts as on, matching the established
    ``.lower() not in ("off", "0", "false")`` convention).  An UNSET
    flag falls back to the registry default, where empty/``None``
    means off (a default-off switch like ``BFTKV_LOCKWATCH``)."""
    f = _check(name)
    v = os.environ.get(name)
    if v is None:
        v = default if default is not None else (f.default or "")
        if v == "":
            return False
    return v.lower() not in ("off", "0", "false")


def get_int(name: str, default: int | None = None) -> int | None:
    f = _check(name)
    v = os.environ.get(name)
    if v is None or v == "":
        if default is not None:
            return default
        return int(f.default) if f.default is not None else None
    return int(v)


def get_float(name: str, default: float | None = None) -> float | None:
    f = _check(name)
    v = os.environ.get(name)
    if v is None or v == "":
        if default is not None:
            return default
        return float(f.default) if f.default is not None else None
    return float(v)


# ---------------------------------------------------------------------------
# README table generation (diff-checked by tools/bftlint).
# ---------------------------------------------------------------------------

README_BEGIN = (
    "<!-- flags-table:begin (generated by "
    "python -m bftkv_tpu.flags --readme; do not edit) -->"
)
README_END = "<!-- flags-table:end -->"


def readme_table() -> str:
    """The generated README section between the flags-table markers."""
    lines = [README_BEGIN, ""]
    section = None
    for f in FLAGS.values():
        if f.section != section:
            section = f.section
            lines.append(f"**{section}**")
            lines.append("")
            lines.append("| Flag | Default | Meaning |")
            lines.append("| --- | --- | --- |")
        default = "_(unset)_" if f.default is None else f"`{f.default}`"
        if f.default == "":
            default = "_(off)_"
        doc = " ".join(f.doc.split())
        lines.append(f"| `{f.name}` | {default} | {doc} |")
    lines.append("")
    lines.append(README_END)
    # Blank line between a table's last row and the next section header.
    out: list[str] = []
    for ln in lines:
        if ln.startswith("**") and out and out[-1].startswith("|"):
            out.append("")
        out.append(ln)
    return "\n".join(out)


def main(argv: list[str] | None = None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m bftkv_tpu.flags",
        description="BFTKV_* environment-flag registry",
    )
    p.add_argument(
        "--readme", action="store_true",
        help="print the generated README flags section",
    )
    args = p.parse_args(argv)
    if args.readme:
        print(readme_table())
    else:
        for f in FLAGS.values():
            d = "(unset)" if f.default is None else repr(f.default)
            print(f"{f.name:32s} {f.kind:7s} default={d}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
