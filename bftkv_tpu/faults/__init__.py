"""Deterministic fault injection: failpoints, chaos nemesis, safety checker.

The paper's whole claim is tolerating ``f`` Byzantine replicas out of
``3f+1``, but a claim is only as strong as the adversary that tested it.
This package is that adversary, in three layers:

- :mod:`bftkv_tpu.faults.failpoint` — a **seeded, deterministic
  failpoint registry**.  Named hook points are woven into the transport
  fan-out (drop / delay / duplicate / corrupt, per-link), server
  admission (error reply, crash, Byzantine handler override), storage
  (I/O error, torn write), the batching dispatcher (flush stall), the
  timestamp path (clock skew), and the anti-entropy daemon (round
  abort).  Disarmed, every hook is a single module-bool test — the
  production path pays nothing.  Armed, every probabilistic decision is
  a counter-indexed hash of one seed, so a fault schedule replays
  identically run to run.
- :mod:`bftkv_tpu.faults.nemesis` — timed chaos schedules against an
  in-process loopback cluster: healing link-matrix partitions,
  crash-restart onto the same storage (anti-entropy must converge the
  replica back), clock skew, and Byzantine modes (collusion, stale
  replay) expressed as failpoint programs instead of subclasses.
  ``python -m bftkv_tpu.faults.nemesis --seed 7`` runs one seeded round.
- :mod:`bftkv_tpu.faults.checker` — a history recorder plus the
  invariants every chaos run must keep: write-once variables never
  change, per-variable timestamps are monotonic at honest replicas,
  every successful read is backed by a sufficient collective signature,
  and no two conflicting values both gather ``2f+1`` acks.

Byzantine handler programs live in :mod:`bftkv_tpu.faults.byzantine`;
``tests/mal_utils.py`` keeps its subclass API as a shim over them, so
hand-written Byzantine tests and chaos runs share one mechanism.
"""

from bftkv_tpu.faults.failpoint import (
    Action,
    FaultEvent,
    FaultRegistry,
    Rule,
    arm,
    disarm,
    fire,
    registry,
)

__all__ = [
    "Action",
    "FaultEvent",
    "FaultRegistry",
    "Rule",
    "arm",
    "disarm",
    "fire",
    "registry",
    "default_chaos_program",
]


def default_chaos_program(reg: FaultRegistry) -> list:
    """The light background chaos a daemon arms under ``--chaos-seed``:
    seeded transport delays and rare drops plus occasional anti-entropy
    round aborts.  Deliberately inside the ``f`` budget — a fleet under
    this program must stay fully correct, only slower."""
    return [
        reg.add(
            "transport.send", "delay",
            prob=0.10, seconds=0.01, max_seconds=0.05,
            rule_id="default:delay",
        ),
        reg.add("transport.send", "drop", prob=0.02, rule_id="default:drop"),
        reg.add("sync.round", "abort", prob=0.10, rule_id="default:abort"),
    ]
