"""Chaos safety checker: history recorder + BFT invariants.

The nemesis runs faults; this module decides whether the run *meant*
anything.  A :class:`HistoryRecorder` collects two event streams while
chaos runs:

- **client ops** — the harness records every write / write-once / read
  outcome observed by honest clients;
- **replica persists** — each replica's storage is wrapped in a
  :class:`RecordingStorage` that notes every stored protocol record
  (variable, t, value, completed?) per node.  Observation lives in the
  harness wrapper, not in a core hook: the store under test runs
  unmodified.

After the run :class:`SafetyChecker` evaluates the paper's safety
contract over the whole history plus the replicas' final state:

1. **Write-once immutability** — a variable committed with
   ``write_once`` never reads back as anything else, and no honest
   replica ever persists a different completed value at ``t = 2^64-1``.
2. **Timestamp monotonicity at honest replicas** — the sequence of
   completed records an honest replica persists for one variable never
   goes back in time (Byzantine replicas are exempt: they may store
   anything, the point is that it must not matter).
3. **Read integrity** — every successful read's value is backed by a
   record carrying a *sufficient collective signature* that actually
   verifies against an honest replica's quorum and keyring.  A value
   no sign quorum endorsed appearing at a reader is the smoking gun of
   a safety violation, whatever path it took.
4. **No conflicting commits** — no two different values at the same
   ``(variable, t)`` are each persisted by ``2f+1`` distinct replicas:
   two such sets would both intersect every quorum in an honest
   replica that acked both, which the equivocation checks forbid.

Liveness is deliberately NOT checked: during a partition, failing
writes is the *correct* behavior.  Failures are recorded (the nemesis
reports them) but only safety violations fail a run.
"""

from __future__ import annotations

from typing import Iterable

from bftkv_tpu import packet as pkt
from bftkv_tpu import quorum as qm
from bftkv_tpu.protocol import MAX_UINT64
from bftkv_tpu.sync.digest import HIDDEN_PREFIX
from bftkv_tpu.devtools.lockwatch import named_lock

__all__ = [
    "Event",
    "HistoryRecorder",
    "RecordingStorage",
    "SafetyChecker",
]


class Event:
    """One history entry; ``kind`` ∈ {persist, write_ok, write_once_ok,
    write_fail, read_ok, read_fail}."""

    __slots__ = ("seq", "kind", "fields")

    def __init__(self, seq: int, kind: str, fields: dict):
        self.seq = seq
        self.kind = kind
        self.fields = fields

    def __getattr__(self, name):
        try:
            return self.fields[name]
        except KeyError:
            raise AttributeError(name) from None

    def __repr__(self) -> str:  # pragma: no cover
        return f"Event({self.seq}, {self.kind}, {self.fields})"


class HistoryRecorder:
    """Thread-safe append-only history; one global sequence."""

    def __init__(self):
        self._lock = named_lock("faults.checker")
        self._events: list[Event] = []
        self._seq = 0

    def record(self, kind: str, **fields) -> None:
        with self._lock:
            self._seq += 1
            self._events.append(Event(self._seq, kind, fields))

    def events(self, kind: str | None = None) -> list[Event]:
        with self._lock:
            evs = list(self._events)
        if kind is None:
            return evs
        return [e for e in evs if e.kind == kind]

    # -- harness conveniences --------------------------------------------

    def write_ok(self, client: str, variable: bytes, value: bytes) -> None:
        self.record("write_ok", client=client, variable=variable, value=value)

    def write_once_ok(
        self, client: str, variable: bytes, value: bytes
    ) -> None:
        self.record(
            "write_once_ok", client=client, variable=variable, value=value
        )

    def write_fail(
        self, client: str, variable: bytes, err: Exception
    ) -> None:
        self.record("write_fail", client=client, variable=variable, err=err)

    def read_ok(
        self, client: str, variable: bytes, value: bytes | None
    ) -> None:
        self.record("read_ok", client=client, variable=variable, value=value)

    def read_fail(self, client: str, variable: bytes, err: Exception) -> None:
        self.record("read_fail", client=client, variable=variable, err=err)


class RecordingStorage:
    """Storage wrapper: delegates everything, records protocol persists.

    Wrap a replica's storage *before* the server touches it (the sync
    digest tree captures ``server.storage`` lazily).  Survives
    crash-restart by construction — the nemesis hands the same wrapper
    to the restarted server, which is exactly "the same storage dir".
    """

    def __init__(
        self, inner, node: str, recorder: HistoryRecorder, honest: bool = True
    ):
        self.inner = inner
        self.node = node
        self.recorder = recorder
        self.honest = honest

    # -- storage contract -------------------------------------------------

    def read(self, variable: bytes, t: int = 0) -> bytes:
        return self.inner.read(variable, t)

    def versions(self, variable: bytes) -> list[int]:
        return self.inner.versions(variable)

    def keys(self) -> list[bytes]:
        return self.inner.keys()

    def scan(self) -> list[tuple[bytes, int]]:
        return self.inner.scan()

    def write(self, variable: bytes, t: int, value: bytes) -> None:
        self.inner.write(variable, t, value)
        self._record_persist(variable, t, value)

    def write_batch(self, items) -> None:
        """Group-commit seam passthrough: the batch persists through
        the inner engine's one-barrier path when it has one (per-item
        writes otherwise), and EVERY item is recorded — the checker's
        commit-point evidence must not thin out because the persists
        were coalesced."""
        items = list(items)
        wb = getattr(self.inner, "write_batch", None)
        if wb is not None:
            wb(items)
        else:
            for variable, t, value in items:
                self.inner.write(variable, t, value)
        for variable, t, value in items:
            self._record_persist(variable, t, value)

    def _record_persist(self, variable: bytes, t: int, value: bytes) -> None:
        if variable.startswith(HIDDEN_PREFIX):
            return  # threshold-CA shares: not protocol records
        completed = False
        pvalue = None
        try:
            p = pkt.parse(value)
            pvalue = p.value
            completed = p.ss is not None and p.ss.completed
        except Exception:
            pass  # non-record bytes (mal tests): recorded as incomplete
        self.recorder.record(
            "persist",
            node=self.node,
            honest=self.honest,
            variable=variable,
            t=t,
            value=pvalue,
            completed=completed,
        )

    def __getattr__(self, name: str):
        # Optional-seam passthrough (sorted_keys / snapshot_records /
        # reopen / close / ...): capability detection on the wrapper
        # must reflect the inner engine's true surface.
        return getattr(self.inner, name)

    # MalStorage pass-through so byzantine programs keep their side area.
    def mal_write(self, variable: bytes, t: int, value: bytes) -> None:
        mw = getattr(self.inner, "mal_write", None)
        if mw is not None:
            mw(variable, t, value)
        else:
            self.inner.write(variable, t, value)


class SafetyChecker:
    """Evaluates the safety invariants over a recorded history.

    ``shard_of_node`` (replica name -> shard index) activates the
    cross-shard invariant for hash-routed sharded clusters; when
    ``routing_stable`` also holds (the shard layout did not change
    during the run — membership churn reroutes the keyspace, and
    migration then LEGITIMATELY copies a variable between shards), the
    strict form applies: a variable never commits certified values in
    two different shards at all."""

    def __init__(
        self,
        recorder: HistoryRecorder,
        f: int,
        shard_of_node: dict[str, int] | None = None,
        routing_stable: bool = False,
        routing_changed: bool = False,
    ):
        self.recorder = recorder
        self.f = f
        self.shard_of_node = shard_of_node
        self.routing_stable = routing_stable
        #: A route-table epoch advanced during the run (autopilot split
        #: / retirement / route_flap): invariant 3's backing signature
        #: may then legitimately verify against the clique that owned
        #: the bucket when the value committed, not the current owner —
        #: the read-integrity search widens to every shard's quorum.
        #: Invariant 5 (no cross-shard equivocation) is untouched.
        self.routing_changed = routing_changed

    def check(self, honest_servers: Iterable) -> list[str]:
        """Returns human-readable violations (empty = safe run).
        ``honest_servers``: the honest replica Server objects, used for
        final-state lookups and collective-signature verification."""
        servers = list(honest_servers)
        out: list[str] = []
        out += self._check_write_once(servers)
        out += self._check_monotonic()
        out += self._check_read_integrity(servers)
        out += self._check_conflicting_commits()
        if self.shard_of_node:
            out += self._check_cross_shard()
        return out

    # -- 1. write-once immutability --------------------------------------

    def _check_write_once(self, servers) -> list[str]:
        out = []
        expected: dict[bytes, bytes] = {}
        for e in self.recorder.events():
            if e.kind == "write_once_ok":
                var, val = e.variable, e.value
                if var in expected and expected[var] != val:
                    out.append(
                        f"write-once {var!r} committed twice with different "
                        f"values ({expected[var]!r} then {val!r})"
                    )
                expected.setdefault(var, val)
            elif e.kind == "read_ok" and e.variable in expected:
                if e.value != expected[e.variable]:
                    out.append(
                        f"write-once {e.variable!r} read back as "
                        f"{e.value!r}, expected {expected[e.variable]!r}"
                    )
            elif (
                e.kind == "persist"
                and e.fields.get("honest")
                and e.fields.get("completed")
                and e.t == MAX_UINT64
                and e.variable in expected
                and e.value != expected[e.variable]
            ):
                out.append(
                    f"honest replica {e.node} persisted conflicting "
                    f"write-once value for {e.variable!r}"
                )
        return out

    # -- 2. timestamp monotonicity at honest replicas --------------------

    def _check_monotonic(self) -> list[str]:
        out = []
        latest: dict[tuple[str, bytes], int] = {}
        for e in self.recorder.events("persist"):
            if not e.fields.get("honest") or not e.fields.get("completed"):
                continue
            key = (e.node, e.variable)
            prev = latest.get(key)
            if prev is not None and e.t < prev:
                out.append(
                    f"honest replica {e.node} went back in time on "
                    f"{e.variable!r}: t={prev} then t={e.t}"
                )
            latest[key] = max(prev or 0, e.t)
        return out

    # -- 3. read integrity ------------------------------------------------

    def _check_read_integrity(self, servers) -> list[str]:
        out = []
        seen: set[tuple[bytes, bytes]] = set()
        for e in self.recorder.events("read_ok"):
            if not e.value:  # empty read: nothing claimed, nothing to back
                continue
            key = (e.variable, e.value)
            if key in seen:
                continue
            seen.add(key)
            if not self._value_is_backed(servers, e.variable, e.value):
                out.append(
                    f"read of {e.variable!r} returned {e.value!r} with no "
                    "verifiable collective signature at any honest replica"
                )
        return out

    def _value_is_backed(self, servers, variable: bytes, value: bytes) -> bool:
        for srv in servers:
            try:
                versions = srv.storage.versions(variable)
            except Exception:
                continue
            for t in sorted(versions, reverse=True):
                try:
                    raw = srv.storage.read(variable, t)
                    p = pkt.parse(raw)
                except Exception:
                    continue
                if (
                    p.value != value
                    or p.ss is None
                    or not p.ss.completed
                ):
                    continue
                # Keyed: the signature must verify against the quorum
                # of the shard that OWNS the variable — a value
                # endorsed only by a foreign clique is not backed.
                # After an epoch change (routing_changed) the THEN
                # owner is also acceptable FOR MOVED BUCKETS ONLY:
                # migration moves certified history between cliques by
                # design, but a variable whose bucket never moved must
                # still verify against its one owner — widening the
                # audit fleet-wide would let a cross-shard laundering
                # bug hide behind any unrelated epoch bump.
                quorums = [
                    qm.choose_quorum_for(srv.qs, variable, qm.AUTH)
                ]
                moved = getattr(
                    srv.qs, "bucket_moved", lambda _v: True
                )
                if self.routing_changed and moved(variable):
                    qfs = getattr(srv.qs, "quorum_for_shard", None)
                    nsh = getattr(srv.qs, "shard_count", lambda: 1)()
                    if qfs is not None:
                        # Verify view: the auditor judges signatures
                        # against each clique's own suff, exactly as
                        # migration admission does.
                        quorums += [
                            qfs(i, qm.AUTH, True) for i in range(nsh)
                        ]
                for quorum in quorums:
                    try:
                        srv.crypt.collective.verify(
                            pkt.tbss(raw),
                            p.ss,
                            quorum,
                            srv.crypt.keyring,
                        )
                        return True
                    except Exception:
                        continue
        return False

    # -- 4. no two conflicting values both gather 2f+1 acks ---------------

    def _check_conflicting_commits(self) -> list[str]:
        out = []
        acks: dict[tuple[bytes, int], dict[bytes, set[str]]] = {}
        for e in self.recorder.events("persist"):
            if not e.fields.get("completed") or e.value is None:
                continue
            acks.setdefault((e.variable, e.t), {}).setdefault(
                e.value, set()
            ).add(e.node)
        need = 2 * self.f + 1
        for (var, t), by_value in acks.items():
            committed = [
                v for v, nodes in by_value.items() if len(nodes) >= need
            ]
            if len(committed) > 1:
                out.append(
                    f"conflicting commits at ({var!r}, t={t}): "
                    f"{len(committed)} values each gathered {need}+ acks"
                )
        return out

    # -- 5. cross-shard: one variable, one owner clique --------------------

    def _check_cross_shard(self) -> list[str]:
        """Sharding's new failure mode: shard B's replicas never run
        shard A's equivocation checks, so a split-brain would show up as
        certified state for one variable living in two shards.  Two
        forms, by strength:

        - always: no (variable, t) carries two DIFFERENT certified
          values at honest replicas of two different shards — that is
          cross-shard equivocation, impossible while routing holds (only
          the owner clique will sign x, and every replica's admission
          verifies the collective signature against the owner quorum);
        - when ``routing_stable``: no variable has certified values in
          two shards AT ALL — same-value copies across shards are
          legitimate only as migration after a routing change, which a
          stable run rules out."""
        out = []
        shard_of = self.shard_of_node or {}
        # (variable, t) -> value -> shard set; variable -> shard set.
        by_vt: dict[tuple[bytes, int], dict[bytes, set[int]]] = {}
        by_var: dict[bytes, set[int]] = {}
        for e in self.recorder.events("persist"):
            if not e.fields.get("honest") or not e.fields.get("completed"):
                continue
            shard = shard_of.get(e.node)
            if shard is None or e.value is None:
                continue
            by_vt.setdefault((e.variable, e.t), {}).setdefault(
                e.value, set()
            ).add(shard)
            by_var.setdefault(e.variable, set()).add(shard)
        for (var, t), by_value in by_vt.items():
            if len(by_value) < 2:
                continue
            shard_sets = list(by_value.values())
            spread = set().union(*shard_sets)
            if len(spread) > 1:
                out.append(
                    f"cross-shard equivocation at ({var!r}, t={t}): "
                    f"{len(by_value)} certified values across shards "
                    f"{sorted(spread)}"
                )
        if self.routing_stable:
            for var, shards in by_var.items():
                if len(shards) > 1:
                    out.append(
                        f"variable {var!r} committed certified values in "
                        f"{len(shards)} shards {sorted(shards)} with no "
                        "routing change to explain migration"
                    )
        return out
