"""Chaos nemesis: seeded, timed fault schedules against a live cluster.

A :class:`Nemesis` turns one integer seed into a deterministic plan of
chaos steps and executes them against a
:class:`~bftkv_tpu.faults.harness.ChaosCluster` while client traffic
runs, then repairs the world (heal partitions, restart crashed
replicas, drive anti-entropy to convergence) and hands the recorded
history to the :class:`~bftkv_tpu.faults.checker.SafetyChecker`.

Step kinds:

- ``partition`` — a link-matrix cut isolating one replica from
  everyone (drop rules on ``transport.send``, both directions), healed
  at the end of the step;
- ``crash_restart`` — the replica goes dark mid-traffic and is
  restarted as a *fresh* ``Server`` on the same storage; anti-entropy
  must converge it back;
- ``clock_skew`` — the replica's ``time`` answers are shifted by a
  seeded delta (the timestamp path under desynchronized clocks);
- ``link_delay`` — seeded delays on one replica's inbound links (the
  partial-synchrony regime where threshold systems pay their latency
  price);
- ``stale_replay`` / ``collude`` — Byzantine modes as failpoint
  programs (:mod:`bftkv_tpu.faults.byzantine`): genuinely-signed stale
  answers, or the full sign-anything/store-anything colluder;
- ``region_partition`` — a WHOLE region loses its WAN egress: every
  link crossing the region boundary is cut while intra-region links
  stay up (DESIGN.md §21).  Eligible only for regions whose seats stay
  within every plane's node-level ``f`` and hold no clients/gateways,
  so the acceptance bar is ZERO failed writes plus the ``region_down``
  anomaly naming the negative region-level budget.

Every step touches at most one replica at a time, keeping the
adversary inside the ``f`` budget a ``3f+1`` cluster promises to
tolerate — so ZERO safety violations is the pass bar, not a wish.

One seeded round from the shell::

    python -m bftkv_tpu.faults.nemesis --seed 7

exits non-zero if the checker reports any violation, and prints the
plan + fault-trace summary as JSON (``--json``) for the CI soak lane.
"""

from __future__ import annotations

import os
import random
import threading
import time

from bftkv_tpu import flags
from bftkv_tpu.faults import byzantine, failpoint as fp
from bftkv_tpu.faults.checker import SafetyChecker
from bftkv_tpu.faults.harness import ChaosCluster, build_cluster
from bftkv_tpu.storage.memkv import MemStorage

__all__ = ["Nemesis", "main"]

STEP_KINDS = (
    "partition",
    "crash_restart",
    "clock_skew",
    "link_delay",
    "stale_replay",
    "collude",
    "slow_node",
    "route_flap",
    "sidecar_crash",
    "overload",
    "region_partition",
)


class SidecarHarness:
    """Embedded shared-crypto sidecar for chaos runs (``--sidecar``).

    Boots one in-process sidecar on a mode-0600 unix socket and routes
    the WHOLE cluster's verify+sign dispatchers through it, so every
    traffic window crosses the service.  ``crash()`` is the kill -9
    shape: listener gone, socket unlinked, the tenant connection
    severed — clients must fall back to local crypto with ZERO failed
    writes and the breaker-open counter must surface as the
    ``sidecar_down`` anomaly.  ``restart()`` serves the same path again
    and clears the (short) breaker so the next window re-registers
    sign-key handles over a fresh connection."""

    def __init__(self):
        import os
        import tempfile

        from bftkv_tpu.cmd import verify_sidecar
        from bftkv_tpu.crypto.remote_verify import (
            RemoteSignerDomain,
            RemoteVerifierDomain,
            SidecarChannel,
        )
        from bftkv_tpu.ops import dispatch

        self._os = os
        self._verify_sidecar = verify_sidecar
        self._dir = tempfile.mkdtemp(prefix="bftkv-sidecar-")
        self._path = os.path.join(self._dir, "crypto.sock")
        self.addr = "unix:" + self._path
        self.srv, _ = verify_sidecar.serve(self.addr)
        # Short breaker: a healed window must be able to go remote
        # again within the next window, exercising reconnect +
        # handle re-registration instead of one long local stretch.
        self.channel = SidecarChannel(self.addr, breaker_seconds=1.0)
        dispatch.install(
            dispatch.VerifyDispatcher(
                verifier=RemoteVerifierDomain(channel=self.channel),
                calibrate=False,
            )
        )
        dispatch.install_signer(
            dispatch.SignDispatcher(
                signer=RemoteSignerDomain(channel=self.channel),
                calibrate=False,
                max_wait=0.002,
            )
        )

    def crash(self) -> None:
        self.srv.service.stop()
        self.srv.shutdown()
        self.srv.server_close()
        try:
            self._os.unlink(self._path)
        except OSError:
            pass
        # Sever the established tenant connection too: a threading
        # server's live handler would otherwise keep answering.
        self.channel.close()

    def restart(self) -> None:
        self.srv, _ = self._verify_sidecar.serve(self.addr)
        self.channel.reset()

    def stop(self) -> None:
        from bftkv_tpu.ops import dispatch

        try:
            self.crash()
        except Exception:
            pass  # teardown-only: a half-crashed sidecar is fine here
        dispatch.uninstall_all()


#: Anomaly kinds that validly evidence each fault kind in a window's
#: flight-recorder bundle — the mirror of hit()'s own acceptance in
#: _window_check (which of them lands first is a race between the
#: failpoint echo, the counter-delta feeds, and member-state scrapes).
_BUNDLE_OK_KINDS: dict[str, set] = {
    "route_flap": {"epoch_skew"},
    "sidecar_crash": {"sidecar_down", "sidecar_dishonest"},
    "crash_restart": {"member_down"},
    "slow_node": {"fault", "gray_member"},
    "overload": {"resource_saturated"},
    # Probes observe cuts (_ChaosProbeSource), so a partitioned member
    # also transitions down at scrape time — either signal is the
    # window's valid black-box evidence.
    "partition": {"fault", "member_down"},
    "region_partition": {"region_down", "member_down", "fault"},
}


class _ChaosProbeSource:
    """A :class:`~bftkv_tpu.obs.source.LocalSource` whose probe also
    crosses the failpoint plane.  In-process partitions never
    unregister a transport, so the stock registration check would call
    a fully cut-off member healthy — but a real external health
    checker's probe RPC would be dropped by the same rule that drops
    everyone else's traffic.  The probe asks the registry the same
    question side-effect-free (:meth:`FaultRegistry.would_drop`): no
    rule budgets consumed, no fault-trace echo, no perturbed seeded
    draws.  Probes carry no region label, so they count as
    outside-the-boundary traffic for a region cut and never match the
    WAN topology's delay rules."""

    def __init__(self, inner, registry: fp.FaultRegistry):
        self._inner = inner
        self._registry = registry
        self.name = inner.name

    def __getattr__(self, attr):
        return getattr(self._inner, attr)

    def probe(self) -> bool:
        if not self._inner.probe():
            return False
        return not self._registry.would_drop(
            "transport.send", src="fleet", dst=self.name, cmd="probe"
        )


class Nemesis:
    def __init__(
        self,
        cluster: ChaosCluster,
        seed: int = 0,
        registry: fp.FaultRegistry | None = None,
        autopilot: bool = False,
        sidecar_ctl: SidecarHarness | None = None,
        rtt_spec: str | None = None,
        workload: str | None = None,
    ):
        self.cluster = cluster
        self.seed = seed
        self.registry = registry or fp.registry
        #: Spec-shaped traffic (``--workload``, DESIGN.md §23): each
        #: window drains the next slice of ONE deterministic workload
        #: op stream on top of the coverage burst, so faults land
        #: under a production op mix (hot-set storms, ramps) instead
        #: of only the hand-rolled burst.
        self.workload = None
        self._wl_cursor = 0
        if workload:
            from bftkv_tpu.workload.spec import parse_spec

            self.workload = parse_spec(workload)
        #: WAN link-delay program (``--rtt-matrix``): compiled onto
        #: quiet background delay rules right after :meth:`run` arms
        #: the registry, so the whole schedule executes under the
        #: deployment geography (DESIGN.md §21).
        self.rtt_spec = rtt_spec
        self.wan = None
        #: region_partition windows where a write failed: an eligible
        #: region's outage stays inside every plane's node-level f
        #: budget by construction, so writes may slow, never fail.
        self.region_blocked: list[dict] = []
        #: Embedded crypto sidecar under test (``--sidecar``): enables
        #: the sidecar_crash step kind and its zero-failed-writes
        #: oracle.
        self.sidecar_ctl = sidecar_ctl
        self.sidecar_blocked: list[dict] = []
        #: Topology autopilot under test: built in :meth:`run` (it
        #: wants the collector), drives ONE forced migration while the
        #: second half of the fault schedule lands — reconfiguration
        #: under chaos, the DESIGN.md §15 acceptance shape.  The
        #: ``route_flap`` step kind needs it too (it ships tables).
        self._want_autopilot = autopilot
        self.autopilot = None
        self._migration: dict | None = None
        self._written: dict[bytes, bytes] = {}
        self.failures = {"write": 0, "read": 0}
        #: Fleet health collector watching the same cluster — the chaos
        #: suite double-checks the *observability plane*: every injected
        #: fault must surface in the anomaly feed within one scrape of
        #: its window (built in :meth:`run`; None = detection off).
        self.collector = None
        self.detection: list[dict] = []
        #: Flight recorder under test (``BFTKV_FLIGHT_RECORDER=1``):
        #: every fault window must yield exactly ONE black-box bundle
        #: whose manifest names the detected anomaly — the "what did
        #: the box look like when it broke" oracle (DESIGN.md §18).
        self.recorder = None
        self.recorder_missing: list[dict] = []
        #: slow_node windows where a write failed: a gray member inside
        #: the f budget must never BLOCK commit — slower is fine,
        #: failed is a violation (the acceptance bar of DESIGN.md §13).
        self.gray_blocked: list[dict] = []
        #: Front-door client when the cluster runs gateways: part of
        #: every traffic window then, so gateway↔quorum faults (and
        #: Byzantine fill attempts crossing the cache) manifest — and
        #: every gateway-served read is RECORDED, so checker invariant
        #: 3 (reads backed by a verifying collective signature) also
        #: proves no uncertified value was ever served off the cache.
        self._gwc = (
            cluster.gateway_client(0)
            if getattr(cluster, "gateways", None)
            else None
        )
        self._gw_seq = 0
        #: The most recently direct-written variable, tracked
        #: explicitly — a lexicographic max over ``_written`` stops
        #: being "newest" once window tags reach two digits.
        self._last_direct_var: bytes | None = None

    # -- deterministic planning -------------------------------------------

    def _region_pool(self) -> list[str]:
        """Regions eligible for a whole-region outage.  The two-level
        budget (DESIGN.md §21) must keep writes alive, so a region
        qualifies only when it holds no client or gateway identities
        and its seats stay within the NODE-level budget of every
        plane: at most ``f`` members of each shard clique and at most
        ``f`` storage replicas.  Empty when no region map is installed
        — plan() then degrades the kind to a plain partition."""
        uni = getattr(self.cluster, "universe", None)
        rmap = getattr(uni, "regions", None) or {}
        if not rmap:
            return []

        def reg(name: str) -> str | None:
            return rmap.get(name)

        barred = {
            reg(i.name)
            for i in list(getattr(uni, "users", ()))
            + list(getattr(uni, "gateways", ()))
        }
        clique_groups = [
            [i.name for i in g]
            for g in (getattr(uni, "shards", None) or [])
            if g
        ] or [[i.name for i in getattr(uni, "servers", ())]]
        storage = [i.name for i in getattr(uni, "storage_nodes", ())]
        out = []
        labels = sorted(
            {
                r
                for k, r in rmap.items()
                if "://" not in k and ":" not in k
            }
        )
        for r in labels:
            if r in barred:
                continue
            ok = all(
                sum(1 for n in g if reg(n) == r) <= (len(g) - 1) // 3
                for g in clique_groups
            )
            if ok and storage:
                ok = (
                    sum(1 for n in storage if reg(n) == r)
                    <= self.cluster.f
                )
            if ok:
                out.append(r)
        return out

    def plan(self, steps: int = 4, kinds: tuple | None = None) -> list[dict]:
        """Pure function of (seed, cluster shape): the schedule replays
        identically run to run.  ``kinds`` restricts the step pool
        (the slow_node-heavy CI soak uses it).

        ``stale_replay`` targets only the storage plane: single reads
        fan out to the read complement ``R = {Vi} − {Ci}`` (wotqs), so
        a read-replayer programmed onto a *quorum* server would never
        receive a read — a fault that cannot manifest exercises
        nothing and is undetectable by construction.

        ``slow_node`` is the gray failure: the member stays ALIVE and
        honest but every inbound link to it is delayed (~5-10x a
        loopback p99); the ``write_sign`` mode is the gray colluder —
        prompt on every command except the one on the write's critical
        path."""
        rng = random.Random(self.seed)
        kinds = tuple(kinds) if kinds else STEP_KINDS
        targets = sorted(self.cluster.names(storage_only=True))
        uni = getattr(self.cluster, "universe", None)
        storage = sorted(
            i.name for i in getattr(uni, "storage_nodes", ())
        ) or targets
        clique = sorted(
            i.name for i in getattr(uni, "servers", ())
        ) or targets
        # write_sign-mode gray colluders must sit in the staged WRITE
        # wave or the fault cannot manifest (the staged fan-out asks
        # the first 2f+1 clique members of the owner shard; a member
        # outside that prefix never receives a WRITE_SIGN at all —
        # same honesty rule as stale_replay's storage-plane scoping).
        shard_groups = [
            [i.name for i in g]
            for g in (getattr(uni, "shards", None) or [])
            if g
        ] or [clique]
        ws_pool = []
        for names in shard_groups:
            f_g = (len(names) - 1) // 3
            ws_pool += names[: 2 * f_g + 1]
        ws_pool = ws_pool or clique
        # Edge gateways join the link-fault pools: a partition or delay
        # on a gateway's links IS the gateway↔quorum fault class (its
        # upstream fan-outs carry its own link id).
        gw_names = sorted(
            getattr(self.cluster, "gateway_names", lambda: [])()
        )
        # route_flap needs ≥ 2 shards AND the autopilot (it ships the
        # epoch-N+1 table); unsupported configs degrade the kind to a
        # partition so a seeded plan stays runnable everywhere.
        flap_ok = (
            self._want_autopilot
            and len(getattr(uni, "shards", None) or []) > 1
        )
        client_names = sorted(
            getattr(c.self_node, "name", f"u{i + 1:02d}")
            for i, c in enumerate(
                getattr(self.cluster, "clients", []) or []
            )
        ) or ["u01"]
        region_pool = self._region_pool()
        out = []
        for i in range(steps):
            kind = kinds[rng.randrange(len(kinds))]
            if kind == "route_flap" and not flap_ok:
                kind = "partition"
            if kind == "sidecar_crash" and self.sidecar_ctl is None:
                # No embedded sidecar armed: degrade like route_flap
                # so one seeded plan stays runnable everywhere.
                kind = "partition"
            if kind == "overload" and self._overload_queue() is None:
                # No admission-bearing component (no sidecar, no
                # gateways): nothing to clamp — degrade, same rule.
                kind = "partition"
            if kind == "region_partition" and not region_pool:
                # No eligible region (map not installed, or every
                # region hosts clients/gateways or exceeds a plane's
                # node-level f): degrade, same rule as route_flap.
                kind = "partition"
            if kind == "region_partition":
                pool = region_pool
            elif kind == "overload":
                pool = [self._overload_queue()[1]]
            elif kind == "sidecar_crash":
                pool = ["sidecar01"]
            elif kind == "route_flap":
                # The held-back principal is a CLIENT: its writes keep
                # routing on epoch N, land on the old owner, and must
                # re-route off the hinted decline — the fault class the
                # epoch_stale counter and epoch_skew anomaly exist for.
                pool = client_names
            elif kind == "stale_replay":
                pool = storage
            elif kind == "slow_node":
                # Gray CLIQUE members are the interesting case: they
                # sit on the WRITE_SIGN critical path.
                mode = ("all", "write_sign")[rng.randrange(2)]
                pool = ws_pool if mode == "write_sign" else clique
            elif kind in ("partition", "link_delay") and gw_names:
                pool = targets + gw_names
            else:
                pool = targets
            step = {"step": i, "kind": kind, "target": pool[rng.randrange(len(pool))]}
            if kind == "clock_skew":
                step["delta"] = rng.choice([-1000, 1000, 1 << 20])
            elif kind == "link_delay":
                step["seconds"] = round(0.01 + 0.04 * rng.random(), 4)
            elif kind == "slow_node":
                step["seconds"] = round(0.4 + 0.3 * rng.random(), 3)
                step["mode"] = mode
            out.append(step)
        return out

    # -- primitives --------------------------------------------------------

    def partition(self, isolated: str, rule_id: str = "") -> list[fp.Rule]:
        """Cut every link to/from ``isolated`` (peers AND clients)."""
        name = isolated

        def cut(ctx: dict) -> bool:
            return ctx.get("src") == name or ctx.get("dst") == name

        return [
            self.registry.add(
                "transport.send",
                "drop",
                match=cut,
                rule_id=rule_id or f"partition:{name}",
            )
        ]

    def region_partition(
        self, region: str, rule_id: str = ""
    ) -> list[fp.Rule]:
        """Whole-region WAN outage: every link CROSSING the region
        boundary is cut, both directions, while intra-region links
        stay up — a region loses its egress, not its LAN.  Fleet
        probes carry no region label, so they count as outside traffic
        and observe the cut like any external health checker."""
        from bftkv_tpu import regions as rg

        def cut(ctx: dict) -> bool:
            a = rg.region_of(ctx.get("src") or "")
            b = rg.region_of(ctx.get("dst") or "")
            return (a == region) != (b == region)

        return [
            self.registry.add(
                "transport.send",
                "drop",
                match=cut,
                rule_id=rule_id or f"region_partition:{region}",
            )
        ]

    def link_delay(
        self, target: str, seconds: float, rule_id: str = ""
    ) -> list[fp.Rule]:
        return [
            self.registry.add(
                "transport.send",
                "delay",
                match={"dst": target},
                seconds=seconds,
                max_seconds=seconds * 3,
                rule_id=rule_id or f"delay:{target}",
            )
        ]

    def slow_node(
        self,
        target: str,
        seconds: float,
        mode: str = "all",
        rule_id: str = "",
    ) -> list[fp.Rule]:
        """Gray failure: ``target`` stays alive and honest, but every
        inbound post to it is delayed.  ``mode="write_sign"`` is the
        gray *colluder* — prompt on every command except WRITE_SIGN,
        so only the collapsed write's critical path suffers (a plain
        liveness probe sees a healthy member)."""
        match: dict = {"dst": target}
        if mode == "write_sign":
            match["cmd"] = "write_sign"
        return [
            self.registry.add(
                "transport.send",
                "delay",
                match=match,
                seconds=seconds,
                max_seconds=seconds * 1.5,
                rule_id=rule_id or f"slow_node:{target}",
            )
        ]

    def _overload_queue(self):
        """``(AdmissionQueue, member label)`` for the overload step —
        the embedded sidecar's admission when armed, else the first
        gateway's — or None when the cluster has neither (plan()
        degrades the step)."""
        if self.sidecar_ctl is not None:
            return self.sidecar_ctl.srv.service.admission, "sidecar01"
        gws = getattr(self.cluster, "gateways", None) or []
        if gws:
            return gws[0].admission, gws[0].self_node.name
        return None

    def overload_burst(self, adm, contenders: int = 4) -> None:
        """One saturated burst against a clamped admission queue: hold
        the only slot, throw contenders at the one queue slot — the
        overflow sheds instantly, the waiters time out, and the wait
        histogram + gauges record the clamp for the capacity plane."""
        held = adm.acquire("chaos-overload")
        threads = [
            threading.Thread(
                target=adm.acquire, args=("chaos-overload",), daemon=True
            )
            for _ in range(contenders)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if held:
            adm.release()

    def clock_skew(
        self, target: str, delta: int, rule_id: str = ""
    ) -> list[fp.Rule]:
        return [
            self.registry.add(
                "server.time",
                "skew",
                match={"node": target},
                delta=delta,
                rule_id=rule_id or f"skew:{target}",
            )
        ]

    def heal(self, rules: list[fp.Rule]) -> None:
        self.registry.remove_all(rules)

    # -- traffic -----------------------------------------------------------

    def _client(self, i: int):
        clients = self.cluster.clients
        return clients[i % len(clients)]

    def traffic(self, tag: str, writes: int = 3, reads: int = 3) -> None:
        """A burst of recorded writes + reads.  Failures are counted,
        not raised: under a partition failing is correct behavior.

        Sharded clusters get COVERAGE traffic on top of the base burst:
        at least one write and one read per shard each window.  A fault
        on a replica only *manifests* when traffic crosses it (a
        partition rule fires on a cut send, a Byzantine handler on an
        arriving request) — without coverage, a window whose random
        keys all routed elsewhere would leave the fault invisible to
        both the checker and the detection assertion."""
        rec = self.cluster.recorder
        cl = self._client(0)
        cname = "u01"
        for i in range(writes):
            var = f"chaos/{tag}/{i}".encode()
            val = f"value-{tag}-{i}".encode()
            self._write_one(cl, rec, cname, var, val)
        shard_of = getattr(cl.qs, "shard_of", None)
        nsh = (
            cl.qs.shard_count()
            if hasattr(cl.qs, "shard_count")
            else 1
        )
        # One small write_many per shard: the batched pipeline keeps
        # the classic BATCH_TIME/SIGN/WRITE rounds, which is the only
        # remaining traffic that crosses ``server.time`` — without it a
        # clock-skew fault could never manifest under collapsed single
        # writes (same honesty rule as stale_replay's storage-plane
        # scoping: an uncrossed fault is undetectable by construction).
        for s in range(nsh) if (shard_of and nsh > 1) else [None]:
            batch: list[tuple[bytes, bytes]] = []
            i = 0
            while len(batch) < 2 and i < 4096:
                v = f"chaos/{tag}/batch/{s}/{i}".encode()
                i += 1
                if s is None or shard_of(v) == s:
                    batch.append((v, f"batch-{tag}-{i}".encode()))
            try:
                res = cl.write_many(batch)
            except Exception as e:
                res = [e] * len(batch)
            for (v, val), err in zip(batch, res):
                if err is None:
                    rec.write_ok(cname, v, val)
                    self._written[v] = val
                else:
                    rec.write_fail(cname, v, err)
                    self.failures["write"] += 1
        if shard_of is not None and nsh > 1:
            covered = {
                shard_of(f"chaos/{tag}/{i}".encode())
                for i in range(writes)
            }
            i = 0
            while len(covered) < nsh and i < 4096:
                var = f"chaos/{tag}/cover/{i}".encode()
                i += 1
                s = shard_of(var)
                if s in covered:
                    continue
                covered.add(s)
                self._write_one(
                    cl, rec, cname, var, f"cover-{tag}".encode()
                )
        if self._gwc is not None:
            self._gateway_traffic(tag)
        if self.workload is not None:
            self._workload_traffic(writes + reads)
        # str seeds hash via sha512 (deterministic); a tuple seed would
        # go through PYTHONHASHSEED-salted hash() and break replay.
        rng = random.Random(f"{self.seed}|{tag}")
        candidates = sorted(self._written)
        picks = [
            candidates[rng.randrange(len(candidates))]
            for _ in range(min(reads, len(candidates)))
        ]
        if shard_of is not None and nsh > 1:
            # One read per shard (newest written var of each), so a
            # read-path fault (stale replayer) sees traffic too.
            per_shard: dict = {}
            for var in candidates:
                per_shard[shard_of(var)] = var
            picks += [
                v
                for s, v in sorted(per_shard.items())
                if not any(shard_of(p) == s for p in picks)
            ]
        for var in picks:
            try:
                rec.read_ok(cname, var, cl.read(var))
            except Exception as e:
                rec.read_fail(cname, var, e)
                self.failures["read"] += 1

    def _gateway_traffic(self, tag: str) -> None:
        """Per-window front-door traffic: one coalesced write + its
        read-back (write-through cache), plus a quorum FILL of the
        newest directly-written variable — so gateway↔quorum faults
        and Byzantine fill attempts have certified-cache traffic to
        cross in every window.  Failures count, never raise (under a
        partitioned gateway failing is correct).  The gateway keyspace
        (``chaos/gw/``) is disjoint from the direct clients' — TOFU
        ownership pins a variable to one writing identity."""
        rec = self.cluster.recorder
        gwc = self._gwc
        cname = "gw"
        self._gw_seq += 1
        var = f"chaos/gw/{tag}/{self._gw_seq}".encode()
        val = f"gw-{tag}".encode()
        try:
            gwc.write(var, val)
            rec.write_ok(cname, var, val)
            self._written[var] = val
        except Exception as e:
            rec.write_fail(cname, var, e)
            self.failures["write"] += 1
        reads = [var]
        if self._last_direct_var is not None:
            # The newest direct var: a COLD quorum fill every window,
            # crossing whatever fault (Byzantine replayer, cut link)
            # is armed on the gateway↔quorum path.
            reads.append(self._last_direct_var)
        for rv in reads:
            try:
                rec.read_ok(cname, rv, gwc.read(rv))
            except Exception as e:
                rec.read_fail(cname, rv, e)
                self.failures["read"] += 1

    def _workload_traffic(self, n: int) -> None:
        """Drain the next ``n`` ops of the ``--workload`` spec stream
        through the recorded-traffic plane.  The stream position
        advances monotonically across windows — op ``g`` is always op
        ``g``, so one seed replays one schedule regardless of window
        count or fault outcome.  Writes are recorded for the checker;
        reads of never-written ranks execute but stay unrecorded (a
        quorum miss carries no invariant).  TOFU holds by construction:
        owner slot ``o`` is always written by client ``o % clients``."""
        spec = self.workload
        rec = self.cluster.recorder
        clients = self.cluster.clients
        for g in range(self._wl_cursor, self._wl_cursor + n):
            op = spec.op_at(g)
            idx = op.owner % len(clients)
            cl = clients[idx]
            cname = f"u{idx + 1:02d}"
            var = spec.key_bytes(op.owner, op.rank)
            if op.kind == "write":
                val = (b"wl-%d" % g).ljust(min(op.size, 1024), b".")
                self._write_one(cl, rec, cname, var, val)
            elif op.kind == "write_many":
                val = (b"wlm-%d" % g).ljust(min(op.size, 1024), b".")
                items = [
                    (spec.key_bytes(op.owner, op.rank + j), val)
                    for j in range(min(spec.wm_batch, 4))
                ]
                try:
                    res = cl.write_many(items)
                except Exception as e:
                    res = [e] * len(items)
                for (v, vv), err in zip(items, res):
                    if err is None:
                        rec.write_ok(cname, v, vv)
                        self._written[v] = vv
                    else:
                        rec.write_fail(cname, v, err)
                        self.failures["write"] += 1
            elif op.kind == "scan":
                keys = [
                    spec.key_bytes(op.owner, op.rank + j)
                    for j in range(min(spec.scan_width, spec.keyspace))
                ]
                try:
                    cl.read_many(keys)
                except Exception:
                    self.failures["read"] += 1
            else:  # read | gateway_read (degrades without gateways)
                rdr = (
                    self._gwc
                    if op.kind == "gateway_read" and self._gwc is not None
                    else cl
                )
                rname = "gw" if rdr is self._gwc else cname
                try:
                    got = rdr.read(var)
                    if var in self._written:
                        rec.read_ok(rname, var, got)
                except Exception as e:
                    if var in self._written:
                        rec.read_fail(rname, var, e)
                    self.failures["read"] += 1
        self._wl_cursor += n

    def _write_one(self, cl, rec, cname: str, var: bytes, val: bytes) -> None:
        try:
            cl.write(var, val)
            rec.write_ok(cname, var, val)
            self._written[var] = val
            self._last_direct_var = var
        except Exception as e:
            rec.write_fail(cname, var, e)
            self.failures["write"] += 1

    # -- convergence -------------------------------------------------------

    def converge(self, max_rounds: int = 6) -> bool:
        """Drive anti-entropy rounds until every storage replica's
        digest root agrees (bounded).  Returns True on convergence.

        Sharded clusters converge PER SHARD: replicas of different
        shards hold disjoint keyspace slices by design, so roots are
        compared within each shard group, never across."""
        from bftkv_tpu.sync import SyncDaemon

        replicas = self.cluster.storage_servers or self.cluster.servers

        def group_of(s) -> object:
            idx_of = getattr(s.qs, "shard_index_of", None)
            if idx_of is None:
                return "all"
            idx = idx_of(s.self_node.get_self_id())
            return "all" if idx is None else idx

        def owned_root(s) -> object:
            """The digest root restricted to buckets the replica OWNS:
            after a migration the old owner's moved-bucket copies are
            inert by design (never synced again), so the full-tree
            root would diverge forever without any safety meaning."""
            tree = s._sync_tree()
            owned = getattr(s.qs, "owned_buckets", lambda: None)()
            if owned is None:
                return tree.root()
            return tuple(
                sorted(
                    (b, h)
                    for b, h in tree.buckets().items()
                    if b in owned
                )
            )

        def converged() -> bool:
            roots: dict[object, set] = {}
            for s in replicas:
                roots.setdefault(group_of(s), set()).add(owned_root(s))
            return all(len(r) == 1 for r in roots.values())

        daemons = [
            SyncDaemon(s, interval=999, rng=random.Random(self.seed + i))
            for i, s in enumerate(replicas)
        ]
        for _ in range(max_rounds):
            if converged():
                return True
            for d in daemons:
                try:
                    d.run_round()
                except Exception:
                    pass
        return converged()

    # -- detection (the observability plane under test) --------------------

    def _forced_migration(self) -> None:
        """One forced hot-shard split, executed on a side thread while
        the fault schedule keeps landing.  A pre-copy blocked by an
        active fault window ABORTS without flipping (correct behavior,
        like failing writes under partition) — retried a couple of
        times so the migration completes once the window heals.  A
        migration that never completes is a report (and a run-failing
        violation), never a crash of the nemesis itself."""
        for attempt in range(3):
            try:
                self._migration = self.autopilot.force_split(pace=0.4)
            except Exception as e:
                self._migration = {"ok": False, "error": repr(e)}
                return
            if self._migration.get("ok"):
                return
            time.sleep(1.0 + attempt)

    def _make_collector(self):
        from bftkv_tpu import trace as trmod
        from bftkv_tpu.metrics import registry as mreg
        from bftkv_tpu.obs import FleetCollector, LocalSource

        sources = [
            # server_named resolves through _by_name, so a source keeps
            # following its member across crash-restarts.  Every probe
            # is wrapped to observe armed drop rules (in-process cuts
            # never unregister a transport).
            _ChaosProbeSource(
                LocalSource(
                    name, lambda n=name: self.cluster.server_named(n)
                ),
                self.registry,
            )
            for name in sorted(self.cluster._by_name)
        ]
        for gw in getattr(self.cluster, "gateways", ()):
            sources.append(
                _ChaosProbeSource(
                    LocalSource(gw.self_node.name, lambda gw=gw: gw),
                    self.registry,
                )
            )
        return FleetCollector(
            sources,
            local_metrics=mreg,
            local_tracer=trmod.tracer,
            fp_registry=self.registry,
        )

    def _observe_window(self, step: dict, seq0: int) -> None:
        """Scrape INSIDE the fault window, then the assertion that
        makes chaos a test of the health plane: the injected fault must
        be in the anomaly feed within one scrape interval.  The
        multicast fan-out abandons stragglers at the quorum threshold,
        so the window's last RPC — the one that trips the rule on the
        target — may still be in flight when traffic() returns; the
        bounded re-scrape below IS the "one interval" allowance, and
        the fault stays armed throughout.

        ``hit()`` returns the MATCHED anomaly kind (or a vacuous
        marker), not just a bool: the flight-recorder oracle below
        needs to know which anomaly this window's bundle must name."""
        if self.collector is None:
            return
        kind, target = step["kind"], step["target"]
        rec = self.recorder
        bundles0: set = set()
        if rec is not None:
            # New coalescing epoch: this window's anomalies mint ONE
            # fresh bundle (follow-ups amend it), never share the
            # previous window's.
            rec.mark_window()
            bundles0 = set(rec.bundles())

        def hit() -> str | None:
            fresh = self.collector.anomalies(since_seq=seq0)
            if kind == "route_flap":
                # The stale-routed client's declined writes surface as
                # the old owner's server.epoch_stale counter delta →
                # epoch_skew anomaly (source is the process-wide
                # metrics feed on loopback clusters, so kind alone is
                # the match).
                if any(a["kind"] == "epoch_skew" for a in fresh):
                    return "epoch_skew"
                return None
            if kind == "sidecar_crash":
                # The crypto service died: tenants must notice — the
                # breaker-open counter delta maps to sidecar_down in
                # the feed (sidecar_dishonest would also count: either
                # way the plane flagged the service).
                for a in fresh:
                    if a["kind"] in ("sidecar_down", "sidecar_dishonest"):
                        return a["kind"]
                return None
            if kind == "overload":
                # The clamped admission tier must surface through the
                # capacity plane's hysteresis: a resource_saturated
                # anomaly naming admission (the gauges ride the
                # process-wide feed, so kind+detail is the match).
                for a in fresh:
                    if (
                        a["kind"] == "resource_saturated"
                        and "admission" in a["detail"]
                    ):
                        return "resource_saturated"
                return None
            if kind == "region_partition":
                # The outage must be named AS a region event: the
                # region_down anomaly carries the region-level budget
                # arithmetic (f_regions - dark < 0, DESIGN.md §21).
                # State form: the rollup reports the region dark at
                # scrape time — consecutive windows on one region
                # never transition back to up in between.
                for a in fresh:
                    if (
                        a["kind"] == "region_down"
                        and a["source"] == target
                    ):
                        return "region_down"
                regs = self.collector.health().get("regions") or {}
                row = (regs.get("rows") or {}).get(target)
                if row and row.get("dark"):
                    return "region_down"
                return None
            if kind == "crash_restart":
                # The plane "sees" an outage either as a fresh
                # member_down transition or as the member simply BEING
                # down at scrape time — consecutive crash windows on
                # one target never transition back to up in between,
                # so the transition alone would under-report.
                m = self.collector.members.get(target)
                if m is not None and m.status == "down":
                    return "member_down"
                if any(
                    a["kind"] == "member_down" and a["source"] == target
                    for a in fresh
                ):
                    return "member_down"
                return None
            if kind == "slow_node":
                # A gray member surfaces three ways: the injected-fault
                # echo (fp registry); a gray_member anomaly from the
                # transport.peer.slow delta, attributed to the peer in
                # the detail string (the counter is recorded client-
                # side, so the scrape source is the process); or the
                # member simply BEING flagged gray at observe time —
                # health-aware staging ranks a still-gray member out of
                # the wave, so consecutive windows on one target may
                # see no fresh traffic at all (the crash_restart
                # being-down-at-scrape rule, gray form).
                from bftkv_tpu import transport as _tp

                try:
                    srv = self.cluster.server_named(target)
                    addr = getattr(srv.self_node, "address", "")
                except Exception:
                    addr = ""
                if addr and _tp.peer_latency.is_gray(addr):
                    return "gray_member"
                for a in fresh:
                    if a["kind"] == "fault" and a["source"] == target:
                        return "fault"
                    if (
                        a["kind"] == "gray_member"
                        and target in a["detail"]
                    ):
                        return "gray_member"
                # Vacuous window: the delay rule never FIRED — health-
                # aware staging (or an earlier gray verdict whose flag
                # has since decayed) kept every post off the target.
                # An uncrossed fault is undetectable by construction
                # (the plan()'s honesty rule), so a zero-fire window
                # counts as detected; when the rule DID fire, only the
                # real channels above count — a crossed fault must
                # surface in the health feed.
                fired = any(
                    e.rule_id == f"slow_node:{target}"
                    and e.seq > step.get("_fp_seq0", 0)
                    for e in self.registry.trace()
                )
                return None if fired else "vacuous"
            if any(
                a["kind"] == "fault" and a["source"] == target
                for a in fresh
            ):
                return "fault"
            return None

        matched = None
        # Generous tail (~6 s worst case, first scrape usually wins):
        # under 2-CPU contention an abandoned straggler post — the one
        # carrying the only RPC that trips the rule on the target — can
        # sit queued behind the writers for whole seconds.
        for attempt in range(24):
            if attempt:
                time.sleep(0.25)
            self.collector.scrape_once()
            matched = hit()
            if matched:
                break
        entry = {
            "step": step["step"], "kind": kind, "target": target,
            "detected": matched is not None, "anomaly": matched,
        }
        if rec is not None and matched and matched != "vacuous":
            # The bundle-per-fault oracle: this window must have minted
            # exactly one bundle whose manifest names the matched
            # anomaly.  Detections via member STATE (down/gray at
            # scrape, no fresh anomaly event) take a demand snapshot
            # naming the verdict — the black box records what the
            # plane concluded, however it concluded it.
            new = sorted(set(rec.bundles()) - bundles0)
            if not new:
                rec.snapshot(
                    reason=f"step{step['step']}-{kind}",
                    anomalies=[{
                        "kind": matched,
                        "source": target,
                        "detail": "state-detected at scrape",
                    }],
                )
                new = sorted(set(rec.bundles()) - bundles0)
            kinds: set = set()
            for b in new:
                try:
                    from bftkv_tpu.obs.recorder import read_manifest

                    kinds.update(
                        str(a.get("kind"))
                        for a in read_manifest(b).get("anomalies", [])
                    )
                except (OSError, ValueError):
                    pass
            entry["bundles"] = len(new)
            entry["bundle_anomalies"] = sorted(kinds)
            # Any anomaly kind that validly evidences THIS fault kind
            # satisfies the oracle, not just the one hit() matched
            # first: a slow_node verdict may be state-detected as
            # gray_member while the window's bundle was minted by the
            # equally-valid "fault" echo event — that bundle IS the
            # black box of this window, not a miss.
            ok_kinds = _BUNDLE_OK_KINDS.get(kind, {"fault"}) | {matched}
            if len(new) != 1 or not (kinds & ok_kinds):
                self.recorder_missing.append(dict(entry))
        self.detection.append(entry)

    # -- one full run ------------------------------------------------------

    def run_step(self, step: dict, dwell: float = 0.0) -> None:
        kind, target = step["kind"], step["target"]
        tag = f"s{step['step']}-{kind}"
        seq0 = (
            self.collector._anomaly_seq if self.collector is not None else 0
        )
        if kind == "partition":
            rules = self.partition(target)
            try:
                self.traffic(tag)
                self._observe_window(step, seq0)
                if dwell:
                    time.sleep(dwell)
            finally:
                self.heal(rules)
        elif kind == "region_partition":
            w0 = self.failures["write"]
            rules = self.region_partition(target)
            try:
                self.traffic(tag)
                self._observe_window(step, seq0)
                if dwell:
                    time.sleep(dwell)
            finally:
                self.heal(rules)
            if self.failures["write"] > w0:
                # The pool admits only regions whose seats fit every
                # plane's node-level f, so a whole-region outage may
                # slow writes (cross-region hedges), never fail them —
                # the DESIGN.md §21 acceptance bar.
                self.region_blocked.append(
                    {
                        "step": step["step"],
                        "region": target,
                        "failed_writes": self.failures["write"] - w0,
                    }
                )
        elif kind == "crash_restart":
            self.cluster.crash(target)
            try:
                self.traffic(tag)
                self._observe_window(step, seq0)
                if dwell:
                    time.sleep(dwell)
            finally:
                self.cluster.restart(target)
                if self.autopilot is not None:
                    # A restarted replica boots at epoch 0; re-deliver
                    # the current table or it would resurrect HRW
                    # routing for buckets that migrated away.
                    self.autopilot.reconcile()
        elif kind == "clock_skew":
            rules = self.clock_skew(target, step["delta"])
            try:
                self.traffic(tag)
                self._observe_window(step, seq0)
            finally:
                self.heal(rules)
        elif kind == "link_delay":
            rules = self.link_delay(target, step["seconds"])
            try:
                self.traffic(tag)
                self._observe_window(step, seq0)
            finally:
                self.heal(rules)
        elif kind == "slow_node":
            w0 = self.failures["write"]
            ev = self.registry.trace()
            step["_fp_seq0"] = ev[-1].seq if ev else 0
            rules = self.slow_node(
                target, step["seconds"], step.get("mode", "all")
            )
            try:
                self.traffic(tag)
                self._observe_window(step, seq0)
            finally:
                self.heal(rules)
            if self.failures["write"] > w0:
                # ≤f gray members may make a write SLOW, never make it
                # FAIL — the hedging/health-staging acceptance bar.
                self.gray_blocked.append(
                    {
                        "step": step["step"],
                        "target": target,
                        "mode": step.get("mode", "all"),
                        "failed_writes": self.failures["write"] - w0,
                    }
                )
        elif kind == "sidecar_crash":
            w0 = self.failures["write"]
            self.sidecar_ctl.crash()
            try:
                self.traffic(tag)
                self._observe_window(step, seq0)
                if dwell:
                    time.sleep(dwell)
            finally:
                self.sidecar_ctl.restart()
            if self.failures["write"] > w0:
                # The sidecar is an OPTIMIZER: its death may slow
                # writes (local crypto), never fail them.
                self.sidecar_blocked.append(
                    {
                        "step": step["step"],
                        "failed_writes": self.failures["write"] - w0,
                    }
                )
        elif kind == "overload":
            # The saturation oracle: clamp a real admission queue to
            # one slot, drive bursts past it for BFTKV_SAT_SCRAPES
            # consecutive scrapes, and require the capacity plane's
            # resource_saturated anomaly (DESIGN.md §20) to name the
            # clamped resource in the feed — the chaos-side proof that
            # the USE hysteresis fires on genuine induced overload,
            # not just in unit tests.
            adm, _label = self._overload_queue()
            saved = (adm.max_inflight, adm.max_queue, adm.max_wait)
            adm.max_inflight, adm.max_queue, adm.max_wait = 1, 1, 0.05
            try:
                k = max(flags.get_int("BFTKV_SAT_SCRAPES") or 3, 1)
                for _ in range(k + 1):
                    self.overload_burst(adm)
                    if self.collector is not None:
                        self.collector.scrape_once()
                self.traffic(tag)
                self._observe_window(step, seq0)
            finally:
                (
                    adm.max_inflight,
                    adm.max_queue,
                    adm.max_wait,
                ) = saved
        elif kind == "stale_replay":
            rules = byzantine.make_stale_replayer(self.registry, target)
            try:
                self.traffic(tag)
                self._observe_window(step, seq0)
            finally:
                self.registry.remove_all(rules)
        elif kind == "collude":
            rules = byzantine.make_colluder(self.registry, target)
            try:
                self.traffic(tag)
                self._observe_window(step, seq0)
            finally:
                self.registry.remove_all(rules)
        elif kind == "route_flap":
            self._route_flap(step, tag, seq0)
        else:  # pragma: no cover
            raise ValueError(f"unknown step kind {kind!r}")

    def _route_flap(self, step: dict, tag: str, seq0: int) -> None:
        """Epoch N+1 delivered to everyone EXCEPT ``target`` (a
        client) for one window: the window's own traffic keys are the
        moving buckets, so the stale client's writes land on the old
        owner, decline with the routing hint, and must re-route
        in-round — surfacing as ``server.epoch_stale`` →
        ``epoch_skew`` in the health feed.  Healing delivers the
        held-back table."""
        from bftkv_tpu.quorum.wotqs import route_bucket

        ap = self.autopilot
        target = step["target"]
        cl = self._client(0)
        qs = cl.qs
        nsh = qs.shard_count()
        owner = qs.effective_route()
        shard_of = qs.shard_of
        # Candidate moving buckets: this window's OWN write keys (the
        # three singles plus the per-shard batch keys traffic() will
        # select with the same arithmetic).  Buckets holding HISTORY
        # are excluded — an abrupt flip ships no pre-copy, so moving a
        # populated bucket would strand its records at the old owner
        # (readers route to the new one); fresh-key buckets carry
        # nothing and the fault still manifests on this window's
        # writes.  Real migrations move populated buckets through the
        # full pre-copy/dual/drain machinery instead.
        candidates = [f"chaos/{tag}/{i}".encode() for i in range(3)]
        for s in range(nsh):
            picked, i = 0, 0
            while picked < 2 and i < 4096:
                v = f"chaos/{tag}/batch/{s}/{i}".encode()
                i += 1
                if shard_of(v) == s:
                    candidates.append(v)
                    picked += 1
        forbidden = {route_bucket(v) for v in self._written}
        forbidden.add(route_bucket(b"chaos/once"))
        assign = {}
        for v in candidates:
            b = route_bucket(v)
            if b in forbidden:
                continue
            assign[b] = (owner[b] + 1) % nsh
        # Issued through the autopilot's linearized builder, so a
        # concurrently in-flight migration can neither mint the same
        # epoch nor lose its moves to this table.
        rt = ap.issue_table(assign, dual=False)
        ap.suppressed.add(target)
        try:
            ap.distribute(rt)
            self.traffic(tag)
            self._observe_window(step, seq0)
        finally:
            ap.suppressed.discard(target)
            ap.distribute(rt)  # heal: the held-back member catches up

    def run(
        self,
        steps: int = 4,
        dwell: float = 0.0,
        detect: bool = True,
        kinds: tuple | None = None,
    ) -> dict:
        """Arm, execute the seeded plan with traffic, repair, check.
        Returns a report dict (``violations`` empty = safe run;
        ``undetected`` empty = every fault surfaced in the health
        plane's anomaly feed within its own window; ``gray_blocked``
        empty = no slow_node window ever blocked a commit)."""
        plan = self.plan(steps, kinds=kinds)
        # Shard layout before the run: if it survives unchanged (no
        # membership churn rerouted the keyspace), the checker may apply
        # the strict one-shard-per-variable invariant.
        shard_map_before = self.cluster.shard_map()
        self.registry.arm(self.seed)
        self.wan = None
        if self.rtt_spec:
            # Arm cleared the rule table; compile the deployment
            # geography onto it FIRST — quiet background rules, so a
            # fault rule armed later at the same point always wins and
            # the trace/anomaly feed stays fault-only (DESIGN.md §21).
            from bftkv_tpu.regions.topology import install_matrix

            self.wan = install_matrix(self.registry, self.rtt_spec)
        self.detection = []  # a re-run must not inherit stale verdicts
        self.gray_blocked = []
        self.sidecar_blocked = []
        self.region_blocked = []
        self.recorder_missing = []
        self._migration = None
        self.collector = self._make_collector() if detect else None
        self.recorder = None
        if self.collector is not None and flags.enabled(
            "BFTKV_FLIGHT_RECORDER"
        ):
            import tempfile

            from bftkv_tpu.obs.recorder import FlightRecorder

            rdir = flags.raw("BFTKV_RECORDER_DIR") or tempfile.mkdtemp(
                prefix="bftkv-nemesis-blackbox-"
            )
            # Bundle-count cap must clear the schedule: one bundle per
            # fault window is the oracle, eviction mid-run would fake a
            # missing bundle.
            self.recorder = FlightRecorder(
                rdir,
                fp_registry=self.registry,
                max_bundles=max(2 * steps + 8, 32),
            ).add_to(self.collector)
        self.autopilot = None
        if self._want_autopilot:
            from bftkv_tpu.autopilot import Autopilot

            self.autopilot = Autopilot.for_cluster(
                self.cluster, collector=self.collector
            )
        epoch_of = getattr(
            self._client(0).qs, "route_epoch", lambda: 0
        )
        epoch_before = epoch_of()
        try:
            if self.collector is not None:
                # Baseline scrape: counter-delta anomalies measure from
                # here, and every member's shard seat is on file before
                # the first fault lands.
                self.collector.scrape_once()
            cl = self._client(0)
            once_var, once_val = b"chaos/once", b"immutable"
            cl.write_once(once_var, once_val)
            self.cluster.recorder.write_once_ok("u01", once_var, once_val)
            self.traffic("baseline")
            mig_thread: threading.Thread | None = None
            for i, step in enumerate(plan):
                if (
                    self.autopilot is not None
                    and mig_thread is None
                    and i >= len(plan) // 2
                    and self._client(0).qs.shard_count() > 1
                ):
                    # ONE forced hot-shard migration, paced so the
                    # remaining fault steps land INSIDE the pre-copy /
                    # flip / drain phases — reconfiguration under
                    # chaos is the thing under test.
                    mig_thread = threading.Thread(
                        target=self._forced_migration, daemon=True
                    )
                    mig_thread.start()
                self.run_step(step, dwell=dwell)
            if mig_thread is not None:
                mig_thread.join(timeout=240)
                if mig_thread.is_alive():
                    self._migration = {"ok": False, "error": "timeout"}
            self.traffic("final")
            try:
                self.cluster.recorder.read_ok(
                    "u01", once_var, cl.read(once_var)
                )
            except Exception as e:
                self.cluster.recorder.read_fail("u01", once_var, e)
            # Collapsed writes certify on an async tail; quiesce every
            # client's tails before convergence + the final safety
            # check, so "back-fill still in flight" can never be
            # mistaken for a violation (or mask one).  Gateways write
            # through their own internal clients — drain those too.
            for cl in list(self.cluster.clients) + [
                gw.client for gw in getattr(self.cluster, "gateways", ())
            ]:
                drain = getattr(cl, "drain_tails", None)
                if drain is not None:
                    drain()
            converged = self.converge(
                max_rounds=10 if self.autopilot is not None else 6
            )
            trace = self.registry.trace()
            if self.collector is not None:
                # Post-repair scrape: restarted members flip back to up
                # (member_up anomalies close the windows).
                self.collector.scrape_once()
        finally:
            self.registry.disarm()
        shard_map = self.cluster.shard_map()
        epoch_after = epoch_of()
        routing_changed = epoch_after != epoch_before
        checker = SafetyChecker(
            self.cluster.recorder,
            f=self.cluster.f,
            shard_of_node=shard_map,
            # Strict one-shard-per-variable only when NOTHING rerouted
            # the keyspace: same seats AND same route epoch.  An epoch
            # change legitimately migrates certified history between
            # cliques (invariant 5's weak form still applies).
            routing_stable=(
                shard_map == shard_map_before and not routing_changed
            ),
            routing_changed=routing_changed,
        )
        replicas = self.cluster.storage_servers or self.cluster.servers
        violations = checker.check(replicas)
        # Retirement acceptance is a recorded-history check, not mere
        # absence of errors: every certified record the migrated
        # buckets held must be readable from the new owners.
        if self._migration is not None and not self._migration.get("ok"):
            violations = violations + [
                f"autopilot migration failed: {self._migration}"
            ]
        autopilot_doc = None
        if self.autopilot is not None:
            autopilot_doc = {
                "migration": self._migration,
                "status": self.autopilot.status(),
            }
        return {
            "seed": self.seed,
            "shards": len(set(shard_map.values())) if shard_map else 1,
            "regions": (
                self.cluster.universe.regions and
                sorted({
                    r
                    for k, r in self.cluster.universe.regions.items()
                    if "://" not in k and ":" not in k
                })
                or None
            ),
            "rtt_matrix": (
                self.wan[0].describe() if self.wan else None
            ),
            "route_epoch": epoch_after,
            "workload": (
                {
                    "spec": self.workload.canonical(),
                    "ops_drained": self._wl_cursor,
                }
                if self.workload is not None
                else None
            ),
            "autopilot": autopilot_doc,
            "plan": plan,
            "converged": converged,
            "faults_fired": len(trace),
            "fault_trace": [list(e) for e in trace[:200]],
            "failures": dict(self.failures),
            "violations": violations,
            "detection": self.detection,
            "undetected": [d for d in self.detection if not d["detected"]],
            "gray_blocked": self.gray_blocked,
            "sidecar_blocked": self.sidecar_blocked,
            "region_blocked": self.region_blocked,
            "recorder": (
                {
                    "dir": self.recorder.dir,
                    "bundles": self.recorder.bundle_count,
                    "coalesced": self.recorder.coalesced,
                    "missing": self.recorder_missing,
                }
                if self.recorder is not None
                else None
            ),
            "recorder_missing": self.recorder_missing,
            "anomalies": (
                len(self.collector.anomalies())
                if self.collector is not None
                else None
            ),
        }


def main(argv: list[str] | None = None) -> int:
    import argparse
    import json

    ap = argparse.ArgumentParser(
        description="seeded chaos round against an in-process cluster"
    )
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--steps", type=int, default=4)
    ap.add_argument("--servers", type=int, default=4,
                    help="quorum servers per shard")
    ap.add_argument("--rw", type=int, default=4,
                    help="storage nodes per shard")
    ap.add_argument("--shards", type=int, default=1,
                    help="disjoint quorum cliques: faults then straddle "
                         "shard boundaries and the checker enforces the "
                         "cross-shard invariant")
    ap.add_argument("--gateways", type=int, default=0,
                    help="run N edge gateways in-process: every traffic "
                         "window crosses the certified cache (write + "
                         "read-back + cold fill), gateway links join "
                         "the partition/link_delay target pool, and "
                         "checker invariant 3 proves no uncertified "
                         "value was ever served through the cache")
    ap.add_argument("--regions", type=int, default=0,
                    help="label every principal round-robin into N "
                         "regions and install the process region map: "
                         "locality-aware staging gets a geography to "
                         "rank, the fleet collector grows region rows "
                         "with the region-level f-budget, and the "
                         "region_partition kind becomes eligible")
    ap.add_argument("--rtt-matrix",
                    default=flags.raw("BFTKV_WAN_RTT_MATRIX") or "",
                    help="WAN link-delay program (regions/topology.py): "
                         "a named matrix (wan2, wan3) or an RTT spec in "
                         "ms, compiled onto quiet background "
                         "transport.send delay rules so the whole "
                         "schedule runs under deployment geography; "
                         "needs --regions (default: BFTKV_WAN_RTT_MATRIX)")
    ap.add_argument("--workload",
                    default=flags.raw("BFTKV_WORKLOAD") or "",
                    help="drive spec-shaped traffic through every "
                         "window on top of the coverage burst: a "
                         "workload spec `preset[,k=v,...]` "
                         "(bftkv_tpu/workload/spec.py, e.g. "
                         "`storm,seed=7`); the op stream position "
                         "advances across windows so one seed replays "
                         "one schedule (default: BFTKV_WORKLOAD)")
    ap.add_argument("--bits", type=int, default=1024)
    ap.add_argument("--dwell", type=float, default=0.0,
                    help="extra seconds to hold each fault window open")
    ap.add_argument("--json", action="store_true",
                    help="print the full report as JSON")
    ap.add_argument("--no-detect", action="store_true",
                    help="skip the fleet-collector detection assertion "
                         "(safety checking only)")
    ap.add_argument("--kinds", default="",
                    help="comma-separated step-kind pool override "
                         "(e.g. a slow_node-heavy soak: "
                         "--kinds slow_node,link_delay,crash_restart; "
                         "route_flap needs --autopilot and --shards 2+)")
    ap.add_argument("--autopilot", action="store_true",
                    help="run the topology autopilot against the "
                         "cluster: one forced hot-shard migration "
                         "executes WHILE the second half of the fault "
                         "schedule lands (pre-copy / flip / drain under "
                         "chaos), crash-restarted replicas are "
                         "re-delivered the current route table, and "
                         "the route_flap kind becomes available")
    ap.add_argument("--storage", choices=["mem", "log"], default="mem",
                    help="replica storage engine: `log` gives every "
                         "replica its own on-disk §19 segment-log "
                         "directory, so crash_restart re-opens the "
                         "SAME log dir (index rebuild + torn-tail "
                         "truncation under chaos)")
    ap.add_argument("--sidecar", action="store_true",
                    help="route the whole cluster's verify+sign through "
                         "an embedded shared crypto sidecar and add the "
                         "sidecar_crash kind to the fault pool: a dead "
                         "sidecar must cost zero failed writes (local "
                         "fallback), surface as the sidecar_down "
                         "anomaly, and reconnect must re-register "
                         "sign-key handles")
    args = ap.parse_args(argv)

    kinds = tuple(
        k.strip() for k in args.kinds.split(",") if k.strip()
    ) or None
    if kinds and any(k not in STEP_KINDS for k in kinds):
        ap.error(f"--kinds must draw from {STEP_KINDS}")
    if kinds and "route_flap" in kinds and not (
        args.autopilot and args.shards > 1
    ):
        ap.error("--kinds route_flap needs --autopilot and --shards 2+")
    if kinds and "sidecar_crash" in kinds and not args.sidecar:
        ap.error("--kinds sidecar_crash needs --sidecar")
    if kinds and "region_partition" in kinds and args.regions < 2:
        ap.error("--kinds region_partition needs --regions 2+")
    if args.rtt_matrix and args.regions < 2:
        ap.error("--rtt-matrix needs --regions 2+")
    if args.workload:
        from bftkv_tpu.workload.spec import parse_spec

        try:
            parse_spec(args.workload)
        except ValueError as e:
            ap.error(f"--workload: {e}")

    # The sidecar's dispatchers are process-global, so it arms BEFORE
    # the cluster boots: every server's share issuance and collective
    # verify then routes through the service under test.
    sidecar_ctl = SidecarHarness() if args.sidecar else None
    storage_factory = MemStorage
    log_root = None
    if args.storage == "log":
        import tempfile

        from bftkv_tpu.storage.logkv import LogStorage

        log_root = tempfile.TemporaryDirectory(prefix="bftkv-nemesis-log-")
        counter = iter(range(10_000))

        def storage_factory(root=log_root.name):
            # One log dir per replica; crash_restart re-opens the same
            # dir via the harness's reopen() hook.  fsync stays ON —
            # the soak exercises the real durability path; the tiny
            # segment size forces seals + compaction within the run.
            return LogStorage(
                os.path.join(root, f"replica-{next(counter):03d}"),
                segment_bytes=256 * 1024,
            )

    cluster = build_cluster(
        args.servers, 1, args.rw, bits=args.bits, n_shards=args.shards,
        n_gateways=args.gateways, storage_factory=storage_factory,
        n_regions=args.regions,
    )
    try:
        report = Nemesis(
            cluster, seed=args.seed, autopilot=args.autopilot,
            sidecar_ctl=sidecar_ctl, rtt_spec=args.rtt_matrix or None,
            workload=args.workload or None,
        ).run(
            steps=args.steps, dwell=args.dwell,
            detect=not args.no_detect, kinds=kinds,
        )
    finally:
        cluster.stop()
        if sidecar_ctl is not None:
            sidecar_ctl.stop()
        if log_root is not None:
            for srv in cluster.all_servers:
                close = getattr(srv.storage, "close", None)
                if close is not None:
                    close()
            log_root.cleanup()
    # Lock-order chaos soak (DESIGN.md §16): with BFTKV_LOCKWATCH=1 the
    # whole schedule ran under the runtime lock sanitizer — any cycle in
    # the acquisition-order graph or blocking call under a watched lock
    # fails the soak exactly like a safety violation.
    from bftkv_tpu.devtools import lockwatch

    report["lockwatch"] = (
        lockwatch.report() if lockwatch.enabled() else None
    )
    lockwatch_msg = (
        lockwatch.fail_message() if lockwatch.enabled() else None
    )
    # Workload-armed oracle (DESIGN.md §23): spec-shaped traffic must
    # degrade under faults, never fail.  Coverage-only runs keep the
    # historical count-don't-raise behavior.
    workload_failed_writes = (
        report["failures"]["write"] if report.get("workload") else 0
    )
    failed = bool(
        report["violations"]
        or not report["converged"]
        or report["undetected"]
        or report["gray_blocked"]
        or report["sidecar_blocked"]
        or report["region_blocked"]
        or report["recorder_missing"]
        or workload_failed_writes
        or lockwatch_msg
    )
    if args.json:
        print(json.dumps(report, indent=2, default=repr))
        return 1 if failed else 0
    detected = [d for d in report["detection"] if d["detected"]]
    print(
        f"nemesis seed={report['seed']} shards={report['shards']} "
        f"steps={len(report['plan'])} "
        f"faults_fired={report['faults_fired']} "
        f"failures={report['failures']} converged={report['converged']} "
        f"detected={len(detected)}/{len(report['detection'])}"
    )
    if report.get("autopilot"):
        mig = report["autopilot"]["migration"]
        print(
            f"autopilot: route epoch {report['route_epoch']} · "
            + (
                "no migration ran"
                if mig is None
                else (
                    f"{mig.get('kind', '?')} shard {mig.get('shard')} → "
                    f"{mig.get('targets')} "
                    f"({mig.get('buckets')} buckets) "
                    + ("ok" if mig.get("ok") else "FAILED")
                )
            )
        )
    for v in report["violations"]:
        print(f"VIOLATION: {v}")
    for d in report["undetected"]:
        print(
            f"UNDETECTED: step {d['step']} {d['kind']} on {d['target']} "
            "never surfaced in the health feed"
        )
    for g in report["gray_blocked"]:
        print(
            f"GRAY BLOCKED: step {g['step']} slow_node({g['mode']}) on "
            f"{g['target']} failed {g['failed_writes']} write(s) — a "
            "single gray member must never block commit"
        )
    for s in report["sidecar_blocked"]:
        print(
            f"SIDECAR BLOCKED: step {s['step']} sidecar_crash failed "
            f"{s['failed_writes']} write(s) — a dead crypto sidecar "
            "must degrade to local crypto, never block a write"
        )
    for r in report["region_blocked"]:
        print(
            f"REGION BLOCKED: step {r['step']} region_partition on "
            f"{r['region']} failed {r['failed_writes']} write(s) — an "
            "in-budget whole-region outage must never block a write"
        )
    if report.get("recorder"):
        r = report["recorder"]
        print(
            f"flight recorder: {r['bundles']} bundle(s) "
            f"({r['coalesced']} coalesced) under {r['dir']}"
        )
    for rm in report["recorder_missing"]:
        print(
            f"NO BUNDLE: step {rm['step']} {rm['kind']} on "
            f"{rm['target']} detected as {rm['anomaly']} but the window "
            f"minted {rm.get('bundles', 0)} bundle(s) naming "
            f"{rm.get('bundle_anomalies', [])} — the black box missed it"
        )
    if report["violations"]:
        print("nemesis: SAFETY VIOLATIONS FOUND")
        return 1
    if not report["converged"]:
        print("nemesis: replicas did not converge")
        return 1
    if report["undetected"]:
        print("nemesis: FAULTS INVISIBLE TO THE HEALTH PLANE")
        return 1
    if report["gray_blocked"]:
        print("nemesis: GRAY MEMBER BLOCKED COMMITS")
        return 1
    if report["sidecar_blocked"]:
        print("nemesis: SIDECAR DEATH BLOCKED WRITES")
        return 1
    if report["region_blocked"]:
        print("nemesis: REGION OUTAGE BLOCKED WRITES")
        return 1
    if report["recorder_missing"]:
        print("nemesis: FAULT WINDOWS WITHOUT A FLIGHT-RECORDER BUNDLE")
        return 1
    if workload_failed_writes:
        print(
            f"nemesis: WORKLOAD WRITES FAILED "
            f"({workload_failed_writes}) — spec-shaped load must "
            f"degrade under faults, never fail"
        )
        return 1
    if lockwatch_msg:
        print(lockwatch_msg)
        print("nemesis: LOCKWATCH FINDINGS (cycle or I/O under lock)")
        return 1
    print(
        "nemesis: ok (zero safety violations; every fault window "
        "visible in the health feed; no gray member blocked a commit"
        + ("; lockwatch clean)" if lockwatch.enabled() else ")")
    )
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
