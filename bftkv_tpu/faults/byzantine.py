"""Byzantine behaviors as failpoint handler programs.

The reference expresses misbehavior by subclassing the real server
(reference: protocol/malserver_test.go:23-194); ``tests/mal_utils.py``
kept that shape.  These are the same behaviors factored into plain
functions with the ``server.admission`` handler-override signature
``fn(server, cmd, req, peer, sender) -> bytes | None``, so one
implementation serves both worlds:

- the chaos nemesis installs them as failpoint rules
  (:func:`make_colluder`, :func:`make_stale_replayer`) — a replica
  turns Byzantine for a scheduled window and back, no subclass swap;
- ``mal_utils.MalServer`` stays a subclass shim whose overridden
  handlers delegate here, keeping the existing Byzantine test suite
  green on the shared mechanism.

None of these behaviors can create authority: honest replicas still
run the full admission path, which is exactly what the chaos checker
verifies.
"""

from __future__ import annotations

from bftkv_tpu import packet as pkt

__all__ = [
    "sign_anything",
    "store_unverified",
    "batch_sign_anything",
    "batch_store_unverified",
    "write_sign_anything",
    "batch_time_skew",
    "stale_replay_read",
    "make_colluder",
    "make_stale_replayer",
]


def sign_anything(server, cmd, req, peer, sender):
    """Sign whatever arrives: no writer-sig verify, no quorum
    certificate, no equivocation check (reference: malSign,
    malserver_test.go:64-89)."""
    pkt.parse(req)
    tbss = pkt.tbss(req)
    share = server.crypt.collective.sign(server.crypt.signer, tbss)
    return pkt.serialize_signature(share)


def store_unverified(server, cmd, req, peer, sender):
    """Store without any verification; conflicting values are kept when
    the storage supports a mal side area (reference: malWrite,
    malserver_test.go:91-112)."""
    p = pkt.parse(req)
    mal_write = getattr(server.storage, "mal_write", None)
    if mal_write is not None:
        mal_write(p.variable or b"", p.t, req)
    else:
        server.storage.write(p.variable or b"", p.t, req)
    return None


def batch_sign_anything(server, cmd, req, peer, sender):
    """The batch pipeline facing the same adversary: every item of the
    batch signed unverified."""
    results = []
    for r in pkt.parse_list(req):
        pkt.parse(r)
        share = server.crypt.collective.sign(server.crypt.signer, pkt.tbss(r))
        results.append((None, pkt.serialize_signature(share)))
    return pkt.serialize_results(results)


def batch_store_unverified(server, cmd, req, peer, sender):
    results = []
    mal_write = getattr(server.storage, "mal_write", None)
    for r in pkt.parse_list(req):
        p = pkt.parse(r)
        if mal_write is not None:
            mal_write(p.variable or b"", p.t, r)
        else:
            server.storage.write(p.variable or b"", p.t, r)
        results.append((None, b""))
    return pkt.serialize_results(results)


def write_sign_anything(server, cmd, req, peer, sender):
    """The round-collapsed write facing the colluder: sign whatever
    arrives AND store it unverified, acking with a genuine share —
    the piggybacked analog of sign_anything + store_unverified.  The
    honest quorum's checks (strict timestamps, equivocation-free share
    issuance, collective verification against the owner quorum) are
    what keep this harmless, which is exactly what the chaos checker
    asserts."""
    p = pkt.parse(req)
    share = server.crypt.collective.sign(server.crypt.signer, pkt.tbss(req))
    mal_write = getattr(server.storage, "mal_write", None)
    if mal_write is not None:
        mal_write(p.variable or b"", p.t, req)
    else:
        server.storage.write(p.variable or b"", p.t, req)
    return pkt.serialize_ws_ack(share=pkt.serialize_signature(share))


def batch_time_skew(server, cmd, req, peer, sender):
    """Answer every batched TIME item with a wildly inflated
    timestamp — the Byzantine clock answer a reader's max() absorbs
    (timestamps only order versions; a jump is legal, a rollback is
    what the monotonicity invariant forbids).  Also the colluder's
    guaranteed-manifest surface: BATCH_TIME fans to the FULL quorum,
    while the staged WRITE_SIGN/SIGN waves may never ask a replica
    outside the minimal prefix at all."""
    items = pkt.parse_list(req)
    fake = (1 << 40).to_bytes(8, "big")
    return pkt.serialize_results([(None, fake)] * len(items))


def stale_replay_read(server, cmd, req, peer, sender):
    """Answer a read with the OLDEST completed version — a genuinely
    signed but stale record.  An honest reader's deterministic
    resolution must still return the newest committed value."""
    p = pkt.parse(req)
    variable = p.variable or b""
    for t in sorted(server.storage.versions(variable)):
        try:
            raw = server.storage.read(variable, t)
        except Exception:
            continue
        try:
            cp = pkt.parse(raw)
        except Exception:
            continue
        if cp.ss is not None and cp.ss.completed:
            return raw
    return None  # nothing committed: indistinguishable from empty


#: The colluder behavior set, keyed by command name — what
#: ``mal_utils.MalServer`` does, as one table.
COLLUDER_HANDLERS = {
    "sign": sign_anything,
    "write": store_unverified,
    "batch_sign": batch_sign_anything,
    "batch_write": batch_store_unverified,
    "write_sign": write_sign_anything,
    "batch_time": batch_time_skew,
}


def make_colluder(registry, node_name: str) -> list:
    """Program one replica as a full colluder via failpoint rules on
    ``server.admission``; returns the rules (remove to heal)."""
    return [
        registry.add(
            "server.admission",
            "handle",
            match={"node": node_name, "cmd": cmd},
            fn=fn,
            rule_id=f"colluder:{node_name}:{cmd}",
        )
        for cmd, fn in sorted(COLLUDER_HANDLERS.items())
    ]


def make_stale_replayer(registry, node_name: str) -> list:
    """Program one replica to answer every single-read with its oldest
    completed version."""
    return [
        registry.add(
            "server.admission",
            "handle",
            match={"node": node_name, "cmd": "read"},
            fn=stale_replay_read,
            rule_id=f"stale:{node_name}:read",
        )
    ]
