"""In-package loopback chaos cluster.

``tests/cluster_utils.py`` builds in-process clusters for the test
suite; the nemesis CLI (``python -m bftkv_tpu.faults.nemesis``) needs
the same capability *inside* the package — plus two chaos-specific
powers the test fixture doesn't have:

- every replica's storage is wrapped in a
  :class:`~bftkv_tpu.faults.checker.RecordingStorage` feeding one
  shared :class:`~bftkv_tpu.faults.checker.HistoryRecorder`;
- :meth:`ChaosCluster.crash` / :meth:`ChaosCluster.restart` model a
  real crash-restart: the old ``Server`` object is abandoned, a fresh
  one is built from the same identity **onto the same storage** (the
  in-process analog of restarting a daemon on its data dir), so
  anti-entropy has to converge the rejoined replica.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from bftkv_tpu import topology
from bftkv_tpu.faults.checker import HistoryRecorder, RecordingStorage
from bftkv_tpu.protocol.client import Client
from bftkv_tpu.protocol.server import Server
from bftkv_tpu.storage.memkv import MemStorage
from bftkv_tpu.transport.loopback import LoopbackNet, TrLoopback

__all__ = ["ChaosCluster", "build_cluster"]


@dataclass
class ChaosCluster:
    universe: topology.Universe
    net: LoopbackNet
    recorder: HistoryRecorder
    servers: list[Server] = field(default_factory=list)  # quorum (a*)
    storage_servers: list[Server] = field(default_factory=list)  # rw*
    clients: list[Client] = field(default_factory=list)
    gateways: list = field(default_factory=list)  # bftkv_tpu.gateway
    gateway_addrs: dict[str, str] = field(default_factory=dict)
    _by_name: dict[str, Server] = field(default_factory=dict)
    _idents: dict[str, object] = field(default_factory=dict)

    def gateway_names(self) -> list[str]:
        return [gw.self_node.name for gw in self.gateways]

    def gateway_client(self, i: int = 0, *, verify: bool = True):
        from bftkv_tpu.gateway import GatewayClient, GatewayPeer

        client = self.clients[i % len(self.clients)]
        peers = [
            GatewayPeer(
                client.crypt.keyring.get(gw.self_node.get_self_id()),
                self.gateway_addrs[gw.self_node.name],
            )
            for gw in self.gateways
        ]
        return GatewayClient(client, peers, verify=verify)

    @property
    def all_servers(self) -> list[Server]:
        return self.servers + self.storage_servers

    @property
    def f(self) -> int:
        """Fault bound of the replica group chaos targets (the storage
        replicas when present, else the quorum servers).  Sharded
        clusters use the PER-SHARD group size: each shard tolerates its
        own f, and the checker's commit threshold must match the quorum
        a single shard actually forms."""
        n = len(self.storage_servers) or len(self.servers)
        nsh = len(self.universe.shards)
        if nsh > 1:
            n = max(1, n // nsh)
        return (n - 1) // 3

    def server_named(self, name: str) -> Server:
        return self._by_name[name]

    def names(self, storage_only: bool = True) -> list[str]:
        if len(self.universe.shards) > 1:
            # Sharded cluster: chaos targets span BOTH planes of every
            # shard — faults must be able to straddle shard boundaries.
            return [
                i.name
                for i in self.universe.servers + self.universe.storage_nodes
            ]
        idents = (
            self.universe.storage_nodes
            if storage_only and self.universe.storage_nodes
            else self.universe.servers
        )
        return [i.name for i in idents]

    def shard_map(self) -> dict[str, int] | None:
        """Replica name -> shard index (clique membership or storage
        assignment), or None for unsharded clusters — the checker's
        cross-shard invariant input."""
        out: dict[str, int] = {}
        sharded = False
        for name, srv in self._by_name.items():
            idx_of = getattr(srv.qs, "shard_index_of", None)
            if idx_of is None:
                continue
            idx = idx_of(srv.self_node.get_self_id())
            if idx is not None:
                out[name] = idx
                sharded = True
        return out if sharded else None

    # -- crash / restart ---------------------------------------------------

    def crash(self, name: str) -> None:
        """Take the replica dark: transport unregistered, peers see
        unreachable.  State (the recording storage) survives."""
        self._by_name[name].tr.stop()

    def restart(self, name: str) -> Server:
        """Fresh Server from the same identity onto the same storage —
        the crash-restart the anti-entropy plane must repair."""
        old = self._by_name[name]
        old.tr.stop()  # idempotent when already crashed
        # Disk-backed engines (§19 log): a real restart re-opens the
        # data dir — drop the in-RAM index, rebuild from the segment
        # scan, truncate any torn tail.  The RecordingStorage wrapper
        # passes reopen() through; memory backends have none.
        reopen = getattr(old.storage, "reopen", None)
        if reopen is not None:
            reopen()
        ident = self._idents[name]
        graph, crypt, qs = topology.make_node(
            ident, self.universe.view_of(ident)
        )
        srv = type(old)(
            graph, qs, TrLoopback(crypt, self.net), crypt, old.storage
        )
        srv.start()
        self._by_name[name] = srv
        for pool in (self.servers, self.storage_servers):
            for i, s in enumerate(pool):
                if s is old:
                    pool[i] = srv
        return srv

    def stop(self) -> None:
        for gw in self.gateways:
            gw.stop()
        for s in self.all_servers:
            s.tr.stop()
        if self.universe.regions:
            # Process-global geography must not outlive its fleet.
            from bftkv_tpu import regions

            regions.clear()


def build_cluster(
    n_servers: int = 4,
    n_users: int = 1,
    n_rw: int = 4,
    *,
    bits: int = 1024,
    recorder: HistoryRecorder | None = None,
    server_cls=Server,
    storage_factory=MemStorage,
    n_shards: int = 1,
    n_gateways: int = 0,
    n_regions: int = 0,
) -> ChaosCluster:
    uni = topology.build_universe(
        n_servers, n_users, n_rw, scheme="loop", bits=bits,
        n_shards=n_shards, n_gateways=n_gateways, n_regions=n_regions,
    )
    if uni.regions:
        from bftkv_tpu import regions

        regions.install(uni.regions)
    net = LoopbackNet()
    recorder = recorder or HistoryRecorder()
    cluster = ChaosCluster(universe=uni, net=net, recorder=recorder)
    for ident in uni.servers + uni.storage_nodes:
        graph, crypt, qs = topology.make_node(ident, uni.view_of(ident))
        storage = RecordingStorage(
            storage_factory(), ident.name, recorder
        )
        srv = server_cls(graph, qs, TrLoopback(crypt, net), crypt, storage)
        srv.start()
        cluster._by_name[ident.name] = srv
        cluster._idents[ident.name] = ident
        if ident in uni.servers:
            cluster.servers.append(srv)
        else:
            cluster.storage_servers.append(srv)
    for i, ident in enumerate(uni.users):
        graph, crypt, qs = topology.make_node(ident, uni.view_of(ident))
        tr = TrLoopback(crypt, net)
        tr.link_id = ident.name  # clients are partitionable links too
        cluster.clients.append(Client(graph, qs, tr, crypt))
    for ident in uni.gateways:
        from bftkv_tpu.gateway import Gateway

        graph, crypt, qs = topology.make_node(ident, uni.view_of(ident))
        gw = Gateway(graph, qs, TrLoopback(crypt, net), crypt)
        dial = uni.gateway_addrs[ident.name]
        gw.start(dial.split("://", 1)[-1])
        cluster.gateways.append(gw)
        cluster.gateway_addrs[ident.name] = dial
    return cluster
