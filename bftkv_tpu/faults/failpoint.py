"""Seeded, deterministic failpoint registry.

A **failpoint** is a named hook woven into a production code path
(``transport.send``, ``server.admission``, ``storage.write``,
``server.time``, ``dispatch.flush``, ``sync.round``).  The hook calls
:func:`fire` with a small context dict; armed rules matching that
context return an :class:`Action` the hook site interprets (drop the
post, sleep, corrupt the payload, raise an error, run a Byzantine
handler instead, ...).

Two properties the whole chaos harness leans on:

- **Zero overhead disarmed.**  Hook sites guard with ``if fp.ARMED:``
  — one module-attribute load and branch — before building the context
  dict, and :func:`fire` itself re-checks.  ``bench.py cluster_4`` with
  failpoints disarmed must be within noise of a build without them.
- **Determinism from one seed.**  Every probabilistic decision (fire /
  skip, delay length, corrupt offset) is ``sha256(seed | rule_id | n)``
  where ``n`` is that rule's evaluation counter — *not* a shared RNG
  stream.  A deterministic call sequence therefore yields a
  byte-identical fault trace for the same seed, and concurrent rules
  cannot perturb each other's draws (within one rule, concurrent calls
  take counter values in arrival order: the decision *set* is fixed,
  only its assignment to threads may vary).

The registry records every fired event into a bounded trace
(:meth:`FaultRegistry.trace`) and counts them as ``faults.fired``
metrics labeled by (point, action) — both closed enums.
"""

from __future__ import annotations

import hashlib
from collections import deque
from typing import NamedTuple

from bftkv_tpu.metrics import registry as metrics
from bftkv_tpu.devtools.lockwatch import named_lock

__all__ = [
    "ARMED",
    "Action",
    "FaultEvent",
    "FaultRegistry",
    "Rule",
    "arm",
    "disarm",
    "fire",
    "registry",
    "corrupt_bytes",
    "delay_seconds",
    "link_of",
]

#: Global arm flag.  Hook sites read ``failpoint.ARMED`` (module
#: attribute, not a from-import — the value must be current) before
#: paying for context construction.
ARMED = False


class Action:
    """What a fired rule tells the hook site to do."""

    __slots__ = ("kind", "params", "rule")

    def __init__(self, kind: str, params: dict, rule: "Rule"):
        self.kind = kind
        self.params = params
        self.rule = rule

    def __repr__(self) -> str:  # pragma: no cover
        return f"Action({self.kind!r}, {self.params!r})"


class FaultEvent(NamedTuple):
    """One fired failpoint — the unit of the reproducible fault trace.
    ``eval_n`` is the rule's evaluation counter at fire time, so two
    runs with the same seed and call sequence produce identical lists."""

    seq: int
    point: str
    rule_id: str
    eval_n: int
    kind: str


class Rule:
    """One armed behavior at one failpoint.

    ``match``: ``None`` (always), a dict of context-key → expected
    value (or predicate over the value), or a predicate over the whole
    context dict.  ``prob``: fire probability per matching evaluation,
    decided by the seed-hash draw.  ``times``: max fires (``None`` =
    unlimited).  ``quiet`` rules fire without tracing or counting —
    the WAN topology plane uses them: a link delay that *is* the
    deployment geography is an environment, not a fault, and must not
    flood the trace or the ``fault_injected`` anomaly feed.  A
    ``background`` rule is evaluated only after every foreground rule
    at its point declined — so an always-matching topology delay can
    never shadow a nemesis step's drop rule added later at the same
    hook.  Remaining kwargs land in ``Action.params``.
    """

    __slots__ = (
        "point",
        "rule_id",
        "kind",
        "params",
        "match",
        "prob",
        "times",
        "enabled",
        "quiet",
        "background",
        "_evals",
        "_fires",
    )

    def __init__(
        self,
        point: str,
        kind: str,
        *,
        rule_id: str,
        match=None,
        prob: float = 1.0,
        times: int | None = None,
        quiet: bool = False,
        background: bool = False,
        **params,
    ):
        self.point = point
        self.rule_id = rule_id
        self.kind = kind
        self.params = params
        self.match = match
        self.prob = prob
        self.times = times
        self.enabled = True
        self.quiet = quiet
        self.background = background
        self._evals = 0
        self._fires = 0

    @property
    def fires(self) -> int:
        return self._fires

    def _matches(self, ctx: dict) -> bool:
        m = self.match
        if m is None:
            return True
        if callable(m):
            return bool(m(ctx))
        for k, want in m.items():
            have = ctx.get(k)
            if callable(want):
                if not want(have):
                    return False
            elif have != want:
                return False
        return True


def _draws(seed: int, rule_id: str, n: int) -> tuple[float, float]:
    """Two uniforms in [0, 1): the fire decision and the parameter
    draw, both pure functions of (seed, rule, evaluation index)."""
    h = hashlib.sha256(f"{seed}|{rule_id}|{n}".encode()).digest()
    return (
        int.from_bytes(h[:8], "big") / 2**64,
        int.from_bytes(h[8:16], "big") / 2**64,
    )


class FaultRegistry:
    """Process-wide rule set + reproducible fault trace."""

    TRACE_MAX = 65536

    def __init__(self):
        self._lock = named_lock("faults.registry")
        self._rules: dict[str, list[Rule]] = {}
        self._seed = 0
        self._seq = 0
        self._events: deque[FaultEvent] = deque(maxlen=self.TRACE_MAX)

    # -- lifecycle --------------------------------------------------------

    @property
    def seed(self) -> int:
        return self._seed

    def arm(self, seed: int = 0) -> "FaultRegistry":
        """Arm the hooks; all decisions derive from ``seed``.  Clears
        any previous rules and trace so a run starts from a clean
        deterministic state.  The armed registry becomes the ACTIVE
        one :func:`fire` dispatches to (last arm wins) — so a harness
        may run its own ``FaultRegistry`` instance and the hook sites
        still see its rules."""
        global ARMED, _active
        with self._lock:
            self._rules.clear()
            self._events.clear()
            self._seq = 0
            self._seed = seed
        _active = self
        ARMED = True
        return self

    def disarm(self) -> None:
        """Back to the zero-overhead no-op state."""
        global ARMED, _active
        ARMED = False
        _active = registry
        with self._lock:
            self._rules.clear()
            self._events.clear()
            self._seq = 0

    # -- rules ------------------------------------------------------------

    def add(
        self,
        point: str,
        kind: str,
        *,
        match=None,
        prob: float = 1.0,
        times: int | None = None,
        rule_id: str | None = None,
        quiet: bool = False,
        background: bool = False,
        **params,
    ) -> Rule:
        with self._lock:
            if rule_id is None:
                rule_id = f"{point}#{sum(len(r) for r in self._rules.values())}"
            rule = Rule(
                point,
                kind,
                rule_id=rule_id,
                match=match,
                prob=prob,
                times=times,
                quiet=quiet,
                background=background,
                **params,
            )
            rules = self._rules.setdefault(point, [])
            if background:
                rules.append(rule)
            else:
                # Foreground rules stay ahead of every background rule
                # regardless of arrival order: _fire returns the FIRST
                # match, and a topology delay must never shadow a fault
                # rule armed later at the same point.
                i = next(
                    (j for j, r in enumerate(rules) if r.background),
                    len(rules),
                )
                rules.insert(i, rule)
            return rule

    def remove(self, rule: Rule) -> None:
        with self._lock:
            rules = self._rules.get(rule.point)
            if rules and rule in rules:
                rules.remove(rule)

    def remove_all(self, rules) -> None:
        for r in rules:
            self.remove(r)

    def clear_rules(self) -> None:
        with self._lock:
            self._rules.clear()

    # -- firing -----------------------------------------------------------

    def _fire(self, point: str, ctx: dict) -> Action | None:
        with self._lock:
            rules = self._rules.get(point)
            if not rules:
                return None
            for rule in rules:
                if not rule.enabled:
                    continue
                if rule.times is not None and rule._fires >= rule.times:
                    continue
                if not rule._matches(ctx):
                    continue
                n = rule._evals
                rule._evals += 1
                p, u = _draws(self._seed, rule.rule_id, n)
                if rule.prob < 1.0 and p >= rule.prob:
                    continue
                rule._fires += 1
                if not rule.quiet:
                    self._seq += 1
                    self._events.append(
                        FaultEvent(
                            self._seq, point, rule.rule_id, n, rule.kind
                        )
                    )
                    metrics.incr(
                        "faults.fired",
                        labels={"point": point, "action": rule.kind},
                    )
                params = dict(rule.params)
                params["u"] = u
                return Action(rule.kind, params, rule)
        return None

    def trace(self) -> list[FaultEvent]:
        with self._lock:
            return list(self._events)

    def would_drop(self, point: str, **ctx) -> bool:
        """Side-effect-free: would an armed ``drop`` rule match this
        context right now?  Health probes use it — a probe must
        OBSERVE a partition (an in-process cut never unregisters the
        transport) without consuming rule fire budgets, perturbing
        the seeded parameter draws, or echoing into the fault trace
        the way a real :meth:`_fire` evaluation would."""
        with self._lock:
            for rule in self._rules.get(point, ()):
                if (
                    rule.enabled
                    and rule.kind == "drop"
                    and (rule.times is None or rule._fires < rule.times)
                    and rule._matches(ctx)
                ):
                    return True
        return False


registry = FaultRegistry()

#: The registry :func:`fire` dispatches to — whichever was armed last
#: (the module singleton by default).
_active: FaultRegistry = registry


def arm(seed: int = 0) -> FaultRegistry:
    return registry.arm(seed)


def disarm() -> None:
    _active.disarm()


def fire(__point: str, **ctx) -> Action | None:
    """The hook-site entry point.  Returns the action of the first
    matching rule that fires, or ``None``.  Disarmed: a single bool
    test (hook sites additionally guard with ``if fp.ARMED:`` so even
    the ``ctx`` dict is never built).  (Positional-only point name so
    context keys like ``name=`` cannot collide.)"""
    if not ARMED:
        return None
    return _active._fire(__point, ctx)


# -- shared action helpers (hook sites interpret, these stay pure) ---------


def delay_seconds(act: Action) -> float:
    """Delay duration for a ``delay``/``stall`` action: fixed
    ``seconds``, or uniform in [seconds, max_seconds] via the rule's
    deterministic parameter draw."""
    lo = float(act.params.get("seconds", 0.0))
    hi = act.params.get("max_seconds")
    if hi is None:
        return lo
    return lo + (float(hi) - lo) * act.params["u"]


def corrupt_bytes(data: bytes, u: float) -> bytes:
    """Flip a few bytes at a draw-determined offset — enough to break
    any MAC/signature over ``data`` without changing its length."""
    if not data:
        return data
    out = bytearray(data)
    i = int(u * len(out)) % len(out)
    out[i] ^= 0xFF
    out[(i * 7 + 13) % len(out)] ^= 0x55
    return bytes(out)


def link_of(addr: str) -> str:
    """Normalize a certificate/post address to a link name the
    partition matcher can compare: scheme and any path stripped —
    ``loop://a01`` → ``a01``, ``http://127.0.0.1:6001/...`` →
    ``127.0.0.1:6001``."""
    if "://" in addr:
        addr = addr.split("://", 1)[1]
    return addr.split("/", 1)[0]
