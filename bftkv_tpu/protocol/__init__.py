"""Protocol layer: the replicated-KV state machines.

Capability parity with the reference's ``protocol`` package:
- :class:`Protocol` — shared state (self node, quorum system, transport,
  crypto, threshold) + membership gossip (reference:
  protocol/protocol.go:13-60);
- :class:`bftkv_tpu.protocol.client.Client` — three-phase signed write,
  quorum read with read-repair and revoke-on-read, TPA driver,
  threshold-signing driver (reference: protocol/client.go:52-546);
- :class:`bftkv_tpu.protocol.server.Server` — the 13 command handlers
  behind decrypt→dispatch→encrypt (reference: protocol/server.go:33-620).

TPU stance: the protocol layer is control flow — pure Python, no
tensors.  All hot crypto (signature verify/sign, modexp, tallies) is
delegated downward to ``bftkv_tpu.crypto`` / ``bftkv_tpu.ops`` where it
runs as batched device kernels; the server additionally funnels verify
work through the cross-request batching dispatcher
(``bftkv_tpu.ops.dispatch``) so concurrent handlers share kernel
launches.
"""

from __future__ import annotations

from collections import Counter

from bftkv_tpu import transport as tp
from bftkv_tpu.crypto import cert as certmod
from bftkv_tpu.crypto.threshold import ThresholdInstance

__all__ = ["Protocol", "majority_error", "MAX_UINT64", "Ref"]

MAX_UINT64 = 2**64 - 1


class Ref:
    """Minimal node stand-in for revoking an id we have no cert for."""

    __slots__ = ("id",)

    def __init__(self, nid: int):
        self.id = nid


def majority_error(errs: list, fallback):
    """The most common error in a fan-out, or ``fallback`` when none
    (reference: protocol/client.go:28-50)."""
    if not errs:
        return fallback
    counts = Counter(str(e) for e in errs)
    winner = counts.most_common(1)[0][0]
    for e in errs:
        if str(e) == winner:
            return e
    return fallback


class Protocol:
    """Shared protocol state (reference: protocol/protocol.go:13-19).

    ``self_node`` is the trust :class:`bftkv_tpu.graph.Graph` doubling
    as the node identity, exactly as the reference's ``Graph``
    implements ``SelfNode``.
    """

    def __init__(self, self_node, qs, tr, crypt):
        self.self_node = self_node
        self.qs = qs
        self.tr = tr
        self.crypt = crypt
        self.threshold = ThresholdInstance(crypt)

    def joining(self) -> None:
        """Iterative gossip crawl: multicast Join to every not-yet-asked
        peer, fold returned certificates into the graph + keyring,
        repeat until no new peers appear (reference:
        protocol/protocol.go:21-52)."""
        asked: set[int] = set()
        pkt = self.self_node.serialize_self()
        while True:
            peers = [
                n for n in self.self_node.get_peers() if n.id not in asked
            ]
            if not peers:
                break
            asked.update(n.id for n in peers)

            def cb(res: tp.MulticastResponse) -> bool:
                # Errors are ignored: the peer may simply not know our
                # certificate yet (reference: protocol.go:39-41).
                if res.data:
                    try:
                        nodes = certmod.parse(res.data)
                    except Exception:
                        return False
                    added = self.self_node.add_peers(nodes)
                    try:
                        self.crypt.keyring.register(added)
                    except Exception:
                        self.self_node.remove_peers(added)
                return False  # go through all nodes

            self.tr.multicast(tp.JOIN, peers, pkt, cb)

    def leaving(self) -> None:
        """Broadcast our departure (reference: protocol/protocol.go:54-60)."""
        pkt = self.self_node.serialize_self()
        self.tr.multicast(tp.LEAVE, self.self_node.get_peers(), pkt, None)
