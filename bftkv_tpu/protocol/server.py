"""Protocol server: the 13 command handlers behind decrypt→dispatch→encrypt.

Capability parity with the reference (protocol/server.go:33-620):
- ``sign`` — the guts of the write path: verify the writer's signature
  with its own certificate, require the writer's certificate to be
  signed by a CERT-quorum threshold (the *quorum certificate*,
  server.go:211-214), the equivocation check "never sign <x,t,v≠v'>"
  with revocation of double-signers (server.go:242-256), and persist
  the request *without* ss to mark the write in-progress
  (server.go:275-281);
- ``write`` — collective-signature sufficiency, timestamp /
  equivocation / TOFU checks (TOFU: a new issuer must match the
  previous issuer's id **or** uid, server.go:329-337);
- ``read`` — latest *completed* version (scan back past sign-only
  entries), TPA proof enforcement on protected variables
  (server.go:145-187);
- TPA session map per protected variable (server.go:375-448),
  ``register`` (decentralized enrollment, server.go:450-514),
  ``distribute``/``dist_sign`` with the ``!!!secret!!!`` hidden prefix
  (server.go:31,516-541), join/leave/revoke/notify maintenance.

TPU stance: handlers are control flow; every signature verification
goes through ``crypt.collective`` / ``verify_with_certificate`` whose
modexp batches run on device, and the server-side entry points are
instrumented so the batching dispatcher can coalesce concurrent
requests.
"""

from __future__ import annotations

import logging
import time
from collections import OrderedDict

from bftkv_tpu import packet as pkt
from bftkv_tpu import quorum as qm
from bftkv_tpu import trace
from bftkv_tpu import transport as tp
from bftkv_tpu.crypto import auth as authmod
from bftkv_tpu.crypto import cert as certmod
from bftkv_tpu.crypto import signature as sigmod
from bftkv_tpu.crypto import vcache
from bftkv_tpu.errors import error_from_string, wrong_shard_error
from bftkv_tpu.faults import failpoint as fp
from bftkv_tpu.errors import (
    ERR_AUTHENTICATION_FAILURE,
    ERR_BAD_TIMESTAMP,
    ERR_CERTIFICATE_NOT_FOUND,
    ERR_EQUIVOCATION,
    ERR_EXIST,
    ERR_INVALID_QUORUM_CERTIFICATE,
    ERR_INVALID_SIGN_REQUEST,
    ERR_INVALID_SIGNATURE,
    ERR_INVALID_USER_ID,
    ERR_MALFORMED_REQUEST,
    ERR_NO_AUTHENTICATION_DATA,
    ERR_NO_MORE_WRITE,
    ERR_NOT_FOUND,
    ERR_PERMISSION_DENIED,
    ERR_TOO_MANY_ATTEMPTS,
    ERR_UNKNOWN_COMMAND,
    ERR_WRONG_SHARD,
)
from bftkv_tpu.metrics import registry as metrics
from bftkv_tpu.protocol import MAX_UINT64, Protocol, Ref
from bftkv_tpu.devtools.lockwatch import named_lock

__all__ = ["Server", "HIDDEN_PREFIX", "MAX_UINT64"]

log = logging.getLogger("bftkv_tpu.protocol.server")

# Threshold shares are stored under variables no client request may
# name directly (reference: server.go:31, time/read reject the prefix).
HIDDEN_PREFIX = b"!!!secret!!!"


class Server(Protocol):
    def __init__(self, self_node, qs, tr, crypt, storage):
        super().__init__(self_node, qs, tr, crypt)
        self.storage = storage
        # Per-variable TPA servers, LRU-bounded + idle-TTL'd: a hostile
        # client naming fresh variables would otherwise grow this map
        # without limit (the reference deletes on done/error,
        # server.go:441-447; we keep sessions alive for mid-handshake
        # peers, so bounding has to be explicit).  The anti-brute-force
        # attempt counter survives eviction in ``_auth_attempts``.
        self._auth: "OrderedDict[bytes, authmod.AuthServer]" = OrderedDict()
        self._auth_used: dict[bytes, float] = {}
        self._auth_attempts: "OrderedDict[bytes, int]" = OrderedDict()
        self._auth_lock = named_lock("server.auth")
        # Anti-entropy digest tree (bftkv_tpu/sync), built lazily on the
        # first SYNC_DIGEST/SYNC_PULL; every persist marks it dirty so
        # digests stay incremental.
        self._sync = None
        self._sync_lock = named_lock("server.sync")

    # -- anti-entropy plumbing (bftkv_tpu/sync) ---------------------------

    def _persist(self, variable: bytes, t: int, data: bytes) -> None:
        """All handler writes go through here: storage write + digest
        invalidation for the anti-entropy plane."""
        with trace.span("storage.write", attrs={"bytes": len(data)}):
            self.storage.write(variable, t, data)
        tree = self._sync
        if tree is not None:
            tree.mark(variable)

    def _persist_many(self, entries) -> None:
        """Batch form of :meth:`_persist` — the group-commit seam.  A
        backend exposing ``write_batch`` (the §19 log engine) persists
        the whole coalesced batch under ONE durability barrier; every
        other backend falls back to per-item writes, so callers
        (BATCH_WRITE, ``admit_records``, the sync back-fill) can batch
        unconditionally."""
        entries = list(entries)
        if not entries:
            return
        wb = getattr(self.storage, "write_batch", None)
        if wb is not None and len(entries) > 1:
            nbytes = sum(len(d) for _v, _t, d in entries)
            with trace.span(
                "storage.write",
                attrs={"bytes": nbytes, "batch": len(entries)},
            ):
                wb(entries)
            tree = self._sync
            if tree is not None:
                for variable, _t, _d in entries:
                    tree.mark(variable)
            return
        for variable, t, data in entries:
            self._persist(variable, t, data)

    def _sync_tree(self):
        with self._sync_lock:
            if self._sync is None:
                from bftkv_tpu.sync.digest import DigestTree

                self._sync = DigestTree(self.storage)
            return self._sync

    def pending_variables(
        self,
        limit: int = 4096,
        after: bytes | None = None,
        scan_window: int | None = None,
    ) -> tuple[list[tuple[bytes, int, bytes, object]], bytes | None]:
        """Commit-pending residue in this replica's own store: the
        latest version of every variable whose record carries a
        partial (non-completed) collective signature — a piggybacked
        write whose async back-fill never landed here.  The repair
        daemon (sync/daemon.py) certifies or demotes these.

        The scan is WINDOWED so steady state stays cheap: at most
        ``scan_window`` keys (sorted order, resuming after ``after``)
        are read+parsed per call — a large store of fully certified
        records costs one bounded slice per repair round, not a
        full-store parse sweep.  Returns ``(pending, next_cursor)``;
        ``next_cursor`` is None when the scan reached the end of the
        keyspace (the caller wraps around next round).

        Excluded by design: hidden-prefix (threshold-CA) state,
        TPA-protected records (certifying them needs the client's auth
        proof, which only a client holds), legacy sign-phase residue
        (``ss is None`` — the read path's scan-back + certify-on-read
        already owns that shape), and anything unparsable."""
        out: list[tuple[bytes, int, bytes, object]] = []
        cursor = None
        sk = getattr(self.storage, "sorted_keys", None)
        if sk is not None and scan_window is not None:
            # Storage-served cursor (§19 log engine): one bisect +
            # slice instead of re-sorting the whole keyspace every
            # repair round.  Ask for one extra key to learn whether
            # the window exhausted the keyspace.
            try:
                keys = sk(after=after, limit=scan_window + 1)
            except Exception:
                return out, None
            if len(keys) > scan_window:
                keys = keys[:scan_window]
                cursor = keys[-1]  # more keys remain past this window
        else:
            try:
                keys = sorted(self.storage.keys())
            except Exception:
                return out, None
            if after is not None:
                keys = [k for k in keys if k > after]
            if scan_window is not None and len(keys) > scan_window:
                keys = keys[:scan_window]
                cursor = keys[-1]  # more keys remain past this window
        for variable in keys:
            if len(out) >= limit:
                break
            if variable.startswith(HIDDEN_PREFIX):
                continue
            try:
                raw = self.storage.read(variable, 0)
                p = pkt.parse(raw)
            except Exception:
                # Unreadable/undecodable record: not repair-eligible —
                # the anti-entropy plane owns hostile storage bytes.
                continue
            if p.sig is None or p.auth is not None:
                continue
            if p.ss is None or p.ss.completed:
                continue
            out.append((variable, p.t, raw, p))
        return out, cursor

    # -- lifecycle (reference: server.go:47-62) ---------------------------

    def start(self, bind_host: str = "") -> None:
        """``bind_host`` overrides the listen interface (containers:
        0.0.0.0) while peers keep dialing the certificate address."""
        addr = self.self_node.address
        if addr:
            listen = _listen_addr(addr)
            if bind_host:
                listen = f"{bind_host}:{listen.rsplit(':', 1)[-1]}"
            self.tr.start(self, listen)
            log.info("server @ %s running (listen %s)", addr, listen)

    def stop(self) -> None:
        self.leaving()
        self.tr.stop()

    # -- dispatch (reference: server.go:562-620) --------------------------

    def handler(self, cmd: int, data: bytes) -> bytes | None:
        """decrypt → dispatch → encrypt.  Errors raise; the transport
        layer tunnels them back (x-error header / loopback raise)."""
        plain, sender, nonce = self.crypt.message.decrypt(data)
        # The client's trace context rides a plaintext envelope inside
        # the encrypted payload (packet.wrap_trace, prepended by the
        # multicast fan-out); strip it before the handlers parse.
        tctx, plain = pkt.unwrap_trace(plain)
        # "peer" is the sender as *we* know it — None on first contact
        # (the reference's nil peer, server.go:566-569).
        peer = self.crypt.keyring.get(sender.id)

        name = self._handlers.get(cmd)
        if name is None:
            raise ERR_UNKNOWN_COMMAND
        cmd_name = tp.COMMAND_NAMES.get(cmd, cmd)
        metrics.incr(f"server.{cmd_name}.count")
        # Dispatch by name so subclasses (the Byzantine Mal* family,
        # reference: malserver_test.go:23-194) override handlers by
        # plain method definition.
        run = getattr(self, name)
        if fp.ARMED:
            # ``server.admission`` failpoint: error reply, crash, or a
            # Byzantine handler override (faults/byzantine.py programs).
            act = fp.fire(
                "server.admission",
                node=getattr(self.self_node, "name", ""),
                cmd=cmd_name,
            )
            if act is not None:
                run = self._admission_fault(act, cmd, run)
        if tctx is not None:
            with trace.attach(trace.SpanContext(*tctx)), trace.span(
                f"server.{cmd_name}",
                attrs={"node": getattr(self.self_node, "name", "")},
            ):
                res = run(plain, peer, sender)
        else:
            res = run(plain, peer, sender)
        return self.crypt.message.encrypt([sender], res or b"", nonce)

    def _admission_fault(self, act, cmd: int, run):
        """Interpret one fired ``server.admission`` action as a handler
        replacement: ``error`` raises the named interned error,
        ``delay`` stalls then serves honestly, ``crash`` takes this
        replica's transport down mid-request, ``handle`` substitutes a
        Byzantine program ``fn(server, cmd, req, peer, sender)``."""
        if act.kind == "error":
            msg = act.params.get("error", "internal error")

            def run_error(req, peer, sender):
                raise error_from_string(msg)

            return run_error
        if act.kind == "delay":

            def run_delayed(req, peer, sender):
                time.sleep(fp.delay_seconds(act))
                return run(req, peer, sender)

            return run_delayed
        if act.kind == "crash":

            def run_crash(req, peer, sender):
                self.tr.stop()  # the node goes dark for everyone
                raise tp.ERR_UNREACHABLE

            return run_crash
        if act.kind == "handle":
            fn = act.params["fn"]
            return lambda req, peer, sender: fn(self, cmd, req, peer, sender)
        return run

    # -- keyspace sharding admission gate ---------------------------------

    def _wrong_shard(self, variable: bytes, stale: bool = False) -> None:
        """Count and raise the wrong-shard decline.  With an installed
        route epoch the decline carries the responder's epoch and the
        owning shard index so a stale-route client re-routes in-round;
        epoch-0 fleets (and non-epoched quorum systems) keep raising
        the bare interned form legacy clients already understand.
        ``stale``: the misroute looks stale-ROUTED (an epoch flip moved
        the bucket away from here) rather than Byzantine — the
        ``server.epoch_stale`` counter feeds the fleet collector's
        ``epoch_skew`` anomaly."""
        qs = self.qs
        # Labeled by the shard THIS replica serves (a closed enum:
        # shard indices, bounded by the clique count) — the fleet
        # collector's anomaly feed attributes misroutes per shard.
        # Unlabeled when the seat is momentarily unknown (topology
        # regenerating): a string fallback under the same name
        # would make Prometheus' sorted() comparison of int and
        # str label values raise.
        my_shard = getattr(qs, "my_shard", lambda: None)()
        labels = {"shard": my_shard} if my_shard is not None else None
        metrics.incr("server.wrong_shard", labels=labels)
        if stale:
            metrics.incr("server.epoch_stale", labels=labels)
        hint = getattr(qs, "route_hint", None)
        if (
            hint is not None
            and getattr(qs, "route_epoch", lambda: 0)() > 0
        ):
            epoch, owner = hint(variable)
            if owner is not None:
                raise wrong_shard_error(epoch, owner)
        raise ERR_WRONG_SHARD

    def _shard_check(self, variable: bytes, write: bool = True) -> str:
        """Admission gate for keyspace routing; returns this replica's
        role for ``variable`` (``owner`` / ``dual`` / ``foreign``).

        On unsharded trust graphs (and for quorum systems without keyed
        routing) this is a no-op, so single-clique clusters behave
        bit-for-bit as before.  The gate is what makes cross-shard
        collective signatures unmintable: the only replicas that will
        sign or store <x,...> are the owner clique's, so a signature
        gathered anywhere else can never reach the owner quorum's
        threshold.

        Epoched routing refines the gate (DESIGN.md §15):

        - a ``dual`` replica (old owner inside the dual-epoch window)
          passes here; the write-path handlers then restrict it to
          versions it ALREADY stored (``_dual_write_ok``) — it keeps
          serving and certifying, it never mints a new version, so the
          new owner stays the single write serializer and invariant 5
          survives the flip;
        - ``foreign`` READS are served (not declined) once an epoch is
          installed — the inert-stale-copy rule: a replica straddling a
          flip keeps serving what it has while refusing new writes for
          buckets it no longer owns."""
        qs = self.qs
        role_of = getattr(qs, "route_role", None)
        if role_of is None:
            owns = getattr(qs, "owns", None)
            if owns is not None and not owns(variable):
                self._wrong_shard(variable)
            return "owner"
        role = role_of(variable)
        if role == "foreign":
            if (
                not write
                and getattr(qs, "route_epoch", lambda: 0)() > 0
            ):
                metrics.incr("server.read.foreign")
                return role
            stale = getattr(qs, "stale_routed", lambda _x: False)
            self._wrong_shard(variable, stale=stale(variable))
        return role

    def _dual_write_ok(self, variable: bytes, t: int, val) -> bool:
        """What a dual-window (old owner) replica may still admit on
        the write plane: exactly the versions it already stored — the
        back-fill / certify / idempotent-retry shapes of in-flight
        writes that started before the flip.  Anything NEW must go to
        the new owner (the decline hint sends the client there)."""
        try:
            vt = self.storage.read(variable, t)
        except Exception:
            return False
        try:
            return pkt.parse(vt).value == val
        except Exception:
            return False

    # -- membership (reference: server.go:64-120) -------------------------

    def _join(self, req: bytes, peer, sender) -> bytes | None:
        if peer is not None and peer.id == self.self_node.id:
            log.info("server [%s]: joining to itself?", peer.name)
            return None
        nodes = certmod.parse(req)
        certs: list = []
        if peer is not None:
            # Accept only the peer's own certificate.
            certs = [n for n in nodes if n.id == peer.id]
        elif nodes:
            # First contact: trust the first certificate.
            if nodes[0].id == self.self_node.id:
                log.info("server [%s]: joining to itself?", nodes[0].name)
                return None
            certs = [nodes[0]]
        certs = self.self_node.add_peers(certs)
        try:
            self.crypt.keyring.register(certs)
        except Exception:
            self.self_node.remove_peers(certs)  # stay consistent
            raise
        # Reply with our whole view so the joiner can crawl the graph.
        return self.self_node.serialize_nodes()

    def _leave(self, req: bytes, peer, sender) -> bytes | None:
        nodes = certmod.parse(req)
        for n in nodes:
            if peer is not None and n.id == peer.id:
                self.self_node.remove_peers([n])
                # the key stays in the keyring (reference: server.go:115)
        return None

    # -- timestamps (reference: server.go:122-143) ------------------------

    def _time(self, req: bytes, peer, sender) -> bytes:
        variable = req
        if variable.startswith(HIDDEN_PREFIX):
            raise ERR_PERMISSION_DENIED
        if self._shard_check(variable) == "dual":
            # A TIME answer would keep a stale classic writer minting
            # NEW versions at the old owner — send it to the new one.
            self._wrong_shard(variable, stale=True)
        t = 0
        try:
            raw = self.storage.read(variable, 0)
            t = pkt.parse(raw).t
        except ERR_NOT_FOUND:
            pass
        if fp.ARMED:
            # ``server.time`` failpoint: clock skew on the timestamp
            # path — this replica's answers shift by delta (clamped to
            # the valid range; MAX_UINT64 stays the write-once marker).
            act = fp.fire(
                "server.time", node=getattr(self.self_node, "name", "")
            )
            if act is not None and act.kind == "skew":
                t = min(max(t + int(act.params.get("delta", 0)), 0),
                        MAX_UINT64 - 1)
        return t.to_bytes(8, "big")

    # -- read (reference: server.go:145-187) ------------------------------

    def _read(self, req: bytes, peer, sender) -> bytes | None:
        p = pkt.parse(req)
        # ``t == 1`` in a read request asks for the latest CERTIFIED
        # record only (skip commit-pending) — the reader's fallback
        # after a pending winner failed to certify.  Old servers ignore
        # the request's t and never serve pending records, so the flag
        # degrades to their behavior exactly.
        return self._read_item(
            p.variable or b"", p.ss, certified_only=(p.t == 1)
        )

    def _read_item(
        self, variable: bytes, proof, certified_only: bool = False
    ) -> bytes | None:
        if variable.startswith(HIDDEN_PREFIX):
            raise ERR_PERMISSION_DENIED
        self._shard_check(variable, write=False)
        raw = None
        authenticated = None
        try:
            raw = self.storage.read(variable, 0)
        except ERR_NOT_FOUND:
            raw = None
        if raw is not None:
            stored = pkt.parse(raw)
            authenticated = stored.auth
            if (
                stored.ss is not None
                and not stored.ss.completed
                and certified_only
            ):
                # Scan back exactly as for a sign-phase record.
                raw = None
                for t in self._versions_below(variable, stored.t):
                    try:
                        candidate = self.storage.read(variable, t)
                    except ERR_NOT_FOUND:
                        continue
                    cp = pkt.parse(candidate)
                    if cp.ss is not None and cp.ss.completed:
                        raw = candidate
                        break
            elif stored.ss is not None and not stored.ss.completed:
                # Commit-pending piggyback record (WRITE_SIGN persists
                # with a partial, non-completed ss; the legacy sign
                # phase persists ss=None): SERVE it.  The client-side
                # resolve accepts it only through the resolve path — a
                # responder threshold plus certify-on-read when no
                # completed collective signature is in the bucket — so
                # a bare value is never served off one replica's word
                # (DESIGN.md §12.3).
                metrics.incr("server.read.pending")
            elif stored.ss is None:
                # A sign request arrived but the write never completed —
                # scan back for the last completed version
                # (reference: server.go:166-180).
                raw = None
                for t in self._versions_below(variable, stored.t):
                    try:
                        candidate = self.storage.read(variable, t)
                    except ERR_NOT_FOUND:
                        continue
                    cp = pkt.parse(candidate)
                    if cp.ss is not None and cp.ss.completed:
                        raw = candidate
                        break
        if authenticated is not None:
            if proof is None:
                raise ERR_AUTHENTICATION_FAILURE
            try:
                # TPA-protected record: the verify memo is never
                # consulted for auth proofs (crypto/vcache.py).
                self.crypt.collective.verify(
                    variable,
                    proof,
                    qm.choose_quorum_for(self.qs, variable, qm.AUTH),
                    self.crypt.keyring,
                    use_cache=False,
                )
            except Exception:
                raise ERR_AUTHENTICATION_FAILURE from None
        return raw

    def _versions_below(self, variable: bytes, t: int):
        """Stored version timestamps < ``t``, descending.  Prefers the
        backend's version listing; falls back to a bounded countdown
        (an incomplete write-once at 2^64-1 must not spin forever)."""
        versions = getattr(self.storage, "versions", None)
        if versions is not None:
            try:
                return sorted(
                    (v for v in versions(variable) if v < t), reverse=True
                )
            except Exception:
                pass  # backend's versions() broken: bounded scan below
        return range(t - 1, max(0, t - 1024), -1)

    # -- sign (reference: server.go:189-284) ------------------------------

    def _sign(self, req: bytes, peer, sender) -> bytes:
        p = pkt.parse(req)
        variable, val, t, sig, ss = p.variable or b"", p.value, p.t, p.sig, p.ss
        if sig is None:
            raise ERR_MALFORMED_REQUEST
        # Hardening beyond the reference (which guards only time/read,
        # server.go:126,153): a client-visible sign/write of a
        # hidden-prefix variable would shadow threshold-CA shares
        # stored there by _distribute.
        if variable.startswith(HIDDEN_PREFIX):
            raise ERR_PERMISSION_DENIED
        if (
            self._shard_check(variable) == "dual"
            and not self._dual_write_ok(variable, t, val)
        ):
            self._wrong_shard(variable, stale=True)

        # Verify the writer's signature with its own certificate.
        issuer = sigmod.issuer(sig, self.crypt.keyring)
        tbs = pkt.tbs(req)
        with trace.span(
            "server.verify_batch",
            attrs={"batch_size": 1, "kind": "writer_sig"},
        ):
            sigmod.verify_with_certificate(tbs, sig, issuer)
        # The presented cert may carry a richer quorum certificate
        # than this replica's keyring copy; check against a transient
        # enriched view (never persisted — see _present).
        if sig.cert:
            try:
                for c in certmod.parse(sig.cert):
                    if c.id == issuer.id:
                        issuer = self._present(c)
                        break
            except Exception:
                # Unparsable embedded chain: keep the presented issuer;
                # the qcert check right below is the authority.
                pass
        self._check_quorum_certificate(issuer)

        proof = self._sign_storage_checks(variable, val, t, sig, ss)

        tbss = pkt.tbss(req)
        share = self.crypt.collective.sign(self.crypt.signer, tbss)
        res = pkt.serialize_signature(share)

        # Persist the request *without* ss — marks the write in-progress
        # (reference: server.go:275-281).
        stored = pkt.serialize(variable, val, t, sig, None, proof)
        self._persist(variable, t, stored)
        metrics.incr("server.sign.ok")
        return res

    def _check_quorum_certificate(self, issuer) -> None:
        """The writer's certificate must carry VALID signatures from a
        CERT-quorum threshold (reference: server.go:211-214).

        Each counted signature is cryptographically verified (memoized
        per (signer, sig-bytes) on the cert object): embedded certs
        presented by writers merge into the keyring copy
        (:meth:`_merge_embedded`, the reference's merge-on-import,
        crypto_pgp.go:186-204), so an id-only count would let a writer
        claim arbitrary signer ids and mint a quorum certificate."""
        q = self.qs.choose_quorum(qm.AUTH | qm.CERT)
        cache = issuer.__dict__.setdefault("_qcert_ok", {})
        tbs = None
        signer_nodes = []
        for sid, sig_bytes in list(issuer.signatures.items()):
            c = self.crypt.keyring.get(sid)
            if c is None:
                continue
            ok = cache.get((sid, sig_bytes))
            if ok is None:
                if tbs is None:
                    tbs = issuer.tbs()
                # The process-wide verify memo spans cert *instances*
                # (keyring copy vs transient _present clones), so a
                # presented rich cert re-verifies each endorsement at
                # most once per process, not once per clone.
                if vcache.enabled() and vcache.get(c, tbs, sig_bytes):
                    ok = True
                else:
                    ok = certmod.verify_detached(tbs, sig_bytes, c)
                    if ok and vcache.enabled():
                        vcache.put(c, tbs, sig_bytes)
                cache[(sid, sig_bytes)] = ok
            if ok:
                signer_nodes.append(c)
        if not q.is_threshold(signer_nodes):
            raise ERR_INVALID_QUORUM_CERTIFICATE

    def _present(self, cert):
        """TRANSIENT view of a presented certificate: the keyring copy
        enriched with the presented signature set, never persisted.

        A writer whose quorum certificate was accumulated across
        replicas presents the rich copy; this replica's sparse keyring
        copy must not shadow it (the reference converges rings by
        merge-on-import, crypto_pgp.go:186-204).  But persisting the
        merge would be unsound the other way: the trust GRAPH derives
        edges from keyring signature sets, so a client presenting a
        cert copy carrying extra *valid* third-party certifications
        (public data) would silently add edges to this replica's graph
        and reshape its quorums.  Hence: enrich a throwaway clone for
        the signature-count check; the keyring and graph keep only
        ring-sourced edges.  Every counted signature is still verified
        cryptographically (:meth:`_check_quorum_certificate`)."""
        have = self.crypt.keyring.get(cert.id)
        if have is None:
            return cert
        if all(sid in have.signatures for sid in cert.signatures):
            return have  # nothing new: keep the memoized keyring copy
        rich = certmod.Certificate(
            n=have.n, e=have.e, name=have.name, address=have.address,
            uid=have.uid, alg=have.alg, point=have.point,
            signatures=dict(have.signatures),
        )
        try:
            rich.merge(cert)
        except Exception:
            return have
        return rich

    def _sign_storage_checks(self, variable, val, t, sig, ss):
        """The per-variable part of ``sign``: TPA proof, write-once,
        equivocation, and timestamp checks against the stored version
        (reference: server.go:232-262).  Returns the auth params to
        inherit into the persisted record."""
        rdata = None
        try:
            rdata = self.storage.read(variable, 0)
        except ERR_NOT_FOUND:
            pass

        proof = None
        if rdata is not None:
            rp = pkt.parse(rdata)
            # TPA check first (reference: server.go:232-241): ``ss`` in
            # the sign request carries the client's auth proof.
            if rp.auth is not None:
                if ss is None:
                    raise ERR_AUTHENTICATION_FAILURE
                try:
                    # TPA-protected record: bypass the verify memo.
                    self.crypt.collective.verify(
                        variable,
                        ss,
                        qm.choose_quorum_for(self.qs, variable, qm.AUTH),
                        self.crypt.keyring,
                        use_cache=False,
                    )
                except Exception:
                    raise ERR_AUTHENTICATION_FAILURE from None
            # Never sign both <x,t,v> and <x,t,v'>
            # (reference: server.go:242-262).  Re-signing the EXACT
            # stored <t, value> stays allowed even at the write-once
            # ceiling: it issues no second signature over anything new,
            # and it is how a reader certifies a commit-pending
            # write-once record (client._certify_pending).
            if rp.t == MAX_UINT64 and not (t == rp.t and val == rp.value):
                raise ERR_NO_MORE_WRITE
            if t == rp.t and val != rp.value:
                if self._revoke_signers(
                    sigmod.signers(sig), sigmod.signers(rp.sig)
                ):
                    metrics.incr("server.equivocation")
                    raise ERR_EQUIVOCATION
                raise ERR_INVALID_SIGN_REQUEST  # someone beat me
            if t < rp.t:
                raise ERR_BAD_TIMESTAMP
            proof = rp.auth  # inherit the auth params
        return proof

    # -- write (reference: server.go:286-352) -----------------------------

    def _write(self, req: bytes, peer, sender) -> bytes | None:
        p = pkt.parse(req)
        variable, val, t, sig, ss = p.variable or b"", p.value, p.t, p.sig, p.ss
        if sig is None or ss is None:
            raise ERR_MALFORMED_REQUEST
        if variable.startswith(HIDDEN_PREFIX):
            raise ERR_PERMISSION_DENIED
        role = self._shard_check(variable)
        if role == "dual" and not self._dual_write_ok(variable, t, val):
            self._wrong_shard(variable, stale=True)

        # Sufficient quorum members must have signed the same <x,v,t> —
        # against the OWNER shard's quorum, so a collective signature
        # gathered from another clique is rejected in admission.
        tbss = pkt.tbss(req)
        with trace.span(
            "server.verify_batch",
            attrs={
                "batch_size": len(sigmod.signers(ss)),
                "kind": "collective",
            },
        ):
            try:
                self.crypt.collective.verify(
                    tbss,
                    ss,
                    qm.choose_quorum_for(self.qs, variable, qm.AUTH),
                    self.crypt.keyring,
                )
            except Exception:
                # A write arriving with a collective signature that does
                # not verify against the owner quorum is exactly the
                # Byzantine signal the fleet health plane watches for.
                metrics.incr("server.verify.collective_fail")
                raise

        out = self._write_storage_checks(variable, val, t, sig, ss, req)
        if out is not None:  # None = idempotent no-op (see checks)
            self._persist(variable, t, out)
        metrics.incr("server.write.ok")
        return None

    def _write_storage_checks(
        self, variable, val, t, sig, ss, req, frame_embedded=None
    ) -> bytes | None:
        """The per-variable part of ``write``: write-once, timestamp,
        equivocation, and TOFU checks against the stored version
        (reference: server.go:314-345).  Returns the bytes to persist
        (the request, with inherited auth params folded in), or
        ``None`` for an idempotent no-op (a stale-version certification
        already satisfied — see ``_stale_version_upgrade``).

        ``frame_embedded`` (id→cert) backstops TOFU issuer resolution
        for batch items whose sig carries no cert of its own (the
        client embeds the writer cert on the first item only) — and is
        folded back into the PERSISTED record, which later overwrites
        must resolve standalone (the frame is gone by then)."""
        rdata = None
        try:
            rdata = self.storage.read(variable, 0)
        except ERR_NOT_FOUND:
            pass

        out = req
        if not sig.cert and frame_embedded:
            # Mid-join writer, non-carrier item: restore the cert the
            # single-item path would have persisted, so the stored
            # record stays issuer-resolvable on its own.
            for sid, _ in sigmod.parse_entries(sig.data):
                if self.crypt.keyring.get(sid) is not None:
                    break
                fe = frame_embedded.get(sid)
                if fe is not None:
                    sig.cert = fe.serialize()
                    out = pkt.serialize(
                        variable, val, t, sig, ss, pkt.parse(req).auth
                    )
                    break
        if rdata is not None:
            rp = pkt.parse(rdata)
            # The exact stored <t, value> is re-admittable even at the
            # write-once ceiling: that is the back-fill certifying a
            # commit-pending write-once record (and a read-repair
            # re-delivering a completed one) — idempotent, not a
            # second write.
            if rp.t == MAX_UINT64 and not (t == rp.t and val == rp.value):
                raise ERR_NO_MORE_WRITE
            if t < rp.t:
                # Below the latest stored version — USUALLY a stale
                # write.  One case is not: the collective back-fill of
                # a committed collapsed write arriving after a newer
                # commit-PENDING version landed (a failed racer's
                # residue, or simply the next write outrunning this
                # one's async tail).  Certifying the exact version this
                # replica already admitted at t must not be blocked, or
                # residue at the top could starve the plane of ANY
                # completed record (DESIGN.md §12.3).
                return self._stale_version_upgrade(variable, val, t, out)
            if t == rp.t and val != rp.value:
                if rp.ss is not None:
                    self._revoke_signers(
                        sigmod.signers(ss), sigmod.signers(rp.ss)
                    )
                if not (
                    ss is not None
                    and ss.completed
                    and (rp.ss is None or not rp.ss.completed)
                ):
                    metrics.incr("server.equivocation")
                    raise ERR_EQUIVOCATION
                # A CERTIFIED record (its collective signature already
                # verified by the caller) beats uncertified residue at
                # the same timestamp: the quorum endorsed this value,
                # the residue is a failed racer's leftovers — refusing
                # would leave this replica permanently divergent.
                # Double-signers were still swept above.
                metrics.incr("server.write.residue_replaced")

            # TOFU: the new issuer must match the CERTIFIED owner's id
            # or uid (reference: server.go:329-337; residue never owns,
            # see _tofu_prev_sig).
            prev_sig = self._tofu_prev_sig(variable, rp)
            if prev_sig is not None:
                new_issuer = sigmod.issuer(
                    sig, self.crypt.keyring, frame_embedded
                )
                prev_issuer = sigmod.issuer(
                    prev_sig, self.crypt.keyring, frame_embedded
                )
                if (
                    prev_issuer.id != new_issuer.id
                    and prev_issuer.uid != new_issuer.uid
                ):
                    raise ERR_PERMISSION_DENIED

            if rp.auth is not None:  # inherit auth params
                out = pkt.serialize(variable, val, t, sig, ss, rp.auth)

        return out

    # -- round-collapsed write (piggyback; no reference analog) ------------

    def _signs_for(self, variable: bytes) -> bool:
        """Whether this replica holds a seat in the sign (AUTH) quorum
        that owns ``variable`` — i.e. whether its WRITE_SIGN ack should
        carry a collective-signature share.  Storage-plane complement
        nodes ack without a share: their signatures could never count
        toward ``suff`` anyway (is_sufficient tallies clique members
        only), and skipping the private-key op keeps the write plane as
        cheap as the legacy WRITE round.  Epoched quorum systems answer
        directly (``WotQS.signs_for``) — a dual-window old owner keeps
        a sign seat for versions it already stored."""
        fn = getattr(self.qs, "signs_for", None)
        if fn is not None:
            return fn(variable)
        qa = qm.choose_quorum_for(self.qs, variable, qm.AUTH)
        myid = self.self_node.get_self_id()
        return any(n.id == myid for n in qa.nodes())

    def _write_sign(self, req: bytes, peer, sender) -> bytes:
        """ONE round carrying what sign + write did in two: verify the
        writer (signature + quorum certificate), run the write-path
        storage checks, persist the record as COMMIT-PENDING (partial
        ss, completed=False), and piggyback this replica's collective-
        signature share inside the ack (packet.serialize_ws_ack).

        Timestamp admission is STRICT — the request's ``t`` must exceed
        the stored timestamp (the sole exception: re-acking the exact
        stored <t, value>, which keeps client retries idempotent).  A
        stale optimistic guess is answered with a DECLINE hint carrying
        the stored timestamp, never with a share and never with the
        equivocation revocation: this replica refuses to sign at or
        below its stored timestamp, so the "never sign both <x,t,v>
        and <x,t,v'>" invariant holds by construction, and an honest
        client whose lease went stale cannot be mistaken for a
        Byzantine double-signer (DESIGN.md §12.2)."""
        p = pkt.parse(req)
        variable, val, t, sig, proof = (
            p.variable or b"", p.value, p.t, p.sig, p.ss,
        )
        if sig is None:
            raise ERR_MALFORMED_REQUEST
        if variable.startswith(HIDDEN_PREFIX):
            raise ERR_PERMISSION_DENIED
        if (
            self._shard_check(variable) == "dual"
            and not self._dual_write_ok(variable, t, val)
        ):
            # The dual window keeps in-flight tails alive (re-acks and
            # certifications of versions this replica already stored);
            # a NEW version must mint at the new owner — the hinted
            # decline re-routes the writer in-round.
            self._wrong_shard(variable, stale=True)

        # Writer authentication, exactly as the sign phase does it.
        issuer = sigmod.issuer(sig, self.crypt.keyring)
        tbs = pkt.tbs(req)
        with trace.span(
            "server.verify_batch",
            attrs={"batch_size": 1, "kind": "writer_sig"},
        ):
            sigmod.verify_with_certificate(tbs, sig, issuer)
        signs = self._signs_for(variable)
        if signs:
            # Quorum-certificate check: sign-seat holders only.  A
            # storage-plane node's distance-0 view holds no CERT clique
            # to count against (it never ran this check in the legacy
            # split either — write admission there rested on the
            # collective signature).  Commit still requires 2f+1 clique
            # acks, every one of which DID enforce the writer's quorum
            # certificate, and a pending record on the write plane
            # carries no authority until certified.
            if sig.cert:
                try:
                    for c in certmod.parse(sig.cert):
                        if c.id == issuer.id:
                            issuer = self._present(c)
                            break
                except Exception:
                    # Unparsable embedded chain: keep the presented
                    # issuer; the qcert check below is the authority.
                    pass
            self._check_quorum_certificate(issuer)

        rdata = None
        try:
            rdata = self.storage.read(variable, 0)
        except ERR_NOT_FOUND:
            pass

        inherit = None
        echo = False  # exact stored <t, value> re-ack
        rp = pkt.parse(rdata) if rdata is not None else None
        if rp is not None:
            # TPA gate first, as in the sign phase: the client's auth
            # proof rides the ss slot of the request.
            if rp.auth is not None:
                if proof is None:
                    raise ERR_AUTHENTICATION_FAILURE
                try:
                    self.crypt.collective.verify(
                        variable,
                        proof,
                        qm.choose_quorum_for(self.qs, variable, qm.AUTH),
                        self.crypt.keyring,
                        use_cache=False,
                    )
                except Exception:
                    raise ERR_AUTHENTICATION_FAILURE from None
            if t == rp.t and val == rp.value:
                echo = True  # idempotent retry, write-once included
            elif rp.t == MAX_UINT64:
                raise ERR_NO_MORE_WRITE
            elif t <= rp.t:
                # Stale optimistic timestamp: decline with the hint.
                metrics.incr("server.write_sign.decline")
                return pkt.serialize_ws_ack(decline_t=rp.t)
            if not echo:
                # TOFU, from the write path (reference: server.go:329-
                # 337) — against the latest CERTIFIED owner only.
                prev_sig = self._tofu_prev_sig(variable, rp)
                if prev_sig is not None:
                    new_issuer = sigmod.issuer(sig, self.crypt.keyring)
                    prev_issuer = sigmod.issuer(
                        prev_sig, self.crypt.keyring
                    )
                    if (
                        prev_issuer.id != new_issuer.id
                        and prev_issuer.uid != new_issuer.uid
                    ):
                        raise ERR_PERMISSION_DENIED
            inherit = rp.auth

        share_bytes = b""
        pending_data = None
        if signs:
            tbss = pkt.tbss(req)
            share = self.crypt.collective.sign(self.crypt.signer, tbss)
            share_bytes = pkt.serialize_signature(share)
            pending_data = share.data

        # Persist as commit-pending: partial ss (our own share when we
        # hold a sign seat, an empty marker otherwise), completed=False.
        # Never downgrade a certified record: an echo of a <t, value>
        # the back-fill already completed keeps the completed bytes.
        if not (echo and rp.ss is not None and rp.ss.completed):
            pending = pkt.SignaturePacket(
                type=pkt.SIGNATURE_TYPE_NATIVE,
                version=1,
                completed=False,
                data=pending_data,
            )
            stored = pkt.serialize(variable, val, t, sig, pending, inherit)
            self._persist(variable, t, stored)
        metrics.incr("server.write_sign.ok")
        return pkt.serialize_ws_ack(share=share_bytes)

    def _tofu_prev_sig(self, variable: bytes, rp) -> pkt.SignaturePacket | None:
        """The writer signature that currently OWNS ``variable`` for
        the TOFU check: the latest CERTIFIED record's.  Commit-pending
        and sign-phase residue never grants ownership — any
        quorum-certificate-valid writer can plant residue, so
        ownership-by-residue would let a failed racer (or a deliberate
        squatter) lock the real owner out of its own variable.  None =
        no certified ownership established yet (TOFU vacuous, exactly
        like a fresh variable)."""
        if rp.sig is not None and rp.ss is not None and rp.ss.completed:
            return rp.sig
        for v in self._versions_below(variable, rp.t):
            try:
                cp = pkt.parse(self.storage.read(variable, v))
            except Exception:
                continue  # torn/alien bytes here: keep scanning older
            if cp.ss is not None and cp.ss.completed:
                return cp.sig
        return None

    def _stale_version_upgrade(
        self, variable: bytes, val, t: int, out: bytes
    ) -> bytes | None:
        """Admission for a write BELOW the latest stored version.

        Allowed only as the in-place certification of a commit-pending
        version this replica already admitted: the stored version at
        ``t`` must exist with the SAME value.  Returns the bytes to
        persist at version ``t``, or ``None`` for an idempotent no-op
        (already certified, or superseded by a newer COMPLETED version
        — upgrading under one would make this replica's completed
        sequence go back in time, the §8 monotonicity invariant).
        Anything else is the plain stale write it always was."""
        try:
            vt = self.storage.read(variable, t)
        except ERR_NOT_FOUND:
            raise ERR_BAD_TIMESTAMP from None
        vp = pkt.parse(vt)
        if vp.value != val:
            raise ERR_BAD_TIMESTAMP
        if vp.ss is not None and vp.ss.completed:
            return None  # already certified at t
        for v in sorted(self._versions_above(variable, t), reverse=True):
            try:
                cp = pkt.parse(self.storage.read(variable, v))
            except ERR_NOT_FOUND:
                continue
            if cp.ss is not None and cp.ss.completed:
                return None  # superseded: a newer certified version rules
        metrics.incr("server.write.upgrade")
        if vp.auth is not None:
            p = pkt.parse(out)
            return pkt.serialize(variable, val, t, p.sig, p.ss, vp.auth)
        return out

    def _versions_above(self, variable: bytes, t: int) -> list[int]:
        versions = getattr(self.storage, "versions", None)
        if versions is None:
            return []
        try:
            return [v for v in versions(variable) if v > t]
        except Exception:
            return []

    def _revoke_signers(self, signers1: list[int], signers2: list[int]) -> bool:
        """Revoke every id present in both signer sets; broadcast the
        revocation list when anyone fell (reference: server.go:354-373)."""
        both = set(signers1) & set(signers2)
        revoked = False
        for sid in both:
            node = self.crypt.keyring.get(sid)
            if node is None:
                node = Ref(sid)
            self.self_node.revoke(node)
            vcache.invalidate_signer(sid)
            revoked = True
            metrics.incr("server.revocations")
        if revoked:
            rl = self.self_node.serialize_revoked()
            if rl:
                self.tr.multicast(
                    tp.NOTIFY, self.self_node.get_peers(), rl, None
                )
        return revoked

    # -- TPA (reference: server.go:375-448) -------------------------------

    def _set_auth(self, req: bytes, peer, sender) -> bytes | None:
        p = pkt.parse(req)
        variable = p.variable or b""
        if p.sig is None or p.auth is None or p.t != 0:
            raise ERR_MALFORMED_REQUEST
        if variable.startswith(HIDDEN_PREFIX):
            raise ERR_PERMISSION_DENIED
        if self._shard_check(variable) == "dual":
            self._wrong_shard(variable, stale=True)
        # Do NOT verify the signature here — it is kept with the auth
        # data for future use (reference: server.go:385).
        try:
            rdata = self.storage.read(variable, 0)
            if pkt.parse(rdata).t != 0:
                raise ERR_EXIST  # can't overwrite the password
        except ERR_NOT_FOUND:
            pass
        self._persist(variable, 0, req)
        return None

    #: Bounds on the per-variable AuthServer map: hard LRU cap plus an
    #: idle TTL (entries idle longer are evicted opportunistically on
    #: the next auth request).  Attempt counters survive eviction in
    #: ``_auth_attempts`` (itself LRU-capped — 64k ints, not sessions).
    AUTH_SESSIONS_MAX = 4096
    AUTH_IDLE_TTL = 3600.0
    AUTH_ATTEMPTS_MAX = 65536

    def _spill_attempts_locked(self, var: bytes, attempts: int) -> None:
        """Fold a retired/orphaned AuthServer's brute-force counter into
        the LRU-capped ``_auth_attempts`` spill map (never decreasing);
        caller holds ``_auth_lock``."""
        if attempts > self._auth_attempts.get(var, 0):
            self._auth_attempts[var] = attempts
            self._auth_attempts.move_to_end(var)
            while len(self._auth_attempts) > self.AUTH_ATTEMPTS_MAX:
                self._auth_attempts.popitem(last=False)

    def _auth_evict_locked(self, now: float) -> None:
        """Evict idle/overflow AuthServers, preserving their attempt
        counters; caller holds ``_auth_lock``."""

        def retire(var: bytes, srv) -> None:
            self._auth_used.pop(var, None)
            self._spill_attempts_locked(var, srv.attempts)

        for var in [
            v
            for v, used in self._auth_used.items()
            if now - used > self.AUTH_IDLE_TTL
        ]:
            retire(var, self._auth.pop(var))
        while len(self._auth) > self.AUTH_SESSIONS_MAX:
            var, srv = self._auth.popitem(last=False)
            retire(var, srv)

    def _authenticate(self, req: bytes, peer, sender) -> bytes:
        phase, variable, adata = pkt.parse_auth_request(req)
        variable = variable or b""
        now = time.monotonic()
        with self._auth_lock:
            self._auth_evict_locked(now)
            a = self._auth.get(variable)
            if a is not None:
                self._auth.move_to_end(variable)
                self._auth_used[variable] = now
        if a is None:
            try:
                rdata = self.storage.read(variable, 0)
            except ERR_NOT_FOUND:
                raise ERR_NO_AUTHENTICATION_DATA from None
            rauth = pkt.parse(rdata).auth
            if rauth is None:
                raise ERR_NO_AUTHENTICATION_DATA
            # Pre-sign our collective-signature share now; it is only
            # released when all auth phases succeed
            # (reference: server.go:425-434).
            share = self.crypt.collective.sign(self.crypt.signer, variable)
            proof = pkt.serialize_signature(share)
            a = authmod.AuthServer(rauth, proof)
            # Two racing first requests may both construct; exactly one
            # instance wins so per-session DH state never splits across
            # copies.
            with self._auth_lock:
                a = self._auth.setdefault(variable, a)
                self._auth.move_to_end(variable)
                self._auth_used[variable] = now
                # An evicted variable's brute-force penalty carries over.
                carried = self._auth_attempts.pop(variable, 0)
                if carried > a.attempts:
                    a.attempts = carried
        # Unlike the reference (server.go:441-447, which deletes the
        # AuthServer on done *and* on error), the AuthServer stays in
        # the map while warm: the anti-brute-force counter must span
        # client sessions or repeated wrong-password runs would each
        # start from attempts=0, and a concurrent client mid-handshake
        # must not lose its per-session DH state.  Per-session state is
        # LRU-bounded inside AuthServer; the map itself is bounded by
        # ``_auth_evict_locked`` with counters durable across eviction.
        try:
            res, done = a.make_response(
                phase, adata or b"", session=(peer or sender).id
            )
        except ERR_TOO_MANY_ATTEMPTS:
            log.warning(
                "server [%s]: auth: too many attempts from %s",
                self.self_node.name,
                getattr(peer or sender, "name", "?"),
            )
            raise
        finally:
            # ``a`` was used outside the lock; a concurrent eviction may
            # have retired it mid-handshake, in which case any attempt
            # increments made here would vanish (ADVICE r4 #1).  Fold
            # them back into whatever now owns the variable's counter.
            self._auth_fold_attempts(variable, a)
        if done:
            # Successful login clears the penalty — on the handler's
            # instance AND on whatever the map holds now (they can
            # differ after a concurrent eviction + re-create).
            a.reset_attempts()
            with self._auth_lock:
                cur = self._auth.get(variable)
                if cur is not None:
                    cur.reset_attempts()
                self._auth_attempts.pop(variable, None)
        return res

    def _auth_fold_attempts(self, variable: bytes, a) -> None:
        """Carry ``a``'s brute-force counter forward if ``a`` is no
        longer the map's instance for ``variable`` (evicted or replaced
        while an in-flight handler held it outside ``_auth_lock``)."""
        with self._auth_lock:
            cur = self._auth.get(variable)
            if cur is a:
                return
            if cur is not None:
                cur.attempts = max(cur.attempts, a.attempts)
            else:
                self._spill_attempts_locked(variable, a.attempts)

    # -- enrollment (reference: server.go:450-514) ------------------------

    def _register(self, req: bytes, peer, sender) -> bytes | None:
        p = pkt.parse(req)
        variable, value, t, sig, ss = p.variable or b"", p.value, p.t, p.sig, p.ss
        if sig is None or ss is None:
            raise ERR_MALFORMED_REQUEST
        if variable.startswith(HIDDEN_PREFIX):
            raise ERR_PERMISSION_DENIED
        if self._shard_check(variable) == "dual":
            self._wrong_shard(variable, stale=True)

        issuer = sigmod.issuer(sig, self.crypt.keyring)
        tbs = pkt.tbs(req)
        sigmod.verify_with_certificate(tbs, sig, issuer)

        # The proof: a collective signature over the uid variable —
        # auth-proof shaped, so the verify memo is bypassed.
        self.crypt.collective.verify(
            variable,
            ss,
            qm.choose_quorum_for(self.qs, variable, qm.AUTH),
            self.crypt.keyring,
            use_cache=False,
        )

        ret = None
        certs = certmod.parse(value or b"")
        if certs:
            c = certs[0]  # take the first one only
            if c.uid.encode() != variable:
                raise ERR_INVALID_USER_ID
            certmod.sign_certificate(c, self.crypt.signer.key)
            ret = c.serialize()

        # Persist to settle the auth-setup process, inheriting any
        # stored auth params (reference: server.go:497-513).
        rauth = None
        try:
            rdata = self.storage.read(variable, 0)
            rauth = pkt.parse(rdata).auth
        except ERR_NOT_FOUND:
            pass
        stored = pkt.serialize(variable, value, t, sig, ss, rauth)
        self._persist(variable, t, stored)
        return ret

    # -- distributed crypto (reference: server.go:516-541) ----------------

    def _distribute(self, req: bytes, peer, sender) -> bytes | None:
        p = pkt.parse(req)
        self.storage.write(
            HIDDEN_PREFIX + (p.variable or b""), 0, p.value or b""
        )
        return None

    def _dist_sign(self, req: bytes, peer, sender) -> bytes | None:
        p = pkt.parse(req)
        params = self.storage.read(HIDDEN_PREFIX + (p.variable or b""), 0)
        return self.threshold.sign(
            params, p.value, (peer or sender).id, self.self_node.id
        )

    # -- revocation (reference: server.go:543-560) ------------------------

    def _revoke(self, req: bytes, peer, sender) -> bytes | None:
        nodes = certmod.parse(req)
        for n in nodes:
            if peer is not None and n.id == peer.id:
                self.self_node.revoke(n)
                vcache.invalidate_signer(n.id)
        return None

    def _notify(self, req: bytes, peer, sender) -> bytes | None:
        return None  # no-op, as in the reference

    # -- anti-entropy (no reference analog; bftkv_tpu/sync) ---------------

    #: Bounds on one SYNC_PULL response — record count AND bytes (the
    #: native backend stores multi-MB values, so a count cap alone
    #: still allowed multi-GB replies).  A puller missing more simply
    #: re-pulls next round.
    SYNC_PULL_MAX = 8192
    SYNC_PULL_MAX_BYTES = 32 << 20

    def _require_sync_peer(self, peer) -> None:
        """Sync serves keyring-known peers only.

        Defense in depth, NOT the confidentiality boundary: open Join
        enrollment registers first-contact certificates (the web-of-
        trust model), so keyring membership is attacker-satisfiable.
        Confidentiality comes from the plane's content rule instead —
        TPA-protected records never enter digests or pulls at all
        (sync/digest.py ``latest_completed``); everything served here
        is what an anonymous quorum READ would serve anyway."""
        if peer is None:
            raise ERR_PERMISSION_DENIED

    def _sync_digest(self, req: bytes, peer, sender) -> bytes:
        """Serve the keyspace digest tree (bucket → rolling hash over
        completed records)."""
        self._require_sync_peer(peer)
        return self._sync_tree().serialize()

    def _sync_pull(self, req: bytes, peer, sender) -> bytes:
        """Stream the latest completed record of every variable in the
        requested buckets.  The puller re-runs full admission on each —
        nothing served here carries authority."""
        from bftkv_tpu.sync.digest import latest_completed

        self._require_sync_peer(peer)
        tree = self._sync_tree()
        records: list[bytes] = []
        total = 0
        for b in pkt.parse_bucket_ids(req):
            for variable in tree.bucket_variables(b):
                if (
                    len(records) >= self.SYNC_PULL_MAX
                    or total >= self.SYNC_PULL_MAX_BYTES
                ):
                    break
                rec = latest_completed(self.storage, variable)
                if rec is None:
                    continue
                raw = rec[1]
                if len(raw) > self.SYNC_PULL_MAX_BYTES:
                    # An oversized record would blow the puller's reply
                    # cap and be discarded wholesale — re-shipping it
                    # every round would be a convergence livelock, so
                    # it simply never syncs (read-repair still covers
                    # it, like everything did in the reference).
                    metrics.incr("server.sync_pull.oversized")
                    continue
                records.append(raw)
                total += len(raw)
        metrics.incr("server.sync_pull.records", len(records))
        return pkt.serialize_list(records)

    # -- batch pipeline (no reference analog; see transport command doc) --

    def _batch_time(self, req: bytes, peer, sender) -> bytes:
        """B ``time`` requests in one round trip."""
        results: list[tuple[str | None, bytes]] = []
        for variable in pkt.parse_list(req):
            try:
                results.append((None, self._time(variable, peer, sender)))
            except Exception as e:
                results.append((_errstr(e), b""))
        return pkt.serialize_results(results)

    def _batch_read(self, req: bytes, peer, sender) -> bytes:
        """B ``read`` requests in one round trip.  An ok item with an
        empty payload means "no data" — the client buckets it at t=0
        exactly like an empty single-read response."""
        results: list[tuple[str | None, bytes]] = []
        for r in pkt.parse_list(req):
            try:
                p = pkt.parse(r)
                raw = self._read_item(
                    p.variable or b"", p.ss, certified_only=(p.t == 1)
                )
                results.append((None, raw or b""))
            except Exception as e:
                results.append((_errstr(e), b""))
        return pkt.serialize_results(results)

    def _batch_sign(self, req: bytes, peer, sender) -> bytes:
        """B ``sign`` requests in one round trip: writer-signature
        verification and share issuance each run as ONE device batch;
        the per-variable checks run sequentially in item order with
        persist-as-you-go, so intra-batch conflicts hit exactly the
        single-``sign`` equivocation path."""
        with metrics.timer("server.batch_sign.handler"):
            return self._batch_sign_inner(req, peer, sender)

    def _batch_sign_inner(self, req: bytes, peer, sender) -> bytes:
        from bftkv_tpu.ops import dispatch

        reqs = pkt.parse_list(req)
        n = len(reqs)
        results: list[tuple[str | None, bytes] | None] = [None] * n
        parsed: list[tuple | None] = [None] * n  # (p, issuer, tbs)
        vitems: list = []
        vidx: list[int] = []
        vmeta: list[tuple] = []  # (issuer, tbs, sig_bytes) per vitem

        # Embedded certificates are FRAME-level: any item's embedded
        # cert resolves signers of every item in the batch, and each
        # distinct cert byte string parses exactly once.  (The client
        # batch pipeline embeds its cert only on the first item; the
        # profile showed per-item cert parsing was ~50% of the whole
        # handler's Python time at batch 1024.)  Mirrors the response
        # side's first-share-only embedding (ADVICE r3 low 4).
        packets: list = [None] * n
        frame_embedded: dict[int, object] = {}
        seen_cert_bytes: set[bytes] = set()
        for i, r in enumerate(reqs):
            try:
                p = pkt.parse(r)
                sig = p.sig
                # Harvest embedded certs BEFORE the per-item policy
                # checks: the cert-carrying item may itself be rejected
                # (hidden prefix, malformed), and the client embeds the
                # writer cert on the first item only — its rejection
                # must not strip signer resolution from the whole frame.
                if sig is not None and sig.cert:
                    if sig.cert not in seen_cert_bytes:
                        seen_cert_bytes.add(sig.cert)
                        for c in certmod.parse(sig.cert):
                            frame_embedded.setdefault(c.id, c)
                if sig is None:
                    raise ERR_MALFORMED_REQUEST
                if (p.variable or b"").startswith(HIDDEN_PREFIX):
                    raise ERR_PERMISSION_DENIED
                if self._shard_check(
                    p.variable or b""
                ) == "dual" and not self._dual_write_ok(
                    p.variable or b"", p.t, p.value
                ):
                    self._wrong_shard(p.variable or b"", stale=True)
                packets[i] = p
            except Exception as e:
                results[i] = (_errstr(e), b"")
        rich_cache: dict[int, object] = {}  # presented-cert views, per frame
        for i, r in enumerate(reqs):
            p = packets[i]
            if p is None:
                continue
            try:
                issuer = sig_bytes = None
                for sid, sb in sigmod.parse_entries(p.sig.data):
                    c = self.crypt.keyring.get(sid)
                    fe = frame_embedded.get(sid)
                    if c is None:
                        c = fe
                    elif fe is not None:
                        # Presented cert may carry a richer quorum
                        # certificate; transient view (see _present).
                        c = rich_cache.get(sid)
                        if c is None:
                            rich_cache[sid] = c = self._present(fe)
                    if c is not None:
                        issuer, sig_bytes = c, sb
                        break
                if issuer is None:
                    raise ERR_CERTIFICATE_NOT_FOUND
                if sig_bytes is None:
                    raise ERR_INVALID_SIGNATURE
                tbs = pkt.tbs(r)
                parsed[i] = (p, issuer, r)
                # Verify-memo prefilter: an exact-triple hit skips the
                # device batch (a miss verifies below and memoizes).
                if vcache.enabled() and vcache.get(issuer, tbs, sig_bytes):
                    continue
                vitems.append((tbs, sig_bytes, issuer.public_key))
                vidx.append(i)
                vmeta.append((issuer, tbs, sig_bytes))
            except Exception as e:
                results[i] = (_errstr(e), b"")

        # One device batch for every writer signature in the request.
        if vitems:
            d = dispatch.get()
            with trace.span(
                "server.verify_batch",
                attrs={"batch_size": len(vitems), "kind": "writer_sig"},
            ):
                ok = (
                    d.verify(vitems)
                    if d is not None
                    else self.crypt.collective.verifier.verify_batch(vitems)
                )
            for j, i in enumerate(vidx):
                if not ok[j]:
                    results[i] = (_errstr(ERR_INVALID_SIGNATURE), b"")
                    parsed[i] = None
                elif vcache.enabled():
                    issuer_j, tbs_j, sig_j = vmeta[j]
                    vcache.put(issuer_j, tbs_j, sig_j)

        # Quorum certificate, cached per issuer within the batch
        # (reference: server.go:211-214).
        qcert_ok: dict[int, bool] = {}
        for i in range(n):
            if parsed[i] is None:
                continue
            _p, issuer, _r = parsed[i]
            good = qcert_ok.get(issuer.id)
            if good is None:
                try:
                    self._check_quorum_certificate(issuer)
                    good = True
                except Exception:
                    good = False
                qcert_ok[issuer.id] = good
            if not good:
                results[i] = (_errstr(ERR_INVALID_QUORUM_CERTIFICATE), b"")
                parsed[i] = None

        # Per-variable checks + persist-without-ss, sequentially: each
        # item's check sees the previous item's persisted record.
        tbss_list: list[bytes] = []
        tbss_idx: list[int] = []
        for i in range(n):
            if parsed[i] is None:
                continue
            p, issuer, r = parsed[i]
            variable, val, t, sig, ss = (
                p.variable or b"",
                p.value,
                p.t,
                p.sig,
                p.ss,
            )
            try:
                proof = self._sign_storage_checks(variable, val, t, sig, ss)
            except Exception as e:
                results[i] = (_errstr(e), b"")
                continue
            # Keep stored records self-contained: a mid-join writer's
            # cert rode the frame's carrier item only, but later
            # overwrites resolve prev_issuer from THIS record alone —
            # restore the embedded cert the single-item path would
            # have persisted.  Keyring-resolvable issuers stay lean.
            if not sig.cert and self.crypt.keyring.get(issuer.id) is None:
                sig.cert = issuer.serialize()
            stored = pkt.serialize(variable, val, t, sig, None, proof)
            self._persist(variable, t, stored)
            tbss_list.append(pkt.tbss(r))
            tbss_idx.append(i)

        # One device batch for every collective-signature share.  The
        # certificate is embedded ONCE (first share of the frame), not
        # per item: a client whose keyring lacks this server's cert
        # (mid-join) keeps single-path semantics — combine() merges the
        # embedded cert and every later share of the frame resolves —
        # without B copies of cert bloat per response (ADVICE r3 low 4).
        if tbss_list:
            shares = self.crypt.signer.issue_many(tbss_list, include_cert=False)
            cert_bytes = self.crypt.signer.cert.serialize()
            for k, (share, i) in enumerate(zip(shares, tbss_idx)):
                share.completed = False
                if k == 0:
                    share.cert = cert_bytes
                results[i] = (None, pkt.serialize_signature(share))
                metrics.incr("server.sign.ok")

        return pkt.serialize_results(
            [
                r if r is not None
                else (_errstr(ERR_MALFORMED_REQUEST), b"")
                for r in results
            ]
        )

    def _batch_write(self, req: bytes, peer, sender) -> bytes:
        """B ``write`` requests in one round trip; all collective
        signatures verify in ONE device batch."""
        with metrics.timer("server.batch_write.handler"):
            return self._batch_write_inner(req, peer, sender)

    def _batch_write_inner(self, req: bytes, peer, sender) -> bytes:
        reqs = pkt.parse_list(req)
        n = len(reqs)
        results: list[tuple[str | None, bytes] | None] = [None] * n
        parsed: list[tuple | None] = [None] * n
        jobs: list[tuple[bytes, object]] = []
        jidx: list[int] = []
        # Frame-level embedded-cert harvest, as in _batch_sign: the
        # writer cert rides the first item only, but TOFU issuer
        # resolution in _write_storage_checks needs it for EVERY item
        # of a mid-join writer's overwrite.
        frame_embedded: dict[int, object] = {}
        seen_cert_bytes: set[bytes] = set()
        for i, r in enumerate(reqs):
            try:
                p = pkt.parse(r)
                variable, sig, ss = p.variable or b"", p.sig, p.ss
                if sig is not None and sig.cert:
                    if sig.cert not in seen_cert_bytes:
                        seen_cert_bytes.add(sig.cert)
                        for c in certmod.parse(sig.cert):
                            frame_embedded.setdefault(c.id, c)
                if sig is None or ss is None:
                    raise ERR_MALFORMED_REQUEST
                if variable.startswith(HIDDEN_PREFIX):
                    raise ERR_PERMISSION_DENIED
                if self._shard_check(variable) == "dual" and not (
                    self._dual_write_ok(variable, p.t, p.value)
                ):
                    self._wrong_shard(variable, stale=True)
                parsed[i] = (p, r)
                jobs.append((pkt.tbss(r), ss))
                jidx.append(i)
            except Exception as e:
                results[i] = (_errstr(e), b"")

        if jobs:
            # Every surviving item passed _shard_check, so they all
            # share this replica's shard — one keyed AUTH quorum
            # verifies the whole frame.
            qa = qm.choose_quorum_for(
                self.qs, parsed[jidx[0]][0].variable or b"", qm.AUTH
            )
            with metrics.timer("server.batch_write.verify"), trace.span(
                "server.verify_batch",
                attrs={"batch_size": len(jobs), "kind": "collective"},
            ):
                verrs = self.crypt.collective.verify_many(
                    jobs, qa, self.crypt.keyring
                )
            for j, i in enumerate(jidx):
                if verrs[j] is not None:
                    results[i] = (_errstr(verrs[j]), b"")
                    parsed[i] = None

        persists: list[tuple[bytes, int, bytes]] = []
        ok_idx: list[int] = []
        seen_vars: set[bytes] = set()
        for i in range(n):
            if parsed[i] is None:
                continue
            p, r = parsed[i]
            variable, val, t, sig, ss = (
                p.variable or b"",
                p.value,
                p.t,
                p.sig,
                p.ss,
            )
            if variable in seen_vars and persists:
                # A frame naming one variable twice: the second item's
                # admission gates (monotonicity, equivocation) must see
                # the first item's stored state — flush the deferred
                # batch before checking it.
                self._persist_many(persists)
                persists = []
            seen_vars.add(variable)
            try:
                out = self._write_storage_checks(
                    variable, val, t, sig, ss, r, frame_embedded
                )
            except Exception as e:
                results[i] = (_errstr(e), b"")
                continue
            if out is not None:  # None = idempotent no-op (see checks)
                persists.append((variable, t, out))
            ok_idx.append(i)
        # One durability barrier for the whole admitted frame — the
        # group-commit seam the gateway write coalescer feeds.
        self._persist_many(persists)
        for i in ok_idx:
            metrics.incr("server.write.ok")
            results[i] = (None, b"")

        return pkt.serialize_results(
            [
                r if r is not None
                else (_errstr(ERR_MALFORMED_REQUEST), b"")
                for r in results
            ]
        )

    _handlers = {
        tp.JOIN: "_join",
        tp.LEAVE: "_leave",
        tp.TIME: "_time",
        tp.READ: "_read",
        tp.WRITE: "_write",
        tp.SIGN: "_sign",
        tp.AUTH: "_authenticate",
        tp.SETAUTH: "_set_auth",
        tp.DISTRIBUTE: "_distribute",
        tp.DISTSIGN: "_dist_sign",
        tp.REGISTER: "_register",
        tp.REVOKE: "_revoke",
        tp.NOTIFY: "_notify",
        tp.BATCH_TIME: "_batch_time",
        tp.BATCH_SIGN: "_batch_sign",
        tp.BATCH_WRITE: "_batch_write",
        tp.BATCH_READ: "_batch_read",
        tp.SYNC_DIGEST: "_sync_digest",
        tp.SYNC_PULL: "_sync_pull",
        tp.WRITE_SIGN: "_write_sign",
    }


def _errstr(e) -> str:
    """Wire form of a per-item batch error — same interned-message
    convention as the x-error header (accepts classes and instances)."""
    m = getattr(e, "message", None)
    return m if isinstance(m, str) else "internal error"


def _listen_addr(addr: str) -> str:
    """Certificate addresses look like ``http://host:port`` or
    ``loop://name``; the transport start wants the listen side
    (reference: server.go:49-53 keeps only the port)."""
    return addr.split("://", 1)[-1]
